"""sync-hazard: implicit host<->device syncs in the hot-path modules.

On a tunneled TPU a device->host readback costs ~100-300 ms of pure RTT
(BASELINE.md), so the engine's whole perf story depends on syncs happening
only at a handful of documented choke points (the final result fetch, the
first-sight cardinality sync, the codec canary). A sync is easy to add by
accident: ``bool()``/``int()``/``float()`` on a jax array, ``.item()``,
``np.asarray`` over a device value, iterating a device array, or an ``if``
over one — none of them LOOK like transfers.

This checker runs a per-function, dataflow-local taint pass over the hot
modules (``exec/``, ``parallel/``):

- taint sources: calls through ``jnp.*`` / ``jax.lax.*`` / ``jax.nn.*`` /
  ``jax.device_put`` / ``jax.jit(...)``'s result, and calls of names locally
  bound to ``self._jitted(...)`` or ``jax.jit(...)`` (the executor idiom:
  ``fn = self._jitted(...); out = fn(...)``). Attribute loads and
  subscripts of tainted values are tainted; ``jax.device_get`` output is
  host data and UNTAINTS its targets.
- sync sinks on tainted values: ``bool/int/float/len/np.asarray/np.array``,
  ``.item()``/``.tolist()``, ``for``-iteration, truth tests (``if``/
  ``while``/``assert``/conditional expressions). Calls to ``.num_live()``
  and ``jax.device_get``/``.block_until_ready()`` are sync sites
  unconditionally — they exist to sync.

Findings are errors unless the enclosing function is a documented choke
point in ``CHOKE_POINTS`` below (each entry carries its rationale; the
whitelist is rendered in docs/static_analysis.md) or carries a
``# lint: allow(sync-hazard)`` suppression. Whitelist entries that match no
function are reported as warnings so the list cannot go stale.
"""
from __future__ import annotations

import ast
from typing import Iterable

from igloo_tpu.lint import Checker, Finding, LintModule, dotted

RULE = "sync-hazard"

# modules (repo-relative prefixes) where implicit syncs are hazards
HOT_PREFIXES = ("igloo_tpu/exec/", "igloo_tpu/parallel/")

# (repo-relative path, function qualname) -> rationale. These are the
# engine's DOCUMENTED sync choke points: each either is the single
# result-fetch round trip a query must pay, or trades one scalar readback
# for a compile/shape decision that cannot be made on device.
CHOKE_POINTS = {
    ("igloo_tpu/exec/batch.py", "DeviceBatch.num_live"):
        "THE count-sync primitive: one int readback, every caller below "
        "budgets it explicitly.",
    ("igloo_tpu/exec/batch.py", "to_arrow"):
        "the result fetch: one device_get for every buffer of the final "
        "batch (one round trip instead of one per column).",
    ("igloo_tpu/exec/batch.py", "arrow_from_host"):
        "output-boundary fallback only: callers that prefetched lanes "
        "without carrier args pay one 0-d device_get per carrier column "
        "to host-widen; the executor fetch sites ship host_cargs in their "
        "single device_get and never hit it.",
    ("igloo_tpu/exec/executor.py", "Executor.execute"):
        "deferred speculative-flag fetch: flags accumulated across the "
        "query come back in one readback at the end.",
    ("igloo_tpu/exec/executor.py", "Executor._fused_run"):
        "the fused path's single fetch: result + flags + cardinality "
        "stats in one device_get (the whole point of fusion).",
    ("igloo_tpu/exec/executor.py", "Executor._staged_to_arrow"):
        "final fetch of the staged path (speculative compact + one "
        "device_get; overflow pays an exact refetch).",
    ("igloo_tpu/exec/executor.py", "Executor._exec"):
        "EXPLAIN ANALYZE detail mode only: per-operator actual row "
        "counts are the product being sold, one num_live sync each.",
    ("igloo_tpu/exec/executor.py", "Executor._exec_join"):
        "non-speculative joins must size the expand capacity: one "
        "candidate-total readback (int(p.total)) per join.",
    ("igloo_tpu/exec/executor.py", "Executor._adaptive_input"):
        "first sight of a subtree costs one live-count sync to seed the "
        "persistent capacity hint; later runs are sync-free.",
    ("igloo_tpu/exec/executor.py", "Executor._maybe_shrink"):
        "capacity shrink between stages: one live-count sync, skipped "
        "entirely under _SYNC_FREE_CAPACITY or a known count.",
    ("igloo_tpu/exec/codec.py", "_scaled_decimal_ok_locked"):
        "one-time per-process canary: replays the scaled-decimal divide "
        "on device before trusting it (round-5 advisor item; the locked "
        "slow path of _scaled_decimal_ok — the lock-free fast read never "
        "syncs).",
    ("igloo_tpu/parallel/executor.py", "ShardedExecutor._observed_live"):
        "mesh broadcast decision on OBSERVED rows, not padded capacity: "
        "first sight of a subtree costs one live-count sync to seed the "
        "persistent hint (same contract as Executor._adaptive_input); "
        "later runs are sync-free.",
    ("igloo_tpu/exec/autotune.py", "_bench_candidate.timed"):
        "the autotuner's candidate benchmark harness: block_until_ready IS "
        "the measurement (sweep mode / offline script only, never on a "
        "query's hot path).",
    ("igloo_tpu/exec/dispatch.py", "exchange_scatter"):
        "the exchange partition is a HOST operation (Arrow table in, bucket "
        "slices out): the kernel's bucket lane must come back to drive "
        "table.take — one readback replacing the numpy hash+argsort it "
        "displaced.",
}

_SOURCE_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "jax.numpy.")
_SOURCE_EXACT = {"jax.device_put"}
# metadata predicates/queries that return HOST values despite the jnp prefix
_HOST_META = {"issubdtype", "iinfo", "finfo", "dtype", "result_type",
              "promote_types", "shape", "ndim", "isdtype"}
_JIT_MAKERS = {"jax.jit"}          # plus any `self._jitted` / `cls._jitted`
_UNTAINT_CALLS = {"jax.device_get"}
_CAST_SINKS = {"bool", "int", "float", "len"}
_NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_METHOD_SINKS = {"item", "tolist"}
_SYNC_CALLS = {"num_live", "block_until_ready"}  # sync by definition


def _is_source_call(call: ast.Call) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    if name.split(".")[-1] in _HOST_META:
        return False
    return name in _SOURCE_EXACT or \
        any(name.startswith(p) for p in _SOURCE_PREFIXES)


class _FunctionPass(ast.NodeVisitor):
    """Taint pass over ONE function body (nested defs get their own pass)."""

    def __init__(self, checker: "SyncHazardChecker", mod: LintModule,
                 qualname: str, fn: ast.AST):
        self.checker = checker
        self.mod = mod
        self.qualname = qualname
        self.fn = fn
        self.tainted: set[str] = set()
        self.jit_fns: set[str] = set()   # names bound to jax.jit/self._jitted

    # --- taint bookkeeping ---

    def _expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Call):
            if _is_source_call(node):
                return True
            name = dotted(node.func)
            if name is not None:
                if name in _UNTAINT_CALLS:
                    return False
                if name in self.jit_fns:
                    return True
                # immediately-invoked jit builder: self._jitted(...)(args)
            if isinstance(node.func, ast.Call):
                inner = dotted(node.func.func)
                if inner is not None and self._is_jit_maker(inner):
                    return True
            return False
        # NOTE: list/tuple displays deliberately do NOT propagate taint —
        # a host list OF device arrays is host data (len()/iteration over it
        # never touch the device)
        if isinstance(node, ast.IfExp):
            return self._expr_tainted(node.body) or \
                self._expr_tainted(node.orelse)
        if isinstance(node, ast.BinOp):
            return self._expr_tainted(node.left) or \
                self._expr_tainted(node.right)
        if isinstance(node, (ast.UnaryOp,)):
            return self._expr_tainted(node.operand)
        return False

    @staticmethod
    def _is_jit_maker(name: str) -> bool:
        return name in _JIT_MAKERS or name.endswith("._jitted")

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if tainted
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        # attribute/subscript stores don't track

    # --- findings ---

    def _report(self, node: ast.AST, what: str) -> None:
        key = (self.mod.relpath, self.qualname)
        if key in CHOKE_POINTS:
            self.checker.used_choke_points.add(key)
            return
        self.checker.out.append(Finding(
            RULE, self.mod.relpath, node.lineno,
            f"{what} in `{self.qualname}` syncs the device on the hot path; "
            "route through a documented choke point, precompute on host, or "
            "whitelist it in igloo_tpu/lint/sync_hazard.py with a rationale"))

    # --- visitors ---

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        val = node.value
        name = dotted(val.func) if isinstance(val, ast.Call) else None
        if name is not None and self._is_jit_maker(name):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.jit_fns.add(t.id)
            return
        t = self._expr_tainted(val)
        if isinstance(val, ast.Call) and name in _UNTAINT_CALLS:
            t = False
        for tgt in node.targets:
            self._bind(tgt, t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self._expr_tainted(node.value):
            self._bind(node.target, True)

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        name = dotted(node.func)
        if name is not None:
            bare = name.split(".")[-1]
            if bare in _SYNC_CALLS and isinstance(node.func, ast.Attribute):
                self._report(node, f"`.{bare}()` call")
                return
            if name in _UNTAINT_CALLS:
                self._report(node, f"`{name}` fetch")
                return
            if (name in _CAST_SINKS or name in _NP_SINKS) and node.args and \
                    self._expr_tainted(node.args[0]):
                self._report(node, f"`{name}()` over a device value")
                return
            if bare in _METHOD_SINKS and isinstance(node.func, ast.Attribute) \
                    and self._expr_tainted(node.func.value):
                self._report(node, f"`.{bare}()` over a device value")

    def visit_For(self, node: ast.For) -> None:
        if self._expr_tainted(node.iter):
            self._report(node, "iteration over a device value")
        self._bind(node.target, False)
        self.generic_visit(node)

    def _check_truth(self, test: ast.AST, node: ast.AST) -> None:
        exprs = test.values if isinstance(test, ast.BoolOp) else [test]
        for e in exprs:
            if isinstance(e, (ast.Compare,)):
                continue  # comparisons produce device bools but don't sync
            if self._expr_tainted(e):
                self._report(node, "truth test over a device value")
                return

    def visit_If(self, node: ast.If) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    # nested functions get their own pass (fresh taint scope)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.fn:
            self.checker._run_function(
                self.mod, f"{self.qualname}.{node.name}", node)
        else:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return  # traced lambdas: no host sinks possible in an expression body


class SyncHazardChecker(Checker):
    name = RULE

    def __init__(self):
        self.out: list[Finding] = []
        self.used_choke_points: set = set()
        self.warnings: list[str] = []

    def check(self, mod: LintModule) -> Iterable[Finding]:
        if not mod.relpath.startswith(HOT_PREFIXES):
            return ()
        self.out = []
        for qual, fn in _top_level_functions(mod.tree):
            self._run_function(mod, qual, fn)
        return self.out

    def _run_function(self, mod: LintModule, qualname: str,
                      fn: ast.AST) -> None:
        p = _FunctionPass(self, mod, qualname, fn)
        for stmt in fn.body:
            p.visit(stmt)

    def finalize(self, modules: list) -> Iterable[Finding]:
        linted = {m.relpath for m in modules}
        for (path, qual), _why in sorted(CHOKE_POINTS.items()):
            if path in linted and (path, qual) not in self.used_choke_points:
                self.warnings.append(
                    f"sync-hazard: whitelist entry ({path}, {qual}) matched "
                    "no sync site — stale entry?")
        return ()


def _top_level_functions(tree: ast.Module):
    """(qualname, node) for every module-level def and each method of every
    class (nested defs are handled inside their parent's pass)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub
