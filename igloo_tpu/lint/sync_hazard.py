"""sync-hazard: implicit host<->device syncs in the hot-path modules.

On a tunneled TPU a device->host readback costs ~100-300 ms of pure RTT
(BASELINE.md), so the engine's whole perf story depends on syncs happening
only at a handful of documented choke points (the final result fetch, the
codec canary, the join expand sizing). A sync is easy to add by accident:
``bool()``/``int()``/``float()`` on a jax array, ``.item()``,
``np.asarray`` over a device value, iterating a device array, or an ``if``
over one — none of them LOOK like transfers.

This checker is a ``TwoPassChecker`` running a per-function taint pass
over the hot modules (``exec/``, ``parallel/``) with ONE level of
interprocedural summaries: the collect pass records, per module, which
top-level functions RETURN a tainted (device) value; the judge pass
re-runs the taint walk with that table, so a helper returning a device
array taints its callers' ``int()``/``bool()``/``.item()`` sinks — the
cross-function pattern the old per-function walk was blind to. Summaries
resolve module-locally (bare ``f()`` and ``self.meth()`` calls), which is
where the engine's helper-extraction idiom actually lives.

- taint sources: calls through ``jnp.*`` / ``jax.lax.*`` / ``jax.nn.*`` /
  ``jax.device_put``, results of names locally bound to ``self._jitted(...)``
  or ``jax.jit(...)`` (``fn = self._jitted(...); out = fn(...)``), calls of
  nested defs that RETURN a jit-built function (the executor's
  ``probe_fn(pp)(...)`` idiom), and module-local calls of functions whose
  summary says they return device values. Attribute loads and subscripts
  of tainted values stay tainted — except host-metadata attributes
  (``.shape``/``.dtype``/``.columns``/``.schema``/...): pytree structure
  and Python containers OF device arrays live on host, so ``len(out.columns)``
  never syncs. ``jax.device_get`` output is host data and UNTAINTS.
- sync sinks on tainted values: ``bool/int/float/len/np.asarray/np.array``,
  ``.item()``/``.tolist()``, ``for``-iteration, truth tests. Calls of
  ``jax.device_get`` / ``.block_until_ready()`` are sync sites
  unconditionally — they exist to sync. A bare sync METHOD whose
  definition is itself a whitelisted choke point (``.num_live()`` ->
  ``DeviceBatch.num_live``) is SANCTIONED ROUTING at the call site: the
  engine's documented count-sync primitive pays the readback once, inside
  the whitelist, and callers are free to use it — remove the whitelist
  entry and every call site lights up again.

Findings are errors unless the enclosing function is a documented choke
point in ``CHOKE_POINTS`` (each entry carries its rationale; the table is
rendered in docs/static_analysis.md), the module is a ``COLD_MODULES``
entry (the autotuner's offline benchmark harness, where
``block_until_ready`` IS the measurement), or the line carries a
``# lint: allow(sync-hazard)`` suppression. Whitelist entries that match
no sync site are reported as warnings — and as ``stale-entry`` findings
under ``--stale-allows`` — so the whitelist shrinks monotonically.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from igloo_tpu.lint import Finding, LintModule, TwoPassChecker, dotted

RULE = "sync-hazard"

# modules (repo-relative prefixes) where implicit syncs are hazards
HOT_PREFIXES = ("igloo_tpu/exec/", "igloo_tpu/parallel/")

# (repo-relative path, function qualname) -> rationale. These are the
# engine's DOCUMENTED sync choke points: each either is the single
# result-fetch round trip a query must pay, or trades one scalar readback
# for a compile/shape decision that cannot be made on device. The
# interprocedural migration shrank this list from 14 to 9: functions whose
# only sync was the ``num_live()`` count primitive (`Executor._exec`,
# `_adaptive_input`, `_maybe_shrink`, `ShardedExecutor._observed_live`)
# are now covered by sanctioned routing through the `DeviceBatch.num_live`
# entry itself, and the autotuner harness moved to COLD_MODULES.
CHOKE_POINTS = {
    ("igloo_tpu/exec/batch.py", "DeviceBatch.num_live"):
        "THE count-sync primitive: one int readback; every call site "
        "routes through this entry (sanctioned routing), so dropping it "
        "re-flags them all.",
    ("igloo_tpu/exec/batch.py", "to_arrow"):
        "the result fetch: one device_get for every buffer of the final "
        "batch (one round trip instead of one per column).",
    ("igloo_tpu/exec/batch.py", "arrow_from_host"):
        "output-boundary fallback only: callers that prefetched lanes "
        "without carrier args pay one 0-d device_get per carrier column "
        "to host-widen; the executor fetch sites ship host_cargs in their "
        "single device_get and never hit it.",
    ("igloo_tpu/exec/executor.py", "Executor.execute"):
        "deferred speculative-flag fetch: flags accumulated across the "
        "query come back in one readback at the end.",
    ("igloo_tpu/exec/executor.py", "Executor._fused_run"):
        "the fused path's single fetch: result + flags + cardinality "
        "stats in one device_get (the whole point of fusion).",
    ("igloo_tpu/exec/executor.py", "Executor._staged_to_arrow"):
        "final fetch of the staged path (speculative compact + one "
        "device_get; overflow pays an exact refetch).",
    ("igloo_tpu/exec/executor.py", "Executor._exec_join"):
        "non-speculative joins must size the expand capacity: one "
        "candidate-total readback (int(p.total), now visible through the "
        "probe_fn jit-closure) per join.",
    ("igloo_tpu/exec/codec.py", "_scaled_decimal_ok_locked"):
        "one-time per-process canary: replays the scaled-decimal divide "
        "on device before trusting it (round-5 advisor item; the locked "
        "slow path of _scaled_decimal_ok — the lock-free fast read never "
        "syncs).",
    ("igloo_tpu/exec/dispatch.py", "exchange_scatter"):
        "the exchange partition is a HOST operation (Arrow table in, bucket "
        "slices out): the kernel's bucket lane must come back to drive "
        "table.take — one readback replacing the numpy hash+argsort it "
        "displaced.",
}

# repo-relative path -> rationale: hot-tree modules that are WHOLLY off the
# query hot path, where syncing is the point. Kept separate from
# CHOKE_POINTS so per-function whitelisting stays the norm.
COLD_MODULES = {
    "igloo_tpu/exec/autotune.py":
        "the autotuner's candidate benchmark harness: block_until_ready IS "
        "the measurement (sweep mode / offline script only, never on a "
        "query's hot path).",
}

_SOURCE_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "jax.numpy.")
_SOURCE_EXACT = {"jax.device_put"}
# metadata predicates/queries that return HOST values despite the jnp prefix
_HOST_META = {"issubdtype", "iinfo", "finfo", "dtype", "result_type",
              "promote_types", "shape", "ndim", "isdtype"}
# attribute loads that return HOST data even off a device value: pytree
# structure, dtypes, and Python containers OF device arrays (a DeviceBatch's
# .columns list is a host list; len()/iteration over it never sync)
_HOST_ATTRS = {"shape", "ndim", "dtype", "schema", "columns", "names",
               "capacity", "sharding", "weak_type", "size"}
_JIT_MAKERS = {"jax.jit"}          # plus any `self._jitted` / `cls._jitted`
_UNTAINT_CALLS = {"jax.device_get"}
_CAST_SINKS = {"bool", "int", "float", "len"}
_NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_METHOD_SINKS = {"item", "tolist"}
_SYNC_CALLS = {"num_live", "block_until_ready"}  # sync by definition

#: sync methods whose DEFINITION is itself a choke point: calls of these are
#: sanctioned routing (derived from the whitelist, so removing the entry
#: re-flags every call site)
_ROUTED_SYNCS = {qual.split(".")[-1]: (path, qual)
                 for (path, qual) in CHOKE_POINTS
                 if qual.split(".")[-1] in _SYNC_CALLS}


def _is_source_call(call: ast.Call) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    if name.split(".")[-1] in _HOST_META:
        return False
    return name in _SOURCE_EXACT or \
        any(name.startswith(p) for p in _SOURCE_PREFIXES)


class _ModSummary:
    """Collect-pass product: which top-level functions return device values."""

    __slots__ = ("mod", "returns")

    def __init__(self, mod: LintModule, returns: dict):
        self.mod = mod
        self.returns = returns     # qualname -> bool (returns tainted)


class _FunctionPass(ast.NodeVisitor):
    """Taint pass over ONE function body (nested defs get their own pass)."""

    def __init__(self, checker: "SyncHazardChecker", mod: LintModule,
                 qualname: str, fn: ast.AST, callee_returns: dict,
                 report: bool):
        self.checker = checker
        self.mod = mod
        self.qualname = qualname
        self.fn = fn
        self.callee_returns = callee_returns
        self.report = report
        self.tainted: set[str] = set()
        self.jit_fns: set[str] = set()   # names bound to jax.jit/self._jitted
        self.jit_ret_fns: set[str] = set()  # nested defs returning a jit fn
        self.returns_tainted = False

    # --- taint bookkeeping ---

    def _callee_tainted(self, name: str) -> bool:
        """Module-local interprocedural lookup: does `f()` / `self.m()`
        return a device value per the collect-pass summary?"""
        parts = name.split(".")
        if len(parts) == 1:
            return self.callee_returns.get(parts[0], False)
        if len(parts) == 2 and parts[0] in ("self", "cls"):
            cls = self.qualname.split(".")[0]
            return self.callee_returns.get(f"{cls}.{parts[1]}", False)
        return False

    def _expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _HOST_ATTRS:
                return False     # pytree metadata / host containers
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Call):
            if _is_source_call(node):
                return True
            name = dotted(node.func)
            if name is not None:
                if name in _UNTAINT_CALLS:
                    return False
                if name in self.jit_fns:
                    return True
                if name.split(".")[-1] in _ROUTED_SYNCS:
                    return False     # the routed count sync returns host int
                if self._callee_tainted(name):
                    return True
            # immediately-invoked jit builder: self._jitted(...)(args) or a
            # jit-returning nested def: probe_fn(pp)(args)
            if isinstance(node.func, ast.Call):
                inner = dotted(node.func.func)
                if inner is not None and (self._is_jit_maker(inner)
                                          or inner in self.jit_ret_fns):
                    return True
            return False
        # NOTE: list/tuple displays deliberately do NOT propagate taint —
        # a host list OF device arrays is host data (len()/iteration over it
        # never touch the device)
        if isinstance(node, ast.IfExp):
            return self._expr_tainted(node.body) or \
                self._expr_tainted(node.orelse)
        if isinstance(node, ast.BinOp):
            return self._expr_tainted(node.left) or \
                self._expr_tainted(node.right)
        if isinstance(node, (ast.UnaryOp,)):
            return self._expr_tainted(node.operand)
        return False

    @staticmethod
    def _is_jit_maker(name: str) -> bool:
        return name in _JIT_MAKERS or name.endswith("._jitted")

    @staticmethod
    def _returns_jit_fn(fn_node: ast.AST) -> bool:
        """Does this (nested) def return jax.jit(...) / self._jitted(...)?"""
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Return) and \
                    isinstance(sub.value, ast.Call):
                n = dotted(sub.value.func)
                if n is not None and _FunctionPass._is_jit_maker(n):
                    return True
        return False

    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if tainted
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        # attribute/subscript stores don't track

    # --- findings ---

    def _report(self, node: ast.AST, what: str) -> None:
        key = (self.mod.relpath, self.qualname)
        if key in CHOKE_POINTS:
            self.checker.used_choke_points.add(key)
            return
        if self.mod.relpath in COLD_MODULES:
            self.checker.used_cold_modules.add(self.mod.relpath)
            return
        if not self.report:
            return
        self.checker.out.append(Finding(
            RULE, self.mod.relpath, node.lineno,
            f"{what} in `{self.qualname}` syncs the device on the hot path; "
            "route through a documented choke point, precompute on host, or "
            "whitelist it in igloo_tpu/lint/sync_hazard.py with a rationale"))

    # --- visitors ---

    def visit_Return(self, node: ast.Return) -> None:
        self.generic_visit(node)
        if node.value is not None and self._expr_tainted(node.value):
            self.returns_tainted = True

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        val = node.value
        name = dotted(val.func) if isinstance(val, ast.Call) else None
        if name is not None and (self._is_jit_maker(name)
                                 or name in self.jit_ret_fns):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.jit_fns.add(t.id)
            return
        t = self._expr_tainted(val)
        if isinstance(val, ast.Call) and name in _UNTAINT_CALLS:
            t = False
        for tgt in node.targets:
            self._bind(tgt, t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self._expr_tainted(node.value):
            self._bind(node.target, True)

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        name = dotted(node.func)
        if name is not None:
            bare = name.split(".")[-1]
            if bare in _SYNC_CALLS and isinstance(node.func, ast.Attribute):
                entry = _ROUTED_SYNCS.get(bare)
                if entry is not None:
                    # sanctioned routing through the whitelisted primitive:
                    # the sync is budgeted at the definition, not per caller
                    self.checker.used_choke_points.add(entry)
                    return
                self._report(node, f"`.{bare}()` call")
                return
            if name in _UNTAINT_CALLS:
                self._report(node, f"`{name}` fetch")
                return
            if (name in _CAST_SINKS or name in _NP_SINKS) and node.args and \
                    self._expr_tainted(node.args[0]):
                self._report(node, f"`{name}()` over a device value")
                return
            if bare in _METHOD_SINKS and isinstance(node.func, ast.Attribute) \
                    and self._expr_tainted(node.func.value):
                self._report(node, f"`.{bare}()` over a device value")

    def visit_For(self, node: ast.For) -> None:
        if self._expr_tainted(node.iter):
            self._report(node, "iteration over a device value")
        self._bind(node.target, False)
        self.generic_visit(node)

    def _check_truth(self, test: ast.AST, node: ast.AST) -> None:
        exprs = test.values if isinstance(test, ast.BoolOp) else [test]
        for e in exprs:
            if isinstance(e, (ast.Compare,)):
                continue  # comparisons produce device bools but don't sync
            if self._expr_tainted(e):
                self._report(node, "truth test over a device value")
                return

    def visit_If(self, node: ast.If) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_truth(node.test, node)
        self.generic_visit(node)

    # nested functions get their own pass (fresh taint scope); nested defs
    # that RETURN a jit-built function feed the enclosing scope's
    # probe_fn(...)(args) taint instead
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.fn:
            if self._returns_jit_fn(node):
                self.jit_ret_fns.add(node.name)
            self.checker._run_function(
                self.mod, f"{self.qualname}.{node.name}", node,
                self.callee_returns, self.report)
        else:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return  # traced lambdas: no host sinks possible in an expression body


class SyncHazardChecker(TwoPassChecker):
    name = RULE

    def __init__(self):
        super().__init__()
        self.out: list[Finding] = []
        self.used_choke_points: set = set()
        self.used_cold_modules: set = set()
        self.warnings: list[str] = []
        self._stale: list[Finding] = []

    def collect(self, mod: LintModule):
        """Level-0 summary: which top-level functions return device values
        (computed WITHOUT callee info — that is the 'one level')."""
        if not mod.relpath.startswith(HOT_PREFIXES):
            return None, ()
        returns: dict = {}
        for qual, fn in _top_level_functions(mod.tree):
            p = self._run_function(mod, qual, fn, {}, report=False)
            returns[qual] = p.returns_tainted
        return _ModSummary(mod, returns), ()

    def _run_function(self, mod: LintModule, qualname: str, fn: ast.AST,
                      callee_returns: dict, report: bool) -> _FunctionPass:
        p = _FunctionPass(self, mod, qualname, fn, callee_returns, report)
        for stmt in fn.body:
            p.visit(stmt)
        return p

    def judge(self, summaries: dict) -> Iterable[Finding]:
        self.out = []
        self.warnings = []
        self._stale = []
        self.used_choke_points = set()
        self.used_cold_modules = set()
        def_lines: dict = {}
        for rel in sorted(summaries):
            sm = summaries[rel]
            if sm is None:
                continue
            for qual, fn in _top_level_functions(sm.mod.tree):
                def_lines[(rel, qual)] = fn.lineno
                self._run_function(sm.mod, qual, fn, sm.returns, report=True)
        linted = set(summaries)
        for (path, qual), _why in sorted(CHOKE_POINTS.items()):
            if path in linted and (path, qual) not in self.used_choke_points:
                self.warnings.append(
                    f"sync-hazard: whitelist entry ({path}, {qual}) matched "
                    "no sync site — stale entry?")
                self._stale.append(Finding(
                    "stale-entry", path, def_lines.get((path, qual), 1),
                    f"CHOKE_POINTS entry `{qual}` matches no sync site — "
                    "remove it from igloo_tpu/lint/sync_hazard.py"))
        for path in sorted(COLD_MODULES):
            if path in linted and path not in self.used_cold_modules:
                self.warnings.append(
                    f"sync-hazard: COLD_MODULES entry {path} suppressed "
                    "no sync site — stale entry?")
                self._stale.append(Finding(
                    "stale-entry", path, 1,
                    "COLD_MODULES entry suppresses no sync site — remove "
                    "it from igloo_tpu/lint/sync_hazard.py"))
        return self.out

    def stale_entries(self) -> list:
        """Structured whitelist staleness for ``--stale-allows`` (computed
        by the last judge pass; empty on partial runs of the hot tree only
        if the entries' paths were linted and unused)."""
        return list(self._stale)


def _top_level_functions(tree: ast.Module):
    """(qualname, node) for every module-level def and each method of every
    class (nested defs are handled inside their parent's pass)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub
