"""thread-roles / lock-order: whole-program concurrency analysis.

``lock-discipline`` verifies accesses to state a module ALREADY declared in
``_GUARDED_BY`` — it says nothing about the undeclared shared state where
every real race in this repo actually lived (the worker lazy-mesh race, the
HintStore flush race, the CounterDelta unlocked reads — each found by hand
in review). These two rules close that gap from the other side: instead of
starting from the declarations, they start from the THREADS.

**thread-roles** (``ThreadRolesChecker``): the collect pass catalogs every
thread-spawn site in the package — ``threading.Thread(target=...)`` /
``threading.Timer``, ``ThreadPoolExecutor``/``pool.submit`` callbacks,
``weakref.finalize`` finalizers, and the Arrow Flight handler entry points
(``do_action`` / ``do_get`` / ``do_put`` / ``do_exchange`` / ``list_*`` /
``get_*``) of the server modules named by ``cluster/protocol.py``'s
``ACTION_SERVERS`` table (parsed, never imported: Flight serves every RPC
on its own thread, so each handler is a role of its own) — and builds a
conservative intra-package call graph. The judge pass computes which
functions each role reaches. Pool-backed roles (executor pools, Flight
handlers) are concurrent with THEMSELVES (weight 2); a dedicated daemon
loop, a timer, or a finalizer needs a second role to race against
(weight 1). Every ``self.<attr>``-rooted / module-global **write** in a
function whose reachable role weight sums to >= 2 is flagged, unless it is:

- lexically under ``with <lock>:`` for a lock-ish name (``*lock``, ``_cv``,
  ``_cond`` — the convention every lock in this tree follows);
- covered by the module's ``_GUARDED_BY`` declaration (then
  ``lock-discipline`` owns the access check — one rule per access);
- in ``__init__``/``__new__``/module scope (not shared yet), or in a
  ``*_locked`` / documented ``caller-locked`` method; or
- suppressed with ``# lint: allow(thread-roles)`` plus a rationale.

The call graph is conservative about RESOLUTION, not about reach:
``self.meth()`` resolves within the enclosing class, a bare ``f()`` against
the enclosing function's nested defs, then module functions, then
``from igloo_tpu.x import f`` imports, and ``alias.f()`` through
intra-package module aliases. A call on an arbitrary object
(``obj.a.b()``) stays unresolved — otherwise the Flight handler role would
"reach" the whole engine through ``self.engine.execute(sql)`` and drown
the signal. Writes are tracked for ``self.``/``cls.``-rooted attribute
chains (``self.executor.last_metrics = ...`` included) and declared module
globals; mutating METHOD calls (``.append()``/``.update()``) are out of
scope — once the attr is declared in ``_GUARDED_BY``, lock-discipline's
any-receiver matching covers those too.

**lock-order** (``LockOrderChecker``): the same collect pass records the
nesting order of ``with``-acquired DECLARED locks (the ``_GUARDED_BY``
keys; lock identity is (module, name), so cross-module edges arise only
from resolved calls), closes the acquired-locks relation over the call
graph to a fixpoint, and flags every cycle in the resulting lock graph —
including self-loops, which are a re-acquisition deadlock for the
non-reentrant ``threading.Lock`` this tree uses — naming the acquisition
sites on both ends of the offending edge.

Both rules are ``TwoPassChecker``s: a partial run only shrinks the role
set and the lock graph, so it can under-report but never invent findings,
and ``--stale-allows`` already treats two-pass rules as unjudgeable on
partial runs.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from igloo_tpu.lint import Finding, LintModule, TwoPassChecker, dotted

_HANDLER_METHODS = frozenset({
    "do_action", "do_get", "do_put", "do_exchange",
    "get_flight_info", "get_schema", "list_flights", "list_actions"})

# lock-ish with-item names: every lock in the tree ends in "lock" or is a
# Condition named _cv/_cond (see the _GUARDED_BY declarations package-wide)
_LOCKISH = re.compile(r"(?:lock$|^_cv$|^_cond$)")

_EXEMPT_METHODS = {"__init__", "__new__"}

#: role weights: a pool-backed role runs concurrently with ITSELF, so one
#: such role alone makes its reachable unguarded writes racy; a dedicated
#: daemon loop / timer / finalizer needs a second role to race against.
_WEIGHTS = {"thread": 1, "timer": 1, "finalize": 1,
            "submit": 2, "handler": 2}


def _lockish(name: Optional[str]) -> Optional[str]:
    """The lock name of a with-item dotted chain, else None."""
    if name is None:
        return None
    last = name.split(".")[-1]
    return last if _LOCKISH.search(last) else None


def _load_literal_dict(tree: ast.Module, varname: str) -> Optional[dict]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == varname:
                    try:
                        v = ast.literal_eval(node.value)
                    except ValueError:
                        return None
                    return v if isinstance(v, dict) else None
    return None


class _FnInfo:
    """Facts about one function NODE (nested defs get their own node, so a
    finalizer closure's writes are not smeared onto its enclosing method)."""

    __slots__ = ("qual", "cls", "exempt", "calls", "writes", "acquires",
                 "lock_events", "line")

    def __init__(self, qual: str, cls: Optional[str], exempt: bool,
                 line: int):
        self.qual = qual
        self.cls = cls               # enclosing class name, if a method
        self.exempt = exempt         # __init__/_locked/caller-locked
        self.calls: set = set()      # ("bare"|"self"|"dotted", ...) refs
        self.writes: list = []       # (attr_or_global, line, guarded)
        self.acquires: dict = {}     # lock name -> first acquisition line
        self.lock_events: list = []  # (held, "acquire"|"call", payload, line)
        self.line = line


class _Summary:
    """One module's contribution to the whole-program judgment."""

    def __init__(self, mod: LintModule):
        self.relpath = mod.relpath
        self.functions: dict = {}       # qual -> _FnInfo
        self.class_methods: dict = {}   # class name -> set of method names
        self.module_fns: set = set()    # module-level def names
        self.spawns: list = []          # (kind, target_ref, line, owner_qual)
        self.guarded_names: set = set()
        self.declared_locks: set = set()
        self.imports: dict = {}         # local name -> import record
        self.action_servers: Optional[dict] = None
        guards = _load_literal_dict(mod.tree, "_GUARDED_BY")
        if guards:
            self.declared_locks = {str(k) for k in guards}
            for names in guards.values():
                self.guarded_names.update(
                    str(n) for n in (names if isinstance(names, (list, tuple))
                                     else (names,)))
        servers = _load_literal_dict(mod.tree, "ACTION_SERVERS")
        if servers:
            self.action_servers = {str(k): str(v) for k, v in servers.items()}


class _Collector(ast.NodeVisitor):
    """One walk over a module: function nodes, call refs, self-/global
    writes with their lock context, spawn sites, lock-nesting events."""

    def __init__(self, mod: LintModule, summary: _Summary):
        self.mod = mod
        self.s = summary
        self.cls_stack: list = []
        self.fn_stack: list = []
        self.held: list = []            # lexical lock-name stack
        self.globals_map: dict = {}     # fn qual -> names from `global` stmts
        self.module_globals: set = set()
        for node in mod.tree.body:
            for t in getattr(node, "targets", []):
                if isinstance(t, ast.Name):
                    self.module_globals.add(t.id)
            if isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                self.module_globals.add(node.target.id)

    # --- scaffolding ---

    def _qual(self, name: str) -> str:
        if self.fn_stack:
            return f"{self.fn_stack[-1].qual}.{name}"
        if self.cls_stack:
            return f"{self.cls_stack[-1]}.{name}"
        return name

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.asname and (a.name == "igloo_tpu"
                             or a.name.startswith("igloo_tpu.")):
                self.s.imports[a.asname] = \
                    ("modpath", a.name.replace(".", "/") + ".py")

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:   # relative import: resolve against this file's pkg
            pkg_parts = self.s.relpath.split("/")[:-1]
            keep = len(pkg_parts) - (node.level - 1)
            if keep < 1:
                return
            base = "/".join(pkg_parts[:keep]
                            + ([node.module.replace(".", "/")]
                               if node.module else []))
        else:
            if not (base == "igloo_tpu" or base.startswith("igloo_tpu.")):
                return
            base = base.replace(".", "/")
        for a in node.names:
            # `from igloo_tpu.cluster import rpc` binds a module OR a name
            # from cluster/__init__ — record both candidates; the judge
            # resolves against what actually exists in the summaries
            self.s.imports[a.asname or a.name] = ("maybe", base, a.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.s.class_methods.setdefault(node.name, set())
        self.generic_visit(node)
        self.cls_stack.pop()

    def _fn_exempt(self, node) -> bool:
        if node.name in _EXEMPT_METHODS or node.name.endswith("_locked"):
            return True
        doc = ast.get_docstring(node)
        return bool(doc and "caller-locked" in doc.lower())

    def _visit_fn(self, node) -> None:
        qual = self._qual(node.name)
        if self.fn_stack:
            cls = self.fn_stack[-1].cls    # closure: `self` still in scope
        else:
            cls = self.cls_stack[-1] if self.cls_stack else None
        exempt = self._fn_exempt(node) or \
            (bool(self.fn_stack) and self.fn_stack[-1].exempt)
        info = _FnInfo(qual, cls, exempt, node.lineno)
        self.s.functions[qual] = info
        if not self.fn_stack:
            if self.cls_stack:
                self.s.class_methods[self.cls_stack[-1]].add(node.name)
            else:
                self.s.module_fns.add(node.name)
        self.fn_stack.append(info)
        saved, self.held = self.held, []   # closures escape the lock scope
        self.generic_visit(node)
        self.held = saved
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # --- lock context ---

    def visit_With(self, node: ast.With) -> None:
        got = []
        for item in node.items:
            lk = _lockish(dotted(item.context_expr))
            if lk is not None:
                got.append(lk)
                if self.fn_stack:
                    fn = self.fn_stack[-1]
                    fn.acquires.setdefault(lk, node.lineno)
                    fn.lock_events.append(
                        (tuple(self.held), "acquire", lk, node.lineno))
        self.held.extend(got)
        self.generic_visit(node)
        for _ in got:
            self.held.pop()

    # --- call refs and spawn sites ---

    @staticmethod
    def _callee_ref(func: ast.AST):
        name = dotted(func)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            return ("bare", parts[0])
        if len(parts) == 2 and parts[0] in ("self", "cls"):
            return ("self", parts[1])
        if len(parts) == 2:
            return ("dotted", parts[0], parts[1])
        return None     # obj.attr.meth(...): deliberately unresolved

    def visit_Call(self, node: ast.Call) -> None:
        fn = self.fn_stack[-1] if self.fn_stack else None
        owner = fn.qual if fn is not None else ""
        name = dotted(node.func)
        if name is not None:
            bare = name.split(".")[-1]
            if bare == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        self.s.spawns.append(
                            ("thread", self._callee_ref(kw.value),
                             node.lineno, owner))
            elif bare == "Timer" and len(node.args) >= 2:
                self.s.spawns.append(
                    ("timer", self._callee_ref(node.args[1]),
                     node.lineno, owner))
            elif name in ("weakref.finalize", "finalize") and \
                    len(node.args) >= 2:
                self.s.spawns.append(
                    ("finalize", self._callee_ref(node.args[1]),
                     node.lineno, owner))
            elif bare == "submit" and isinstance(node.func, ast.Attribute) \
                    and node.args:
                self.s.spawns.append(
                    ("submit", self._callee_ref(node.args[0]),
                     node.lineno, owner))
        ref = self._callee_ref(node.func)
        if fn is not None and ref is not None:
            fn.calls.add(ref)
            if self.held:
                fn.lock_events.append(
                    (tuple(self.held), "call", ref, node.lineno))
        self.generic_visit(node)

    # --- writes ---

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        """The written attribute name of a self./cls.-rooted chain
        (`self.executor.last_metrics` -> `last_metrics`), else None."""
        name = dotted(node)
        if name is None:
            return None
        parts = name.split(".")
        if len(parts) >= 2 and parts[0] in ("self", "cls"):
            return parts[-1]
        return None

    def _record_write(self, name: str, line: int) -> None:
        if not self.fn_stack:
            return            # module/class scope: import-lock serialized
        fn = self.fn_stack[-1]
        guarded = bool(self.held) or fn.exempt or \
            name in self.s.guarded_names
        fn.writes.append((name, line, guarded))

    def _target_write(self, tgt: ast.AST, line: int) -> None:
        if isinstance(tgt, ast.Attribute):
            name = self._self_attr(tgt)
            if name is not None:
                self._record_write(name, line)
        elif isinstance(tgt, ast.Subscript):
            base = tgt.value
            if isinstance(base, ast.Attribute):
                name = self._self_attr(base)
                if name is not None:
                    self._record_write(name, line)
            elif isinstance(base, ast.Name) and \
                    base.id in self.module_globals:
                self._record_write(base.id, line)
        elif isinstance(tgt, ast.Name):
            # `global X` must precede the assignment syntactically, so the
            # in-order walk has already filled globals_map for this fn
            if self.fn_stack and tgt.id in self.globals_map.get(
                    self.fn_stack[-1].qual, ()):
                self._record_write(tgt.id, line)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._target_write(e, line)

    def visit_Global(self, node: ast.Global) -> None:
        if self.fn_stack:
            self.globals_map.setdefault(
                self.fn_stack[-1].qual, set()).update(node.names)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._target_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._target_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._target_write(t, node.lineno)
        self.generic_visit(node)


def _collect(mod: LintModule) -> _Summary:
    s = _Summary(mod)
    _Collector(mod, s).visit(mod.tree)
    return s


class _GraphJudge:
    """Shared resolution + call-graph machinery for both judges."""

    def __init__(self, summaries: dict):
        # drop modules a partial run never collected (summary is None)
        self.summaries = {k: v for k, v in summaries.items()
                          if isinstance(v, _Summary)}
        self.edges: dict = {}     # (rel, qual) -> set of (rel, qual)
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            for qual in sorted(s.functions):
                fn = s.functions[qual]
                tgt = self.edges.setdefault((rel, qual), set())
                for ref in sorted(fn.calls):
                    r = self.resolve(s, fn, ref)
                    if r is not None:
                        tgt.add(r)

    def resolve(self, s: _Summary, fn: Optional[_FnInfo], ref):
        """A collected callee ref -> (relpath, qual) node, or None."""
        if ref is None:
            return None
        kind = ref[0]
        if kind == "bare":
            n = ref[1]
            if fn is not None:
                parts = fn.qual.split(".")
                # nested-def scopes: innermost enclosing FUNCTION first
                # (a prefix that is itself a function qual — class names
                # never are, so `Cls.meth` doesn't fake-match `Cls.n`)
                for i in range(len(parts), 0, -1):
                    prefix = ".".join(parts[:i])
                    if prefix in s.functions and \
                            f"{prefix}.{n}" in s.functions:
                        return (s.relpath, f"{prefix}.{n}")
            if n in s.module_fns:
                return (s.relpath, n)
            return self._resolve_import_fn(s.imports.get(n))
        if kind == "self":
            cls = fn.cls if fn is not None else None
            if cls and ref[1] in s.class_methods.get(cls, ()):
                return (s.relpath, f"{cls}.{ref[1]}")
            return None
        if kind == "dotted":
            alias, n = ref[1], ref[2]
            rel2 = self._resolve_import_mod(s.imports.get(alias))
            if rel2 is not None:
                s2 = self.summaries.get(rel2)
                if s2 is not None and n in s2.module_fns:
                    return (rel2, n)
            return None
        return None

    def _resolve_import_mod(self, imp) -> Optional[str]:
        if imp is None:
            return None
        if imp[0] == "modpath":
            return imp[1] if imp[1] in self.summaries else None
        base, name = imp[1], imp[2]
        cand = f"{base}/{name}.py"
        return cand if cand in self.summaries else None

    def _resolve_import_fn(self, imp):
        if imp is None or imp[0] != "maybe":
            return None
        base, name = imp[1], imp[2]
        for cand in (f"{base}.py", f"{base}/__init__.py"):
            s2 = self.summaries.get(cand)
            if s2 is not None and name in s2.module_fns:
                return (cand, name)
        return None

    def reach(self, start) -> set:
        seen = {start}
        frontier = [start]
        while frontier:
            nxt = []
            for node in frontier:
                for succ in self.edges.get(node, ()):
                    if succ not in seen:
                        seen.add(succ)
                        nxt.append(succ)
            frontier = nxt
        return seen

    def roles(self) -> list:
        """[(label, weight, root node)] for every spawn site + handler."""
        out = []
        for rel in sorted(self.summaries):
            s = self.summaries[rel]
            for kind, ref, line, owner in s.spawns:
                fn = s.functions.get(owner)
                root = self.resolve(s, fn, ref)
                if root is None:
                    continue      # non-package callback (e.g. permit.release)
                out.append((f"{kind} {rel}:{line} -> {root[1]}",
                            _WEIGHTS[kind], root))
            if s.action_servers:
                for srv_rel in sorted(set(s.action_servers.values())):
                    s2 = self.summaries.get(srv_rel)
                    if s2 is None:
                        continue
                    for cls in sorted(s2.class_methods):
                        for m in sorted(s2.class_methods[cls]
                                        & _HANDLER_METHODS):
                            out.append(
                                (f"flight-handler {srv_rel}:{cls}.{m}",
                                 _WEIGHTS["handler"],
                                 (srv_rel, f"{cls}.{m}")))
        return out


class ThreadRolesChecker(TwoPassChecker):
    name = "thread-roles"

    def collect(self, mod: LintModule):
        return _collect(mod), ()

    def judge(self, summaries: dict) -> Iterable[Finding]:
        g = _GraphJudge(summaries)
        roles = g.roles()
        roles_at: dict = {}       # fn node -> {role index}
        for idx, (_label, _w, root) in enumerate(roles):
            for node in g.reach(root):
                roles_at.setdefault(node, set()).add(idx)
        out = []
        for (rel, qual), idxs in sorted(roles_at.items()):
            weight = sum(roles[i][1] for i in idxs)
            if weight < 2:
                continue
            fn = g.summaries[rel].functions[qual]
            labels = sorted(roles[i][0] for i in idxs)
            shown = ", ".join(labels[:2]) + \
                (f" (+{len(labels) - 2} more)" if len(labels) > 2 else "")
            for name, line, guarded in fn.writes:
                if guarded:
                    continue
                out.append(Finding(
                    self.name, rel, line,
                    f"`{name}` is written in `{qual}`, which is reachable "
                    f"from concurrent thread roles [{shown}]; guard the "
                    "write with a lock and declare the attr in _GUARDED_BY, "
                    "or add `# lint: allow(thread-roles)` with a rationale"))
        return out


class LockOrderChecker(TwoPassChecker):
    name = "lock-order"

    def collect(self, mod: LintModule):
        return _collect(mod), ()

    def judge(self, summaries: dict) -> Iterable[Finding]:
        g = _GraphJudge(summaries)
        declared = {rel: s.declared_locks for rel, s in g.summaries.items()}
        rep: dict = {}            # lock id -> representative acquisition site
        acquired: dict = {}       # fn node -> set of lock ids
        for rel in sorted(g.summaries):
            s = g.summaries[rel]
            for qual in sorted(s.functions):
                fn = s.functions[qual]
                direct = set()
                for lk in sorted(fn.acquires):
                    if lk in declared[rel]:
                        lid = (rel, lk)
                        direct.add(lid)
                        rep.setdefault(lid, (rel, fn.acquires[lk]))
                acquired[(rel, qual)] = direct
        # close over the call graph: A(f) ⊇ A(g) for every resolved callee
        changed = True
        while changed:
            changed = False
            for node in acquired:
                cur = acquired[node]
                for succ in g.edges.get(node, ()):
                    extra = acquired.get(succ, set())
                    if not extra <= cur:
                        cur |= extra
                        changed = True
        # edges of the lock graph, each with the site that witnesses it
        lock_edges: dict = {}     # (outer id, inner id) -> (path, line)
        for rel in sorted(g.summaries):
            s = g.summaries[rel]
            for qual in sorted(s.functions):
                fn = s.functions[qual]
                for held, kind, payload, line in fn.lock_events:
                    hids = [(rel, h) for h in held if h in declared[rel]]
                    if not hids:
                        continue
                    if kind == "acquire":
                        if payload not in declared[rel]:
                            continue
                        inner = {(rel, payload)}
                    else:
                        callee = g.resolve(s, fn, payload)
                        if callee is None:
                            continue
                        inner = acquired.get(callee, set())
                    for left in hids:
                        for m in inner:
                            lock_edges.setdefault((left, m), (rel, line))
        succs: dict = {}
        for (left, m) in lock_edges:
            succs.setdefault(left, set()).add(m)
        out, seen_cycles = [], set()

        def lname(lid):
            return f"`{lid[1]}` ({lid[0]})"

        for (left, m), (path, line) in sorted(lock_edges.items()):
            if left == m:
                if (left,) in seen_cycles:
                    continue
                seen_cycles.add((left,))
                out.append(Finding(
                    self.name, path, line,
                    f"{lname(left)} is re-acquired while already held "
                    f"(first acquired at {rep[left][0]}:{rep[left][1]}) — "
                    "threading.Lock is non-reentrant; this deadlocks"))
                continue
            # a path m ->* left means this left->m edge closes a cycle
            stack, seen, closes = [m], {m}, False
            while stack:
                cur = stack.pop()
                if cur == left:
                    closes = True
                    break
                for nxt in succs.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            if not closes:
                continue
            key = frozenset((left, m))
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            rpath, rline = lock_edges[(m, left)] if (m, left) in lock_edges \
                else rep[m]
            out.append(Finding(
                self.name, path, line,
                f"lock-order cycle: {lname(left)} -> {lname(m)} here, but "
                f"{lname(m)} -> {lname(left)} near {rpath}:{rline} — "
                "acquired in opposite orders; potential deadlock"))
        return out
