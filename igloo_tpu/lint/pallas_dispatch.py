"""pallas-dispatch: every Pallas kernel call must go through exec/dispatch.py.

The ``IGLOO_TPU_PALLAS`` flag, the eligibility checks, the negative caches
fed by runtime overflow flags, and the ``pallas.*`` counters all live in
``exec/dispatch.py`` (docs/kernels.md#fallback-ladder). A direct import of
``exec/pallas_kernels`` anywhere else creates a call path that bypasses the
flag AND the fallback ladder: the kernel then runs with no sort-path escape
on overflow and no attribution — exactly the hole that would make
``IGLOO_TPU_PALLAS=0`` stop being a trustworthy kill switch. This checker
flags every import form of the kernels module in every package module
except the dispatch site.

Scope is the package only: tests and scripts legitimately reach the
kernels directly for kernel-level equivalence assertions.
"""
from __future__ import annotations

import ast
from typing import Iterable

from igloo_tpu.lint import Checker, Finding, LintModule

RULE = "pallas-dispatch"

#: the modules allowed to call into the Pallas kernels: the dispatch ladder
#: itself, and the autotuner (exec/autotune.py), which benchmarks candidate
#: shapes by invoking kernels directly on synthetic lanes — outside the
#: ladder by design, never on query data
DISPATCH_SITES = frozenset({"igloo_tpu/exec/dispatch.py",
                            "igloo_tpu/exec/autotune.py"})

KERNELS_MODULE = "igloo_tpu.exec.pallas_kernels"

_MSG = ("direct pallas_kernels import bypasses the dispatch layer "
        "(IGLOO_TPU_PALLAS flag, eligibility checks, overflow fallback "
        "ladder, pallas.* counters) — route through igloo_tpu.exec.dispatch")


def _resolve_from(relpath: str, level: int, module):
    """Absolute dotted module a `from ... import` refers to: `level` dots
    climb packages from the importing file's package (PEP 328), so
    `from .pallas_kernels import x` inside igloo_tpu/exec/foo.py resolves
    to igloo_tpu.exec.pallas_kernels."""
    if not level:
        return module or ""
    pkg = relpath.rsplit("/", 1)[0].split("/") if "/" in relpath else []
    if level > 1:
        pkg = pkg[: len(pkg) - (level - 1)]
    base = ".".join(pkg)
    if not module:
        return base
    return f"{base}.{module}" if base else module


class PallasDispatchChecker(Checker):
    name = RULE

    def check(self, mod: LintModule) -> Iterable[Finding]:
        if mod.relpath in DISPATCH_SITES or \
                not mod.relpath.startswith("igloo_tpu/"):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == KERNELS_MODULE:
                        yield Finding(RULE, mod.relpath, node.lineno,
                                      f"`import {a.name}`: {_MSG}")
            elif isinstance(node, ast.ImportFrom):
                # absolute AND relative forms resolve to one dotted path
                target = _resolve_from(mod.relpath, node.level or 0,
                                       node.module)
                if target == KERNELS_MODULE:
                    yield Finding(RULE, mod.relpath, node.lineno,
                                  f"`from {node.module or '.'} "
                                  f"import ...`: {_MSG}")
                elif target == "igloo_tpu.exec":
                    for a in node.names:
                        if a.name == "pallas_kernels":
                            yield Finding(
                                RULE, mod.relpath, node.lineno,
                                f"`from {node.module or '.'} import "
                                f"pallas_kernels`: {_MSG}")
