"""AST extraction of the cluster protocol registry for the lint checkers.

The ``wire-contract`` and ``flight-actions`` rules judge the package against
the declarative registry in ``igloo_tpu/cluster/protocol.py``. The lint
framework is pure AST — it never imports checked code — so this module
re-reads the registry the same way: parse the file, walk the module-level
``NAME = Message("msg", [Field(...), ...])`` assignments and the literal
action/name tables. That only works because protocol.py keeps its
declarations PURE LITERALS (its module docstring states the rule); anything
non-literal here is skipped and simply invisible to the checkers.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from igloo_tpu.lint import const_str


@dataclass
class FieldSpec:
    name: str
    required: bool = False
    line: int = 1


@dataclass
class MessageSpec:
    var: str                    # the module-level variable name
    name: str                   # the wire message name
    check: str = "flow"         # flow | schema
    line: int = 1
    fields: dict = field(default_factory=dict)   # name -> FieldSpec


@dataclass
class Registry:
    path: Path                  # resolved registry file
    relpath: str                # as it should appear in findings
    messages: dict = field(default_factory=dict)      # var -> MessageSpec
    actions: dict = field(default_factory=dict)       # role -> {name: line}
    action_servers: dict = field(default_factory=dict)  # role -> relpath
    wire_modules: list = field(default_factory=list)
    parse_helpers: dict = field(default_factory=dict)   # helper -> msg name

    def by_message_name(self, name: str) -> Optional[MessageSpec]:
        for m in self.messages.values():
            if m.name == name:
                return m
        return None

    def flow_fields(self) -> set:
        """Union of field names of every flow-checked message (the scope of
        the raw-wire-access rule)."""
        out: set = set()
        for m in self.messages.values():
            if m.check == "flow":
                out.update(m.fields)
        return out


def _parse_message(var: str, call: ast.Call, line: int
                   ) -> Optional[MessageSpec]:
    if not call.args or const_str(call.args[0]) is None:
        return None
    spec = MessageSpec(var=var, name=const_str(call.args[0]), line=line)
    for kw in call.keywords:
        if kw.arg == "check" and const_str(kw.value):
            spec.check = const_str(kw.value)
    if len(call.args) > 1 and isinstance(call.args[1], ast.List):
        for elt in call.args[1].elts:
            if not (isinstance(elt, ast.Call)
                    and isinstance(elt.func, ast.Name)
                    and elt.func.id == "Field" and elt.args):
                continue
            fname = const_str(elt.args[0])
            if fname is None:
                continue
            required = any(
                kw.arg == "required" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in elt.keywords)
            spec.fields[fname] = FieldSpec(fname, required=required,
                                           line=elt.lineno)
    return spec


def load_registry(path: Path, root: Path) -> Optional[Registry]:
    """Parse the registry file; None when it is missing or unparsable (the
    checkers turn that into a finding of their own)."""
    path = Path(path).resolve()
    if not path.exists():
        return None
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError:
        return None
    try:
        rel = path.relative_to(Path(root).resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    reg = Registry(path=path, relpath=rel)
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        var = node.targets[0].id
        value = node.value
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
                and value.func.id == "Message":
            spec = _parse_message(var, value, node.lineno)
            if spec is not None:
                reg.messages[var] = spec
        elif var in ("COORDINATOR_ACTIONS", "WORKER_ACTIONS") and \
                isinstance(value, ast.Dict):
            role = "coordinator" if var.startswith("COORD") else "worker"
            reg.actions[role] = {
                const_str(k): k.lineno for k in value.keys
                if const_str(k) is not None}
        elif var == "ACTION_SERVERS" and isinstance(value, ast.Dict):
            reg.action_servers = {
                const_str(k): const_str(v)
                for k, v in zip(value.keys, value.values)
                if const_str(k) is not None and const_str(v) is not None}
        elif var == "WIRE_MODULES" and isinstance(value, ast.List):
            reg.wire_modules = [const_str(e) for e in value.elts
                                if const_str(e) is not None]
        elif var == "PARSE_HELPERS" and isinstance(value, ast.Dict):
            reg.parse_helpers = {
                const_str(k): const_str(v)
                for k, v in zip(value.keys, value.values)
                if const_str(k) is not None and const_str(v) is not None}
    return reg
