"""metric-names: tracing counter/histogram/gauge names must match the catalog.

Migrated from scripts/check_metrics_names.py into the shared lint framework
(same rules, same catalog): every ``tracing.counter(...)`` /
``tracing.histogram(...)`` / ``tracing.gauge(...)`` / ``tracing.gauge_add
(...)`` name used in the package must be covered by the catalog in
docs/observability.md, so metric names cannot silently drift or typo-fork
(``pack.hits`` vs ``pack.hit``).

Rules:
- a literal name must be covered by the catalog verbatim (or by a
  documented ``prefix.*`` wildcard);
- an f-string name is reduced to its literal prefix (up to the first ``{``,
  trailing dot stripped) which must be covered by a ``prefix.*`` wildcard;
- a name with NO literal prefix (e.g. ``f"{self.counter_prefix}.hit"``)
  must resolve through DYNAMIC_PREFIXES below, each expansion documented.

Catalog entries no code uses are warnings only (some call sites are
platform-gated).
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Iterable, Optional

from igloo_tpu.lint import REPO_ROOT, Checker, Finding, LintModule

RULE = "metric-names"

# placeholder -> the values it takes across the codebase (SnapshotLRU
# subclasses set counter_prefix)
DYNAMIC_PREFIXES = {
    "self.counter_prefix": ["cache", "result_cache"],
}

CALL_RE = re.compile(
    r"(?:tracing\.)?(?:counter|histogram|gauge|gauge_add)\(\s*(f?)[\"']",
    re.MULTILINE)
# metric-name string literals inside one call region (covers ternary arms:
# counter("a" if ok else "b"))
NAME_STR_RE = re.compile(
    r"[\"']([a-z][a-z0-9_]*(?:\.[a-z0-9_{}.]+)*|\{[a-zA-Z_.]+\}[a-z0-9_.]*)"
    r"[\"']")
DOC_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_*.]+)+)`")


def _covered(name: str, catalog: set) -> bool:
    if name in catalog:
        return True
    parts = name.split(".")
    return any(".".join(parts[:i]) + ".*" in catalog
               for i in range(len(parts) - 1, 0, -1))


class MetricNamesChecker(Checker):
    name = RULE

    #: overridable for fixture tests (None -> docs/observability.md)
    doc_path: Optional[Path] = None
    dynamic_prefixes = DYNAMIC_PREFIXES

    def __init__(self, doc_path: Optional[Path] = None):
        if doc_path is not None:
            self.doc_path = Path(doc_path)
        self.sites: list[tuple] = []       # (name, is_fstring, path, line)
        self.warnings: list[str] = []

    def check(self, mod: LintModule) -> Iterable[Finding]:
        text = mod.text
        for m in CALL_RE.finditer(text):
            line = text[: m.start()].count("\n") + 1
            region = text[m.start():]
            # the call's argument region: up to the first close-paren at
            # line end (good enough for this codebase's formatting)
            end = region.find(")\n")
            region = region[: end + 1 if end >= 0 else 240]
            is_f = m.group(1) == "f" or ', f"' in region or " f\"" in region
            for nm in NAME_STR_RE.findall(region):
                if "." not in nm and "{" not in nm:
                    continue  # not a metric-shaped string (e.g. format arg)
                self.sites.append((nm, is_f or "{" in nm,
                                   mod.relpath, line))
        return ()

    def _doc(self) -> Path:
        return self.doc_path if self.doc_path is not None \
            else REPO_ROOT / "docs" / "observability.md"

    def _catalog(self) -> Optional[set]:
        doc = self._doc()
        if not doc.exists():
            return None
        text = doc.read_text()
        start = text.find("## Metrics catalog")
        end = text.find("## Per-query", start)
        section = text[start:end] if start >= 0 else text
        return set(DOC_NAME_RE.findall(section))

    def finalize(self, modules: list) -> Iterable[Finding]:
        catalog = self._catalog()
        if catalog is None:
            return [Finding(RULE, "docs/observability.md", 1,
                            "metrics catalog file is missing")]
        out: list[Finding] = []
        used_plain: set = set()
        for nm, is_f, path, line in self.sites:
            if not is_f:
                used_plain.add(nm)
                if not _covered(nm, catalog):
                    out.append(Finding(
                        RULE, path, line, f"metric `{nm}` is not documented "
                        "in docs/observability.md"))
                continue
            if nm.startswith("{"):
                ph = nm[1:].split("}", 1)[0]
                suffix = nm.split("}", 1)[1].lstrip(".") if "}" in nm else ""
                expansions = self.dynamic_prefixes.get(ph)
                if expansions is None:
                    out.append(Finding(
                        RULE, path, line, f"fully dynamic metric name "
                        f"`{nm}` is not in DYNAMIC_PREFIXES "
                        "(igloo_tpu/lint/metric_names.py)"))
                    continue
                for p in expansions:
                    full = f"{p}.{suffix}" if suffix else p
                    used_plain.add(full)
                    if not _covered(full, catalog):
                        out.append(Finding(
                            RULE, path, line, f"metric `{full}` "
                            "(dynamic-prefix expansion) is undocumented"))
                continue
            prefix = nm.split("{", 1)[0].rstrip(".")
            used_plain.add(prefix + ".dynamic")
            if not _covered(prefix + ".dynamic", catalog):
                out.append(Finding(
                    RULE, path, line, f"f-string metric `{nm}` needs a "
                    f"`{prefix}.*` wildcard in the catalog"))
        # unused-entry warnings only make sense when the WHOLE package was
        # scanned — on a partial run (explicit paths) nearly every entry
        # would look stale and drown real warnings
        from igloo_tpu.lint import REPO_ROOT as _root
        from igloo_tpu.lint import iter_package_files
        linted = {m.relpath for m in modules}
        pkg = {p.resolve().relative_to(_root.resolve()).as_posix()
               for p in iter_package_files()}
        if pkg and pkg <= linted:
            for entry in sorted(catalog):
                base = entry[:-2] if entry.endswith(".*") else entry
                hit = any(u == base or u.startswith(base + ".")
                          for u in used_plain) if entry.endswith(".*") \
                    else base in used_plain
                if not hit:
                    self.warnings.append(
                        f"metric-names: catalog entry `{entry}` matches no "
                        "code call site")
        return out
