"""lock-discipline: declared guarded state must be accessed under its lock.

The threaded subsystems (FragmentStore, the metrics registry, the GRACE
prefetch pipeline, the worker's Flight RPC threads) guard shared state with
locks whose discipline was previously enforced only by convention — nothing
stopped a new method from reading ``self._entries`` without ``self._lock``.

A module opts in by declaring its guarded state at module level:

    _GUARDED_BY = {"_lock": ("_entries", "_seq"), "_delta_lock": ("_data",)}

Keys are lock names — matched as ``self.<lock>`` (instance locks) or a bare
module-global name; values are the attribute/global names they guard. The
checker then requires every load/store of a guarded name anywhere in the
module (any receiver — aliases like ``ent._entries`` are deliberately
caught) to be one of:

- lexically inside ``with self.<lock>:`` / ``with <lock>:`` (any of the
  with-items; ``.acquire()`` calls don't count — use ``with``);
- in a method whose name ends in ``_locked`` (the caller-holds-the-lock
  naming convention), or whose docstring contains ``caller-locked``;
- in ``__init__``/``__new__`` (the object is not shared yet) or at module
  scope (import-time init, serialized by the import lock);
- suppressed with ``# lint: allow(lock-discipline)``.

Declared locks or guarded names that never appear in the module are
warnings (stale declaration).
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from igloo_tpu.lint import Checker, Finding, LintModule, dotted

RULE = "lock-discipline"

_EXEMPT_METHODS = {"__init__", "__new__"}


def _load_guarded_by(tree: ast.Module) -> Optional[dict]:
    """Evaluate the module-level `_GUARDED_BY = {...}` literal, if any."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "_GUARDED_BY":
                    try:
                        v = ast.literal_eval(node.value)
                    except ValueError:
                        return None
                    if isinstance(v, dict):
                        return {str(k): tuple(vs) for k, vs in v.items()}
    return None


class _Access:
    __slots__ = ("name", "line", "kind")

    def __init__(self, name: str, line: int, kind: str):
        self.name, self.line, self.kind = name, line, kind


class _ModulePass(ast.NodeVisitor):
    """Collect guarded-name accesses with their lock/function context."""

    def __init__(self, guards: dict):
        self.guards = guards                      # lock -> guarded names
        self.guarded: dict = {}                   # name -> lock
        for lock, names in guards.items():
            for n in names:
                self.guarded[n] = lock
        self.held: list[str] = []                 # lock-name stack
        self.fn_stack: list[ast.AST] = []
        self.violations: list[_Access] = []
        self.seen_names: set = set()
        self.seen_locks: set = set()

    # --- context helpers ---

    def _fn_exempt(self) -> bool:
        for fn in reversed(self.fn_stack):
            name = getattr(fn, "name", "")
            if name in _EXEMPT_METHODS or name.endswith("_locked"):
                return True
            doc = ast.get_docstring(fn) if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
            if doc and "caller-locked" in doc.lower():
                return True
        return False

    def _lock_of(self, item: ast.AST) -> Optional[str]:
        """'_lock' for `self._lock` / `cls._lock` / bare `_lock` with-items."""
        name = dotted(item)
        if name is None:
            return None
        parts = name.split(".")
        cand = parts[-1]
        if cand in self.guards and (len(parts) == 1 or
                                    parts[0] in ("self", "cls")):
            return cand
        return None

    # --- visitors ---

    def visit_With(self, node: ast.With) -> None:
        got = [lk for item in node.items
               if (lk := self._lock_of(item.context_expr)) is not None]
        self.held.extend(got)
        self.seen_locks.update(got)
        self.generic_visit(node)
        for _ in got:
            self.held.pop()

    def _visit_fn(self, node) -> None:
        # a `with self._lock:` held OUTSIDE a nested def is NOT held when the
        # def later runs (closures escape) — reset the held stack inside
        self.fn_stack.append(node)
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn

    def _record(self, name: str, line: int, kind: str) -> None:
        self.seen_names.add(name)
        lock = self.guarded[name]
        if lock in self.held or self._fn_exempt():
            return
        if not self.fn_stack:
            return  # module scope: import-time init, serialized by the
            #         import lock before any thread can share the state
        self.violations.append(_Access(name, line, kind))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in self.guarded:
            kind = "write" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "read"
            self._record(node.attr, node.lineno, kind)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        # module-global guarded names (e.g. a counter next to a module lock)
        if node.id in self.guarded:
            kind = "write" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "read"
            self._record(node.id, node.lineno, kind)

    def visit_Global(self, node: ast.Global) -> None:
        return  # `global x` declarations are not accesses


class LockDisciplineChecker(Checker):
    name = RULE

    def __init__(self):
        self.warnings: list[str] = []
        self._stale: list[Finding] = []       # accumulating, this run
        self._last_stale: list[Finding] = []  # snapshot of the last run

    def check(self, mod: LintModule) -> Iterable[Finding]:
        guards = _load_guarded_by(mod.tree)
        if guards is None:
            return ()
        decl_line = 1
        p = _ModulePass(guards)
        # skip the _GUARDED_BY assignment itself
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                    for t in node.targets):
                decl_line = node.lineno
                continue
            p.visit(node)
        for lock, names in guards.items():
            if lock not in p.seen_locks:
                self.warnings.append(
                    f"lock-discipline: {mod.relpath}: declared lock "
                    f"`{lock}` never appears in a `with` block")
                self._stale.append(Finding(
                    "stale-entry", mod.relpath, decl_line,
                    f"_GUARDED_BY lock `{lock}` never appears in a `with` "
                    "block — stale declaration"))
            for n in names:
                if n not in p.seen_names:
                    self.warnings.append(
                        f"lock-discipline: {mod.relpath}: guarded name "
                        f"`{n}` never accessed — stale declaration?")
                    self._stale.append(Finding(
                        "stale-entry", mod.relpath, decl_line,
                        f"_GUARDED_BY name `{n}` is never accessed in the "
                        "module — stale declaration"))
        return [Finding(
            RULE, mod.relpath, a.line,
            f"{a.kind} of `{a.name}` (guarded by `{p.guarded[a.name]}` per "
            "_GUARDED_BY) outside a `with` block holding the lock; hold the "
            "lock, rename the method `*_locked`, or document it caller-locked")
            for a in p.violations]

    def finalize(self, modules: list) -> Iterable[Finding]:
        # snapshot per run, like TwoPassChecker's summaries: a reused
        # checker instance must not leak one run's staleness into the next
        self._last_stale, self._stale = self._stale, []
        return ()

    def stale_entries(self) -> list:
        """Structured stale-declaration report for ``--stale-allows``."""
        return list(self._last_stale)
