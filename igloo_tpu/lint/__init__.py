"""igloo-lint: AST-based hazard analysis for the engine's own bug classes.

The reference gates every change behind ``clippy -D warnings`` — a semantic
linter that knows Rust's hazard classes (Send/Sync, borrow discipline). Ruff
gives us style, but none of the bug classes this codebase has actually
shipped were machine-checked: PR 2 fixed an ``id()``-reuse cache-staleness
bug by hand, PR 4 added a second threaded subsystem whose lock discipline is
enforced only by convention, and the whole perf story depends on implicit
host<->device syncs staying out of the hot path. This package is the
counterpart: one shared AST walk over ``igloo_tpu/`` with per-checker
visitors (docs/static_analysis.md has the rule catalog):

- ``sync-hazard``     implicit device syncs (bool/int/float/len/.item()/
                      np.asarray/iteration/device_get on jax-originating
                      values) in the hot-path modules (exec/, parallel/)
                      outside the documented choke-point whitelist;
- ``cache-key``       identity (``id()``) tokens, ``hash()`` over mutable
                      state, and dict/set iteration order feeding cache or
                      jit keys — the PR-2 staleness bug class;
- ``jit-key``         raw data-dependent ints (live counts, device-get
                      readbacks, ``int()`` casts) flowing into ``_jitted``
                      fingerprints — the compile-cache fragmentation class
                      the cold-start work (docs/compile_cache.md) exists to
                      kill; quantize through exec/capacity.py first;
- ``lock-discipline`` every access to state a module declares via
                      ``_GUARDED_BY`` must hold the declared lock (or sit in
                      a caller-locked method);
- ``metric-names``    tracing counter/histogram names must match the catalog
                      in docs/observability.md (migrated from
                      scripts/check_metrics_names.py);
- ``span-names``      flight-recorder span names (tracing.span /
                      Trace.add_span / request_scope) must match the Span
                      catalog in docs/observability.md — timeline names
                      must not typo-fork any more than metric names can;
- ``rpc-policy``      no ``flight.connect`` / ``FlightClient`` outside
                      ``cluster/rpc.py`` — every Flight connection must run
                      under the RPC policy (deadlines, retry/backoff), or a
                      hung peer wedges the calling thread forever;
- ``pallas-dispatch`` no ``exec/pallas_kernels`` import outside
                      ``exec/dispatch.py`` — every Pallas kernel call must
                      run under the dispatch layer (IGLOO_TPU_PALLAS flag,
                      eligibility checks, overflow fallback ladder), or the
                      kill switch stops being trustworthy.

Suppress a finding with a trailing ``# lint: allow(<rule>)`` comment on the
offending line (or a standalone allow-comment on the line directly above);
every suppression should say why on the same line or the surrounding code.

Entry point: ``python -m igloo_tpu.lint`` (wired into scripts/validate.sh
and the __graft_entry__ dryrun preamble). Pure AST — no imports of the
checked code, so it runs in a couple of seconds with no device/backend.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

PACKAGE_ROOT = Path(__file__).resolve().parent.parent   # igloo_tpu/
REPO_ROOT = PACKAGE_ROOT.parent

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class LintModule:
    """One parsed source file, shared by every checker."""
    path: Path
    relpath: str                        # repo-relative, forward slashes
    text: str
    tree: ast.Module
    # line -> set of rule names allowed on that line (an allow-comment on its
    # own line also covers the line below, for statements too long to share)
    allows: dict = field(default_factory=dict)

    @classmethod
    def parse(cls, path: Path, root: Path = REPO_ROOT) -> "LintModule":
        path = Path(path).resolve()
        text = path.read_text()
        tree = ast.parse(text, filename=str(path))
        allows: dict[int, set] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _ALLOW_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            allows.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):   # standalone comment line
                allows.setdefault(i + 1, set()).update(rules)
        try:
            rel = path.relative_to(Path(root).resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()  # outside the root: report the full path
        return cls(path=path, relpath=rel, text=text, tree=tree,
                   allows=allows)

    def allowed(self, rule: str, line: int) -> bool:
        return rule in self.allows.get(line, ())


class Checker:
    """One rule family. Subclasses set `name` and implement `check`;
    checkers needing repo-level context (docs files) override `finalize`,
    which runs once after every module has been checked."""

    name = "checker"

    def check(self, mod: LintModule) -> Iterable[Finding]:
        return ()

    def finalize(self, modules: list) -> Iterable[Finding]:
        return ()


def dotted(node: ast.AST) -> Optional[str]:
    """'jnp.sum' / 'jax.lax.scan' / 'self._lock' for Name/Attribute chains;
    None for anything else (calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_package_files(root: Path = PACKAGE_ROOT) -> list[Path]:
    """Every package source file except lint/ itself (the linter's own regex
    literals and rule tables would self-match)."""
    lint_dir = root / "lint"
    return sorted(p for p in root.rglob("*.py")
                  if "__pycache__" not in p.parts
                  and lint_dir not in p.parents)


def default_checkers() -> list:
    from igloo_tpu.lint.cache_key import CacheKeyChecker
    from igloo_tpu.lint.jit_key import JitKeyChecker
    from igloo_tpu.lint.lock_discipline import LockDisciplineChecker
    from igloo_tpu.lint.metric_names import MetricNamesChecker
    from igloo_tpu.lint.pallas_dispatch import PallasDispatchChecker
    from igloo_tpu.lint.rpc_policy import RpcPolicyChecker
    from igloo_tpu.lint.span_names import SpanNamesChecker
    from igloo_tpu.lint.sync_hazard import SyncHazardChecker
    return [SyncHazardChecker(), CacheKeyChecker(), JitKeyChecker(),
            LockDisciplineChecker(), MetricNamesChecker(),
            SpanNamesChecker(), RpcPolicyChecker(), PallasDispatchChecker()]


def run_lint(paths: Optional[list] = None, checkers: Optional[list] = None,
             select: Optional[set] = None, root: Path = REPO_ROOT
             ) -> tuple[list, list]:
    """-> (findings, warnings). `paths` defaults to the igloo_tpu package
    (lint/ itself excluded); `select` restricts to a subset of rule names."""
    if checkers is None:
        checkers = default_checkers()
    if select:
        checkers = [c for c in checkers if c.name in select]
    files = paths if paths is not None else iter_package_files()
    modules = [LintModule.parse(Path(p), root=root) for p in files]
    findings: list[Finding] = []
    warnings: list[str] = []
    by_path = {m.relpath: m for m in modules}
    for c in checkers:
        got: list[Finding] = []
        for mod in modules:
            for f in c.check(mod):
                if not mod.allowed(f.rule, f.line):
                    got.append(f)
        for f in c.finalize(modules):
            m = by_path.get(f.path)
            if m is None or not m.allowed(f.rule, f.line):
                got.append(f)
        warnings.extend(getattr(c, "warnings", ()))
        findings.extend(got)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, warnings
