"""igloo-lint: AST-based hazard analysis for the engine's own bug classes.

The reference gates every change behind ``clippy -D warnings`` — a semantic
linter that knows Rust's hazard classes (Send/Sync, borrow discipline). Ruff
gives us style, but none of the bug classes this codebase has actually
shipped were machine-checked: PR 2 fixed an ``id()``-reuse cache-staleness
bug by hand, PR 4 added a second threaded subsystem whose lock discipline is
enforced only by convention, and the whole perf story depends on implicit
host<->device syncs staying out of the hot path. This package is the
counterpart: one shared AST walk over ``igloo_tpu/`` with per-checker
visitors (docs/static_analysis.md has the rule catalog):

- ``sync-hazard``     implicit device syncs (bool/int/float/len/.item()/
                      np.asarray/iteration/device_get on jax-originating
                      values) in the hot-path modules (exec/, parallel/)
                      outside the documented choke-point whitelist;
- ``cache-key``       identity (``id()``) tokens, ``hash()`` over mutable
                      state, and dict/set iteration order feeding cache or
                      jit keys — the PR-2 staleness bug class;
- ``jit-key``         raw data-dependent ints (live counts, device-get
                      readbacks, ``int()`` casts) flowing into ``_jitted``
                      fingerprints — the compile-cache fragmentation class
                      the cold-start work (docs/compile_cache.md) exists to
                      kill; quantize through exec/capacity.py first;
- ``lock-discipline`` every access to state a module declares via
                      ``_GUARDED_BY`` must hold the declared lock (or sit in
                      a caller-locked method);
- ``metric-names``    tracing counter/histogram names must match the catalog
                      in docs/observability.md (migrated from
                      scripts/check_metrics_names.py);
- ``span-names``      flight-recorder span names (tracing.span /
                      Trace.add_span / request_scope) must match the Span
                      catalog in docs/observability.md — timeline names
                      must not typo-fork any more than metric names can;
- ``event-names``     cluster-journal event kinds (``events.emit``) must
                      match the Event catalog in docs/observability.md —
                      the journal's kinds are its schema (dashboards and
                      ``igloo_events_total{kind=...}`` filter on them);
- ``rpc-policy``      no ``flight.connect`` / ``FlightClient`` outside
                      ``cluster/rpc.py`` — every Flight connection must run
                      under the RPC policy (deadlines, retry/backoff), or a
                      hung peer wedges the calling thread forever;
- ``pallas-dispatch`` no ``exec/pallas_kernels`` import outside
                      ``exec/dispatch.py`` — every Pallas kernel call must
                      run under the dispatch layer (IGLOO_TPU_PALLAS flag,
                      eligibility checks, overflow fallback ladder), or the
                      kill switch stops being trustworthy;
- ``wire-contract``   whole-program protocol conformance against the
                      declarative registry in ``cluster/protocol.py``: every
                      registry-tagged ``build``/``parse`` site's fields must
                      be declared, flow-checked message fields must be both
                      produced AND consumed somewhere in the package, and
                      raw json field plucking in the wire modules is flagged
                      (the PR 7/10/11 protocol-drift bug class);
- ``flight-actions``  action strings dispatched in server ``do_action``
                      methods and passed to ``flight_action*`` helpers must
                      match the registry's action tables exactly, both
                      directions;
- ``env-knobs``       every ``IGLOO_*`` env knob read in the package must
                      have a row in the consolidated ``docs/knobs.md``
                      catalog with a matching default, every catalog row
                      must have a live reader, and ``[rpc]``/``[serving]``
                      config keys must agree with their documented env twin.

- ``thread-roles``    whole-program race detection: every thread-spawn site
                      (Thread/Timer/pool.submit/weakref.finalize, plus the
                      Flight handler entry points derived from
                      cluster/protocol.py's ACTION_SERVERS) is a role; any
                      ``self.<attr>``/module-global write reachable from
                      concurrent roles through the conservative call graph
                      must be locked, ``_GUARDED_BY``-declared, or
                      allow-commented;
- ``lock-order``      the nesting order of ``with``-acquired declared locks,
                      closed over the call graph, must be acyclic (cycles =
                      potential deadlock; self-loops = re-acquisition of a
                      non-reentrant Lock).

``wire-contract``/``flight-actions``/``env-knobs`` were the framework's
first WHOLE-PROGRAM rules on the ``TwoPassChecker`` API (collect per-file
summaries, then judge globally); ``thread-roles``/``lock-order`` build on
it, and ``sync-hazard`` adopted it for one level of interprocedural taint
summaries (a helper returning a device value now taints its callers'
``int()``/``bool()``/``.item()`` sinks).

Suppress a finding with a trailing ``# lint: allow(<rule>)`` comment on the
offending line (or a standalone allow-comment on the line directly above);
every suppression should say why on the same line or the surrounding code.
``python -m igloo_tpu.lint --stale-allows`` reports allow-comments that no
longer suppress anything, so dead suppressions don't linger as false cover.

Entry point: ``python -m igloo_tpu.lint`` (wired into scripts/validate.sh
and the __graft_entry__ dryrun preamble). Pure AST — no imports of the
checked code, so it runs in a couple of seconds with no device/backend.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

PACKAGE_ROOT = Path(__file__).resolve().parent.parent   # igloo_tpu/
REPO_ROOT = PACKAGE_ROOT.parent

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class LintModule:
    """One parsed source file, shared by every checker."""
    path: Path
    relpath: str                        # repo-relative, forward slashes
    text: str
    tree: ast.Module
    # line -> set of rule names allowed on that line (an allow-comment on its
    # own line also covers the line below, for statements too long to share)
    allows: dict = field(default_factory=dict)

    @classmethod
    def parse(cls, path: Path, root: Path = REPO_ROOT) -> "LintModule":
        path = Path(path).resolve()
        text = path.read_text()
        tree = ast.parse(text, filename=str(path))
        allows: dict[int, set] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _ALLOW_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            allows.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):   # standalone comment line
                allows.setdefault(i + 1, set()).update(rules)
        try:
            rel = path.relative_to(Path(root).resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()  # outside the root: report the full path
        return cls(path=path, relpath=rel, text=text, tree=tree,
                   allows=allows)

    def allowed(self, rule: str, line: int) -> bool:
        return rule in self.allows.get(line, ())


class Checker:
    """One rule family. Subclasses set `name` and implement `check`;
    checkers needing repo-level context (docs files) override `finalize`,
    which runs once after every module has been checked."""

    name = "checker"

    def check(self, mod: LintModule) -> Iterable[Finding]:
        return ()

    def finalize(self, modules: list) -> Iterable[Finding]:
        return ()


class TwoPassChecker(Checker):
    """Whole-program rule family: pass 1 `collect`s a per-file summary (plus
    any immediately-judgeable findings), pass 2 `judge`s the summaries
    globally once every module has been seen. The framework routes `check`
    into collect and `finalize` into judge, so two-pass checkers run under
    the same driver (and the same allow-comment filtering) as per-file ones.

    `judge` findings land wherever the checker anchors them — a registry
    declaration line, a docs-catalog row — and are allow-filterable only
    when that file is among the linted modules (run_lint's by_path rule)."""

    def __init__(self):
        self._summaries: dict = {}   # relpath -> summary object

    def collect(self, mod: LintModule):
        """-> (summary, findings) for one module."""
        return None, ()

    def judge(self, summaries: dict) -> Iterable[Finding]:
        """Global pass over every module's summary."""
        return ()

    def check(self, mod: LintModule) -> Iterable[Finding]:
        summary, findings = self.collect(mod)
        self._summaries[mod.relpath] = summary
        return findings

    def finalize(self, modules: list) -> Iterable[Finding]:
        # one run's summaries must not leak into the next: a reused checker
        # instance (whole-package run followed by a single-file run) would
        # otherwise judge the second run against the first run's files
        summaries, self._summaries = self._summaries, {}
        return self.judge(summaries)


def dotted(node: ast.AST) -> Optional[str]:
    """'jnp.sum' / 'jax.lax.scan' / 'self._lock' for Name/Attribute chains;
    None for anything else (calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node) -> Optional[str]:
    """The value of a string-literal AST node, else None."""
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def iter_package_files(root: Path = PACKAGE_ROOT) -> list[Path]:
    """Every package source file except lint/ itself (the linter's own regex
    literals and rule tables would self-match)."""
    lint_dir = root / "lint"
    return sorted(p for p in root.rglob("*.py")
                  if "__pycache__" not in p.parts
                  and lint_dir not in p.parents)


def default_checkers() -> list:
    from igloo_tpu.lint.cache_key import CacheKeyChecker
    from igloo_tpu.lint.env_knobs import EnvKnobsChecker
    from igloo_tpu.lint.event_names import EventNamesChecker
    from igloo_tpu.lint.flight_actions import FlightActionsChecker
    from igloo_tpu.lint.jit_key import JitKeyChecker
    from igloo_tpu.lint.lock_discipline import LockDisciplineChecker
    from igloo_tpu.lint.metric_names import MetricNamesChecker
    from igloo_tpu.lint.pallas_dispatch import PallasDispatchChecker
    from igloo_tpu.lint.rpc_policy import RpcPolicyChecker
    from igloo_tpu.lint.span_names import SpanNamesChecker
    from igloo_tpu.lint.sync_hazard import SyncHazardChecker
    from igloo_tpu.lint.thread_roles import (
        LockOrderChecker, ThreadRolesChecker,
    )
    from igloo_tpu.lint.wire_contract import WireContractChecker
    return [SyncHazardChecker(), CacheKeyChecker(), JitKeyChecker(),
            LockDisciplineChecker(), MetricNamesChecker(),
            SpanNamesChecker(), EventNamesChecker(), RpcPolicyChecker(),
            PallasDispatchChecker(), WireContractChecker(),
            FlightActionsChecker(), EnvKnobsChecker(),
            ThreadRolesChecker(), LockOrderChecker()]


def _raw_lint(modules: list, checkers: list,
              timings: Optional[dict] = None) -> tuple[list, list]:
    """Every finding, SUPPRESSIONS INCLUDED, plus warnings. Pass a dict as
    `timings` to get per-rule wall seconds back (keyed by rule name)."""
    import time
    findings: list[Finding] = []
    warnings: list[str] = []
    for c in checkers:
        t0 = time.perf_counter()
        for mod in modules:
            findings.extend(c.check(mod))
        findings.extend(c.finalize(modules))
        if timings is not None:
            timings[c.name] = time.perf_counter() - t0
        warnings.extend(getattr(c, "warnings", ()))
    return findings, warnings


def run_lint(paths: Optional[list] = None, checkers: Optional[list] = None,
             select: Optional[set] = None, root: Path = REPO_ROOT,
             timings: Optional[dict] = None) -> tuple[list, list]:
    """-> (findings, warnings). `paths` defaults to the igloo_tpu package
    (lint/ itself excluded); `select` restricts to a subset of rule names;
    a dict passed as `timings` comes back with per-rule wall seconds plus
    the shared parse time under the pseudo-rule "(parse)"."""
    import time
    if checkers is None:
        checkers = default_checkers()
    if select:
        checkers = [c for c in checkers if c.name in select]
    files = paths if paths is not None else iter_package_files()
    t0 = time.perf_counter()
    modules = [LintModule.parse(Path(p), root=root) for p in files]
    if timings is not None:
        timings["(parse)"] = time.perf_counter() - t0
    by_path = {m.relpath: m for m in modules}
    raw, warnings = _raw_lint(modules, checkers, timings=timings)
    findings = []
    for f in raw:
        m = by_path.get(f.path)
        if m is None or not m.allowed(f.rule, f.line):
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, warnings


def stale_allows(paths: Optional[list] = None,
                 checkers: Optional[list] = None,
                 root: Path = REPO_ROOT) -> list:
    """Report mode for ``--stale-allows``: every ``# lint: allow(<rule>)``
    comment that no longer suppresses any finding — the rule was fixed, the
    code moved, or the rule name was always wrong. Returns Findings (rule
    ``stale-allow``) so the CLI renders them like everything else. A stale
    allow is dead weight at best and false cover at worst: the next REAL
    finding on that line would be silently swallowed.

    Checkers with their own whitelists report staleness the same way: a
    checker may expose ``stale_entries()`` returning Findings (rule
    ``stale-entry``) for whitelist rows that no longer match anything —
    sync-hazard's ``CHOKE_POINTS``/``COLD_MODULES`` rows and
    lock-discipline's ``_GUARDED_BY`` locks/names — so every suppression
    surface shrinks monotonically through one report."""
    if checkers is None:
        checkers = default_checkers()
    files = paths if paths is not None else iter_package_files()
    modules = [LintModule.parse(Path(p), root=root) for p in files]
    raw, _warnings = _raw_lint(modules, checkers)
    hit: set = set()              # (relpath, line, rule) actually suppressed
    for f in raw:
        hit.add((f.path, f.line, f.rule))
    known_rules = {c.name for c in checkers}
    # on a PARTIAL run the whole-program rules gate their global pass off,
    # so an allow suppressing one of their findings would look stale here
    # and its removal would break the full run — skip those rules' allows
    pkg = {p.resolve().relative_to(Path(root).resolve()).as_posix()
           for p in iter_package_files()
           if Path(root).resolve() in p.resolve().parents}
    partial = not pkg or not pkg <= {m.relpath for m in modules}
    unjudgeable = {c.name for c in checkers
                   if partial and isinstance(c, TwoPassChecker)}
    out: list[Finding] = []
    for m in modules:
        # reconstruct each allow COMMENT from the text (mod.allows smears a
        # standalone comment over two lines; report the comment's own line)
        for i, line in enumerate(m.text.splitlines(), start=1):
            match = _ALLOW_RE.search(line)
            if not match:
                continue
            rules = {r.strip() for r in match.group(1).split(",")
                     if r.strip()}
            covered = {i, i + 1} if line.lstrip().startswith("#") else {i}
            for rule in sorted(rules):
                if rule not in known_rules:
                    out.append(Finding(
                        "stale-allow", m.relpath, i,
                        f"allow({rule}) names no known rule"))
                elif rule in unjudgeable:
                    continue  # global pass gated off: cannot judge here
                elif not any((m.relpath, ln, rule) in hit
                             for ln in covered):
                    out.append(Finding(
                        "stale-allow", m.relpath, i,
                        f"allow({rule}) suppresses nothing — remove it"))
    linted = {m.relpath for m in modules}
    for c in checkers:
        hook = getattr(c, "stale_entries", None)
        if hook is None or c.name in unjudgeable:
            continue   # partial run: a whole-program whitelist row may only
            #            LOOK unused because its users weren't linted
        out.extend(f for f in hook() if f.path in linted)
    out.sort(key=lambda f: (f.path, f.line))
    return out
