"""rpc-policy: every Flight connection must go through cluster/rpc.py.

The failure model (per-call deadlines, retry/backoff, retryable-vs-fatal
classification — docs/distributed.md#failure-model) lives in the
`cluster/rpc.py` helpers. A raw ``flight.connect(...)`` or
``FlightClient(...)`` anywhere else creates a connection with NO deadline:
one hung peer then wedges that code path forever, exactly the bug class the
RPC policy exists to kill. This checker flags both call forms (through any
import alias of ``pyarrow.flight``) in every package module except
``cluster/rpc.py`` itself — so no future code path can bypass the policy.

Scope is the package only: tests and examples legitimately use stock
clients (interop is the point of speaking Arrow Flight).
"""
from __future__ import annotations

import ast
from typing import Iterable

from igloo_tpu.lint import Checker, Finding, LintModule, dotted

RULE = "rpc-policy"

#: the ONE module allowed to open Flight connections
CONNECT_SITE = "igloo_tpu/cluster/rpc.py"

_MSG = ("direct Flight connection bypasses the RPC policy "
        "(deadlines/retry/backoff) — use the igloo_tpu.cluster.rpc helpers "
        "(connect / flight_action* / flight_stream_batches)")


def _flight_aliases(tree: ast.Module) -> tuple[set, set]:
    """(module aliases of pyarrow.flight, direct aliases of connect/
    FlightClient). Covers `import pyarrow.flight as X`, `import pyarrow
    as P` (usage `P.flight.connect`), `from pyarrow import flight as X`,
    `from pyarrow.flight import connect as Y, FlightClient as Z`."""
    mod_aliases: set = set()
    fn_aliases: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "pyarrow.flight":
                    # `import pyarrow.flight` binds `pyarrow`; usage is the
                    # dotted pyarrow.flight.connect form, handled below
                    mod_aliases.add(a.asname or "pyarrow.flight")
                elif a.name == "pyarrow":
                    # `import pyarrow as pa` reaches the flight submodule as
                    # `pa.flight` once ANY module in the process imported it
                    # (every cluster module does) — `pa.flight.connect(...)`
                    # is a live bypass, not a hypothetical
                    mod_aliases.add((a.asname or "pyarrow") + ".flight")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "pyarrow":
                for a in node.names:
                    if a.name == "flight":
                        mod_aliases.add(a.asname or "flight")
            elif node.module == "pyarrow.flight":
                for a in node.names:
                    if a.name in ("connect", "FlightClient"):
                        fn_aliases.add(a.asname or a.name)
    return mod_aliases, fn_aliases


class RpcPolicyChecker(Checker):
    name = RULE

    def check(self, mod: LintModule) -> Iterable[Finding]:
        if mod.relpath == CONNECT_SITE or \
                not mod.relpath.startswith("igloo_tpu/"):
            return
        mod_aliases, fn_aliases = _flight_aliases(mod.tree)
        if not mod_aliases and not fn_aliases:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            hit = name in fn_aliases
            if not hit and "." in name:
                base, leaf = name.rsplit(".", 1)
                hit = leaf in ("connect", "FlightClient") and \
                    base in mod_aliases
            if hit:
                yield Finding(RULE, mod.relpath, node.lineno,
                              f"`{name}(...)`: {_MSG}")
