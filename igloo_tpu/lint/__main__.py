"""`python -m igloo_tpu.lint` — run the hazard checkers over the package.

Exit 0 when clean, 1 on findings, 2 on usage errors. Pure AST: no engine
imports, no jax backend init, so the whole run takes a couple of seconds
(scripts/validate.sh and __graft_entry__.py's dryrun preamble gate on it).
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path


def main(argv=None) -> int:
    from igloo_tpu.lint import (
        default_checkers, iter_package_files, run_lint,
    )
    ap = argparse.ArgumentParser(prog="python -m igloo_tpu.lint")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the igloo_tpu package)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule names to run "
                         "(default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--stale-allows", action="store_true",
                    help="report `# lint: allow(<rule>)` comments and "
                         "checker whitelist rows that no longer suppress "
                         "any finding")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output: every finding "
                         "(suppressed ones included, with their allow "
                         "state) plus per-rule wall seconds")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress warnings and the OK summary")
    args = ap.parse_args(argv)

    checkers = default_checkers()
    if args.list_rules:
        for c in checkers:
            doc = (sys.modules[type(c).__module__].__doc__ or "").strip()
            head = doc.splitlines()[0] if doc else ""
            print(f"{c.name}: {head}")
        return 0

    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        known = {c.name for c in checkers}
        bad = select - known
        if bad:
            print(f"igloo-lint: unknown rule(s): {', '.join(sorted(bad))} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2

    paths = None
    if args.paths:
        paths = []
        for raw in args.paths:
            p = Path(raw).resolve()   # relative args must map into the repo
            if not p.exists():
                print(f"igloo-lint: no such file: {raw}", file=sys.stderr)
                return 2
            if p.is_dir():
                paths.extend(sorted(q for q in p.rglob("*.py")
                                    if "__pycache__" not in q.parts))
            else:
                paths.append(p)

    if args.as_json:
        import json

        from igloo_tpu.lint import LintModule, _raw_lint
        files = paths if paths is not None else iter_package_files()
        run = checkers if select is None else \
            [c for c in checkers if c.name in select]
        t0 = time.perf_counter()
        modules = [LintModule.parse(Path(p)) for p in files]
        parse_s = time.perf_counter() - t0
        by_path = {m.relpath: m for m in modules}
        timings: dict = {}
        raw, warnings = _raw_lint(modules, run, timings=timings)
        items, live = [], 0
        for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
            m = by_path.get(f.path)
            allowed = bool(m is not None and m.allowed(f.rule, f.line))
            live += 0 if allowed else 1
            items.append({"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message, "allowed": allowed})
        print(json.dumps({
            "files": len(modules),
            "wall_s": round(time.perf_counter() - t0, 3),
            "parse_s": round(parse_s, 3),
            "rules": {k: round(v, 3) for k, v in sorted(timings.items())},
            "findings": items,
            "warnings": list(warnings),
        }, indent=2))
        return 1 if live else 0

    if args.stale_allows:
        if select:
            print("igloo-lint: --stale-allows runs every rule (an allow "
                  "for an unselected rule would look stale); drop --select",
                  file=sys.stderr)
            return 2
        from igloo_tpu.lint import stale_allows
        stale = stale_allows(paths=paths, checkers=checkers)
        for f in stale:
            print(f.render())
        if stale:
            print(f"igloo-lint: {len(stale)} stale allow-comment"
                  f"{'s' if len(stale) != 1 else ''}", file=sys.stderr)
            return 1
        if not args.quiet:
            print("igloo-lint: no stale allows")
        return 0

    t0 = time.perf_counter()
    timings: dict = {}
    findings, warnings = run_lint(paths=paths, checkers=checkers,
                                  select=select, timings=timings)
    slowest = ", ".join(
        f"{k} {v:.2f}s" for k, v in
        sorted(((k, v) for k, v in timings.items() if k != "(parse)"),
               key=lambda kv: -kv[1])[:3])
    per_rule = f"parse {timings.get('(parse)', 0.0):.2f}s; " \
               f"slowest: {slowest}" if slowest else ""
    if not args.quiet:
        for w in warnings:
            print(f"warning: {w}", file=sys.stderr)
    if findings:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"igloo-lint: {n} finding{'s' if n != 1 else ''} "
              f"({time.perf_counter() - t0:.1f}s; {per_rule})",
              file=sys.stderr)
        return 1
    if not args.quiet:
        nfiles = len(paths) if paths else len(iter_package_files())
        print(f"igloo-lint: OK ({nfiles} files, "
              f"{time.perf_counter() - t0:.1f}s; {per_rule})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
