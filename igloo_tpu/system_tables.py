"""SQL-queryable telemetry: the `system.*` tables — `system.metrics`,
`system.query_log`, `system.query_traces`, and the watchtower trio
`system.metrics_history` / `system.slow_queries` / `system.cluster_events`.

All are ordinary TableProviders registered in every QueryEngine's catalog
under the `system.` namespace (Catalog.register_system — resolvable by the
binder, hidden from SHOW TABLES), so `SELECT * FROM system.metrics` runs
through the normal parse -> bind -> optimize -> execute path like any other
query. Their snapshot token is the metrics registry's mutation version, so
the engine's scan/result caches invalidate exactly when telemetry changed —
a repeated SELECT always sees live numbers.

Schemas are documented in docs/observability.md; changing them is a
documented-contract change, not a refactor.
"""
from __future__ import annotations

import json
from typing import Optional

import pyarrow as pa

from igloo_tpu.cluster import events
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.utils import flight_recorder, stats, timeseries, tracing, watch


class _SystemTable:
    """Shared provider shell: in-memory snapshot tables, snapshot-versioned
    by the metrics registry so caches never serve stale telemetry."""

    # row order within one snapshot is deterministic, but the column-granular
    # scan cache must not stitch columns from DIFFERENT snapshots into one
    # batch — the whole-batch path (stable_row_order=False) reads atomically
    stable_row_order = False

    _arrow_schema: pa.Schema = None  # set by subclass

    def __deepcopy__(self, memo):
        return self

    def schema(self):
        return schema_from_arrow(self._arrow_schema)

    def snapshot(self) -> int:
        return tracing.REGISTRY.version()

    def _build(self) -> pa.Table:
        raise NotImplementedError

    def read(self, projection: Optional[list] = None,
             filters: Optional[list] = None) -> pa.Table:
        t = self._build()
        if projection is not None:
            t = t.select(projection)
        return t

    def num_partitions(self) -> int:
        return 1

    def read_partition(self, index: int, projection=None, filters=None):
        return self.read(projection=projection, filters=filters)

    def estimated_bytes(self) -> int:
        # tiny by construction; a concrete size keeps the host-route sizing
        # path working when the default backend is an accelerator
        return 1 << 16


class MetricsTable(_SystemTable):
    """`system.metrics`: one row per counter and gauge, four per histogram
    (count/sum/min/max), straight out of the process registry."""

    _arrow_schema = pa.schema([
        pa.field("name", pa.string(), False),
        pa.field("kind", pa.string(), False),
        pa.field("value", pa.float64(), False),
    ])

    def _build(self) -> pa.Table:
        names: list = []
        kinds: list = []
        values: list = []
        for name, v in sorted(tracing.counters().items()):
            names.append(name)
            kinds.append("counter")
            values.append(float(v))
        for name, h in sorted(tracing.histograms().items()):
            for part in ("count", "sum", "min", "max"):
                names.append(name)
                kinds.append(f"hist_{part}")
                values.append(float(h[part]))
        for name, v in sorted(tracing.gauges().items()):
            names.append(name)
            kinds.append("gauge")
            values.append(float(v))
        return pa.Table.from_arrays(
            [pa.array(names, type=pa.string()),
             pa.array(kinds, type=pa.string()),
             pa.array(values, type=pa.float64())],
            schema=self._arrow_schema)


class QueryLogTable(_SystemTable):
    """`system.query_log`: the ring of recent per-query stats (most recent
    last). rows = -1 marks a query whose row count was never observed."""

    _arrow_schema = pa.schema([
        pa.field("qid", pa.int64(), False),
        pa.field("ts", pa.float64(), False),
        pa.field("sql", pa.string(), False),
        pa.field("tier", pa.string(), False),
        pa.field("rows", pa.int64(), False),
        pa.field("elapsed_s", pa.float64(), False),
        pa.field("compile_s", pa.float64(), False),
        pa.field("execute_s", pa.float64(), False),
        pa.field("h2d_bytes", pa.int64(), False),
        pa.field("d2h_bytes", pa.int64(), False),
        pa.field("operators", pa.int64(), False),
        pa.field("grace_partitions", pa.int64(), False),
        pa.field("jit_misses", pa.int64(), False),
        pa.field("cache_hits", pa.int64(), False),
        pa.field("status", pa.string(), False),
        # serving-path columns (coordinator front door, docs/serving.md):
        # admission-queue wait, priority tier, and demotion count (0 =
        # executed at its planned tier)
        pa.field("queue_wait_s", pa.float64(), False),
        pa.field("priority", pa.int64(), False),
        pa.field("demoted", pa.int64(), False),
        # flight-recorder join key: logs, metrics, and the stitched trace
        # (system.query_traces) correlate on this one id ("" = recorder off)
        pa.field("trace_id", pa.string(), False),
    ])

    def _build(self) -> pa.Table:
        recs = [qs.to_record() for qs in stats.query_log()]
        cols = {f.name: [r[f.name] for r in recs]
                for f in self._arrow_schema}
        return pa.Table.from_arrays(
            [pa.array(cols[f.name], type=f.type) for f in self._arrow_schema],
            schema=self._arrow_schema)


class QueryTracesTable(_SystemTable):
    """`system.query_traces`: one row per SPAN of every ring-resident query
    trace (utils/flight_recorder.py), most recent trace last. Joins with
    system.query_log on `trace_id`; `parent_id` is '' for root spans; `args`
    is the span's attributes as a JSON string. The publish path bumps the
    metrics-registry version, so scans always see live traces."""

    _arrow_schema = pa.schema([
        pa.field("trace_id", pa.string(), False),
        pa.field("qid", pa.string(), False),
        pa.field("span_id", pa.string(), False),
        pa.field("parent_id", pa.string(), False),
        pa.field("name", pa.string(), False),
        pa.field("proc", pa.string(), False),
        pa.field("t0", pa.float64(), False),
        pa.field("dur_s", pa.float64(), False),
        pa.field("args", pa.string(), False),
    ])

    def _build(self) -> pa.Table:
        cols: dict = {f.name: [] for f in self._arrow_schema}
        for rec in flight_recorder.records():
            for s in rec.get("spans", ()):
                cols["trace_id"].append(rec.get("trace_id", ""))
                cols["qid"].append(str(rec.get("qid", "")))
                cols["span_id"].append(str(s.get("id", "")))
                cols["parent_id"].append(str(s.get("parent") or ""))
                cols["name"].append(str(s.get("name", "")))
                cols["proc"].append(str(s.get("proc", "")))
                cols["t0"].append(float(s.get("t0", 0.0)))
                cols["dur_s"].append(
                    round(max(float(s.get("t1", 0.0)) -
                              float(s.get("t0", 0.0)), 0.0), 7))
                cols["args"].append(json.dumps(s.get("args") or {},
                                               default=str))
        return pa.Table.from_arrays(
            [pa.array(cols[f.name], type=f.type) for f in self._arrow_schema],
            schema=self._arrow_schema)


class MetricsHistoryTable(_SystemTable):
    """`system.metrics_history`: the watchtower sampler ring
    (utils/timeseries.py) flattened to one row per series per sample —
    `kind` is 'rate' (counter first-derivative, per second) or 'gauge'
    (instantaneous). `source` labels the sampling process; a coordinator's
    local table shows its own ring, the `metrics_history` Flight action
    aggregates the workers'. Empty until `IGLOO_WATCH` sampling runs."""

    _arrow_schema = pa.schema([
        pa.field("ts", pa.float64(), False),
        pa.field("source", pa.string(), False),
        pa.field("kind", pa.string(), False),
        pa.field("name", pa.string(), False),
        pa.field("value", pa.float64(), False),
    ])

    def _build(self) -> pa.Table:
        cols: dict = {f.name: [] for f in self._arrow_schema}
        for sample in timeseries.samples():
            for kind in ("rates", "gauges"):
                for name, v in sorted((sample.get(kind) or {}).items()):
                    cols["ts"].append(float(sample.get("ts", 0.0)))
                    cols["source"].append(str(sample.get("source", "")))
                    cols["kind"].append(kind[:-1])
                    cols["name"].append(name)
                    cols["value"].append(float(v))
        return pa.Table.from_arrays(
            [pa.array(cols[f.name], type=f.type) for f in self._arrow_schema],
            schema=self._arrow_schema)


class SlowQueriesTable(_SystemTable):
    """`system.slow_queries`: the watchtower's anomaly escalations
    (utils/watch.py) — queries that ran beyond IGLOO_WATCH_SLOW_FACTOR x
    their own fingerprint's P99. Joins system.query_log /
    system.query_traces on trace_id; the trace is pinned in the recorder,
    so the evidence outlives ring eviction."""

    _arrow_schema = pa.schema([
        pa.field("ts", pa.float64(), False),
        pa.field("qid", pa.string(), False),
        pa.field("trace_id", pa.string(), False),
        pa.field("fingerprint", pa.string(), False),
        pa.field("observed_s", pa.float64(), False),
        pa.field("baseline_p99_s", pa.float64(), False),
        pa.field("factor", pa.float64(), False),
        pa.field("observed_bytes", pa.float64(), False),
        pa.field("baseline_p99_bytes", pa.float64(), False),
        pa.field("dominant_phase", pa.string(), False),
        pa.field("tier", pa.string(), False),
        pa.field("sql", pa.string(), False),
    ])

    def _build(self) -> pa.Table:
        recs = watch.slow_queries()
        cols = {f.name: [r.get(f.name) for r in recs]
                for f in self._arrow_schema}
        return pa.Table.from_arrays(
            [pa.array(cols[f.name], type=f.type) for f in self._arrow_schema],
            schema=self._arrow_schema)


class ClusterEventsTable(_SystemTable):
    """`system.cluster_events`: the structured cluster journal
    (cluster/events.py), oldest first — worker membership churn, fragment
    recovery, admission sheds, demotions, cache traffic, plan flips, slow
    queries. `attrs` is the event's extra attributes as a JSON string."""

    _arrow_schema = pa.schema([
        pa.field("ts", pa.float64(), False),
        pa.field("kind", pa.string(), False),
        pa.field("severity", pa.string(), False),
        pa.field("worker", pa.string(), False),
        pa.field("qid", pa.string(), False),
        pa.field("trace_id", pa.string(), False),
        pa.field("attrs", pa.string(), False),
    ])

    def _build(self) -> pa.Table:
        cols: dict = {f.name: [] for f in self._arrow_schema}
        for ev in events.events():
            cols["ts"].append(float(ev.get("ts", 0.0)))
            cols["kind"].append(str(ev.get("kind", "")))
            cols["severity"].append(str(ev.get("severity", "info")))
            cols["worker"].append(str(ev.get("worker", "")))
            cols["qid"].append(str(ev.get("qid", "")))
            cols["trace_id"].append(str(ev.get("trace_id", "")))
            cols["attrs"].append(json.dumps(ev.get("attrs") or {},
                                            default=str))
        return pa.Table.from_arrays(
            [pa.array(cols[f.name], type=f.type) for f in self._arrow_schema],
            schema=self._arrow_schema)


def register_system_tables(catalog) -> None:
    """Install the system namespace into a catalog (engine construction)."""
    catalog.register_system("system.metrics", MetricsTable())
    catalog.register_system("system.query_log", QueryLogTable())
    catalog.register_system("system.query_traces", QueryTracesTable())
    catalog.register_system("system.metrics_history", MetricsHistoryTable())
    catalog.register_system("system.slow_queries", SlowQueriesTable())
    catalog.register_system("system.cluster_events", ClusterEventsTable())
