"""Persistent XLA compile cache: policy, telemetry, and cluster transfer.

Join-heavy TPC-H stages cost 12-31 s of cold XLA compile per query against
0.08-1.2 s warm (BENCH_r05) — for ad-hoc traffic, compilation IS the
latency. This module owns the three pieces that turn JAX's persistent
compilation cache into a *cluster-wide* one (docs/compile_cache.md):

- **policy** (`configure`): resolve the IGLOO_TPU_COMPILE_CACHE setting into
  a cache directory and install it into jax.config. Imported-time entry
  point for `igloo_tpu/__init__.py`; also applied by workers when the
  coordinator propagates its setting at registration.
- **telemetry** (`install_metrics`): hook jax.monitoring's
  `/jax/compilation_cache/*` events into the MetricsRegistry as
  `compile_cache.hit` / `compile_cache.miss` counters and a
  `compile_cache.saved_s` histogram. Listeners run on the compiling thread,
  so per-query `counter_delta()` collectors (EXPLAIN ANALYZE, the bench
  sweep) see exactly their own query's cache traffic.
- **transfer** (`entry_names` / `read_entry` / `write_entry`): the
  filename-keyed entry store the cluster actions move around — workers pull
  missing entries from the coordinator at registration (pre-warm) and push
  entries they compile back (cluster/coordinator.py, cluster/worker.py), so
  a query shape compiles once per *cluster*, ever.

Env knobs:
    IGLOO_TPU_COMPILE_CACHE      0/false/off disables; 1/true/on (or unset)
                                 uses the default directory; anything else
                                 is the directory to use.
    IGLOO_TPU_COMPILE_CACHE_MIN_SECS
                                 persist threshold override (default 1.0 —
                                 sub-second programs are cheaper to
                                 recompile than to ship; tests set 0).
"""
from __future__ import annotations

import base64
import os
import re
from typing import Optional

# entry filenames XLA writes (key-hash based) plus the sidecar files the
# cache keeps next to them; path separators and dotfiles are rejected so a
# malicious peer can never traverse out of the cache directory
_SAFE_NAME = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

# the adaptive-hint store (exec/hints.py) lives beside the XLA entries but
# has merge semantics of its own — never ship it as a cache entry
_EXCLUDE = {"nhints.json"}

# MUTABLE sidecar entries (the autotune tuning table): unlike XLA programs,
# same name does NOT imply same bytes, so write_entry routes them through a
# registered merge hook instead of first-writer-wins. Each value is
# (merge_fn(existing_bytes_or_None, incoming_bytes) -> bytes,
#  on_written_fn_or_None) — see exec/autotune.register_with_compile_cache.
_MERGE_HOOKS: dict = {}


def register_merge(name: str, merge_fn, on_written=None) -> None:
    """Register merge semantics for a mutable entry name (idempotent)."""
    _MERGE_HOOKS[name] = (merge_fn, on_written)


def merge_names() -> frozenset:
    """Entry names with registered merge semantics — the cluster transfer
    always re-pulls/re-pushes these (their content evolves), where immutable
    XLA entries ship at most once."""
    return frozenset(_MERGE_HOOKS)

# refuse to read/accept pathological blobs (largest observed TPU entries are
# tens of MB; anything bigger is a bug or an attack, not a cache entry)
MAX_ENTRY_BYTES = 256 << 20

# cluster transfer only lists entries stable for this long (see entry_names)
TRANSFER_MIN_AGE_S = 5.0

_disabled_reason: Optional[str] = None


def default_dir() -> str:
    """Alongside the package tree when writable (repo checkouts), else the
    user cache dir (pip installs into read-only site-packages)."""
    parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if os.access(parent, os.W_OK):
        return os.path.join(parent, ".xla_cache")
    return os.path.join(os.path.expanduser("~"), ".cache", "igloo_tpu_xla")


def resolve_setting(raw: Optional[str] = None) -> Optional[str]:
    """IGLOO_TPU_COMPILE_CACHE value -> cache directory (None = disabled)."""
    if raw is None:
        raw = os.environ.get("IGLOO_TPU_COMPILE_CACHE", "1")
    flag = raw.strip().lower()
    if flag in ("0", "false", "off", "no", ""):
        return None
    if flag in ("1", "true", "on", "yes"):
        return default_dir()
    return raw


def configure(raw: Optional[str] = None) -> Optional[str]:
    """Install the persistent-cache setting into jax.config. Returns the
    active directory (None when disabled). A failure (ancient jax without
    the knobs, unwritable config) downgrades to cold compiles only — but
    LOUDLY: one warning plus a `compile_cache.disabled` counter, so a
    silently-dead cache shows up in system.metrics instead of as a
    mysterious 30 s per query."""
    global _disabled_reason
    cache_dir = resolve_setting(raw)
    import jax
    if not cache_dir:
        # an explicit "off" must also UNDO a previously-installed directory:
        # workers adopting the coordinator's disabled setting at registration
        # would otherwise keep persisting to their import-time default
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass  # ancient jax without the knob was never persisting anyway
        return None
    try:
        # parse BEFORE touching jax.config so a failure can't leave the
        # cache half-enabled (dir installed, thresholds defaulted)
        min_secs = float(os.environ.get(
            "IGLOO_TPU_COMPILE_CACHE_MIN_SECS", "1.0"))
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as ex:
        try:  # roll back a partially-installed dir: disabled means DISABLED
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass
        if _disabled_reason is None:
            _disabled_reason = f"{type(ex).__name__}: {ex}"
            import warnings
            warnings.warn(
                "igloo_tpu: persistent XLA compile cache could NOT be "
                f"enabled ({_disabled_reason}); every process will pay cold "
                "compiles. Set IGLOO_TPU_COMPILE_CACHE=0 to silence.",
                RuntimeWarning, stacklevel=2)
            from igloo_tpu.utils import tracing
            tracing.counter("compile_cache.disabled")
        return None
    return cache_dir


def disabled_reason() -> Optional[str]:
    return _disabled_reason


def active_dir() -> Optional[str]:
    """The directory jax is currently configured to persist into."""
    import jax
    try:
        d = jax.config.jax_compilation_cache_dir
    except AttributeError:
        return None
    return d or None


# --- telemetry ---------------------------------------------------------------

_metrics_installed = False


def install_metrics() -> None:
    """Register jax.monitoring listeners mapping compilation-cache events to
    the engine's metrics registry. Idempotent; safe before any compile."""
    global _metrics_installed
    if _metrics_installed:
        return
    _metrics_installed = True

    from igloo_tpu.utils import tracing

    def on_event(event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            tracing.counter("compile_cache.hit")
        elif event == "/jax/compilation_cache/cache_misses":
            tracing.counter("compile_cache.miss")

    def on_duration(event: str, duration: float, **kw) -> None:
        if event == "/jax/compilation_cache/compile_time_saved_sec":
            # can be slightly negative on trivial programs (retrieval cost
            # exceeds the compile it replaced); record what was measured
            tracing.histogram("compile_cache.saved_s", duration)

    try:
        from jax import monitoring
        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:
        # jax without the monitoring API: the cache still works, only the
        # hit/miss telemetry is absent — never fail `import igloo_tpu` on it
        pass


# --- filename-keyed entry transfer ------------------------------------------


def entry_names(cache_dir: Optional[str] = None,
                min_age_s: float = 0.0) -> list:
    """Sorted filenames of the persistent-cache entries in `cache_dir`
    (default: the active directory). Only plain, safely-named files count —
    the hint store and anything unshippable is excluded. `min_age_s` skips
    entries modified more recently than that: XLA writes its cache files
    NON-atomically, so the cluster transfer must only list entries that have
    been stable for a few seconds (a truncated blob shipped once would pin
    itself cluster-wide — write_entry never overwrites)."""
    d = cache_dir if cache_dir is not None else active_dir()
    if not d or not os.path.isdir(d):
        return []
    import time
    cutoff = time.time() - min_age_s
    out = []
    for name in os.listdir(d):
        if name in _EXCLUDE or not _SAFE_NAME.match(name):
            continue
        p = os.path.join(d, name)
        try:
            st = os.stat(p)
        except OSError:
            continue
        if not os.path.isfile(p):
            continue
        # zero-byte stubs and unshippable oversizes never make the listing:
        # read_entry would refuse them anyway, so advertising them only
        # makes every worker pull an empty body
        if not 0 < st.st_size <= MAX_ENTRY_BYTES:
            continue
        if min_age_s and st.st_mtime > cutoff:
            continue
        out.append(name)
    return sorted(out)


def _entry_path(name: str, cache_dir: Optional[str]) -> Optional[str]:
    d = cache_dir if cache_dir is not None else active_dir()
    if not d or name in _EXCLUDE or not _SAFE_NAME.match(name):
        return None
    return os.path.join(d, name)


def entry_stat(name: str,
               cache_dir: Optional[str] = None) -> Optional[tuple]:
    """(size, mtime) of an entry file, or None — the change signature the
    cluster transfer uses to re-push mutable merge-named entries."""
    p = _entry_path(name, cache_dir)
    if p is None or not os.path.isfile(p):
        return None
    try:
        st = os.stat(p)
    except OSError:
        return None
    return (st.st_size, st.st_mtime)


def read_entry(name: str, cache_dir: Optional[str] = None) -> Optional[bytes]:
    """Entry bytes by filename, or None (unknown name, unsafe name, no
    cache). Oversized entries read as None rather than shipping gigabytes;
    so do empty files — a zero-byte entry is never a valid XLA cache blob,
    only the stub of an abandoned write."""
    p = _entry_path(name, cache_dir)
    if p is None or not os.path.isfile(p):
        return None
    if not 0 < os.path.getsize(p) <= MAX_ENTRY_BYTES:
        return None
    with open(p, "rb") as f:
        return f.read()


def write_entry(name: str, data: bytes,
                cache_dir: Optional[str] = None) -> bool:
    """Store an entry under `name` (atomic rename; concurrent writers of
    the same key write identical content, so last-wins is fine). Returns
    True when the entry is now present with this content. Unsafe names,
    empty payloads, and oversized payloads are rejected, never written.

    An existing file of the SAME size is kept (same key ⇒ same bytes); a
    SIZE MISMATCH is overwritten — it can only be an abandoned partial
    write from a killed process, and skipping it would pin the truncated
    blob cluster-wide with no repair path.

    Names with a registered merge hook (mutable sidecars, e.g. the autotune
    tuning table) skip the same-size shortcut entirely: the hook merges the
    incoming bytes with the existing file and its result is what lands."""
    p = _entry_path(name, cache_dir)
    if p is None or not data or len(data) > MAX_ENTRY_BYTES:
        return False
    hook = _MERGE_HOOKS.get(name)
    if hook is not None:
        try:
            existing = read_entry(name, cache_dir)
            data = hook[0](existing, data)
        except Exception:
            return False
        if not data or len(data) > MAX_ENTRY_BYTES:
            return False
    else:
        try:
            if os.path.getsize(p) == len(data):
                return True
        except OSError:
            pass
    import tempfile
    try:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p))
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, p)
    except OSError:
        return False
    if hook is not None and hook[1] is not None:
        try:
            hook[1]()
        except Exception:
            pass
    return True


def encode_entry(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def decode_entry(data: str) -> bytes:
    return base64.b64decode(data.encode("ascii"))
