"""Catalog: table name -> provider.

Counterpart of the reference's `MemoryCatalog` (crates/common/src/catalog.rs:5-27,
a name -> Arc<dyn TableProvider> map) — but the provider interface is ours: providers
expose an engine `Schema` and produce pyarrow data host-side with projection and
filter pushdown; the executor moves it into HBM (SURVEY.md §2 #9: "catalog service:
table name -> {format, location, schema, partitioning, device placement}").
"""
from __future__ import annotations

import threading
from typing import Optional, Protocol, runtime_checkable

import pyarrow as pa

from igloo_tpu.errors import CatalogError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.types import Schema


@runtime_checkable
class TableProvider(Protocol):
    """A registered table. `read` returns a pyarrow Table containing (at least) the
    requested columns; `filters` are bound Expr the provider MAY pre-apply
    (best-effort pruning — the engine always re-applies them exactly)."""

    def schema(self) -> Schema: ...

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table: ...

    def num_partitions(self) -> int:
        """How many independently readable chunks exist (files / row groups); the
        distributed planner uses this for scan placement."""
        ...

    def read_partition(self, index: int, projection: Optional[list[str]] = None,
                       filters: Optional[list] = None) -> pa.Table: ...


class MemTable:
    """In-memory table over a pyarrow Table (reference uses DataFusion MemTable for
    the CLI's sample `users` table, crates/igloo/src/main.rs:59-77)."""

    # repeated reads return identical row order (column-granular scan cache)
    stable_row_order = True

    def __deepcopy__(self, memo):
        # providers are shared by plan/expression copies (see copy_plan)
        return self

    def __init__(self, table: pa.Table, partitions: int = 1):
        self._table = table
        self._schema = schema_from_arrow(table.schema)
        self._partitions = max(1, min(partitions, max(table.num_rows, 1)))

    @staticmethod
    def from_pydict(d: dict, schema: Optional[pa.Schema] = None) -> "MemTable":
        return MemTable(pa.table(d, schema=schema))

    def schema(self) -> Schema:
        return self._schema

    def read(self, projection=None, filters=None) -> pa.Table:
        t = self._table
        if projection is not None:
            t = t.select(projection)
        return t

    def num_partitions(self) -> int:
        return self._partitions

    def read_partition(self, index: int, projection=None, filters=None) -> pa.Table:
        n = self._table.num_rows
        per = (n + self._partitions - 1) // self._partitions if n else 0
        t = self._table.slice(index * per, per)
        if projection is not None:
            t = t.select(projection)
        return t

    def estimated_bytes(self) -> int:
        return self._table.nbytes


class Catalog:
    """Thread-safe name -> provider registry (the coordinator serves one per
    cluster; the reference wraps a plain HashMap, catalog.rs:10-27)."""

    def __init__(self):
        self._tables: dict[str, TableProvider] = {}
        # the `system.` namespace (system.metrics / system.query_log,
        # igloo_tpu/system_tables.py): resolvable by the binder like any
        # table but hidden from SHOW TABLES / list_flights, and shielded
        # from register/deregister so user DDL cannot shadow or drop it
        self._system: dict[str, TableProvider] = {}
        self._lock = threading.RLock()

    def register(self, name: str, provider: TableProvider) -> None:
        key = name.lower()
        if key.startswith("system.") or key in ("system",):
            # the system namespace is read-only by contract: registering a
            # user table over it would shadow live telemetry silently
            raise CatalogError(f"cannot register table in the reserved "
                               f"system namespace: {name}")
        with self._lock:
            self._tables[key] = provider

    def register_system(self, name: str, provider: TableProvider) -> None:
        with self._lock:
            self._system[name.lower()] = provider

    def deregister(self, name: str) -> None:
        with self._lock:
            self._tables.pop(name.lower(), None)

    def get(self, name: str) -> TableProvider:
        with self._lock:
            p = self._tables.get(name.lower())
            if p is None:
                p = self._system.get(name.lower())
        if p is None:
            raise CatalogError(f"table not found: {name}")
        return p

    def maybe_get(self, name: str) -> Optional[TableProvider]:
        with self._lock:
            return self._tables.get(name.lower()) or \
                self._system.get(name.lower())

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._tables)

    def system_names(self) -> list[str]:
        with self._lock:
            return sorted(self._system)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name.lower() in self._tables
