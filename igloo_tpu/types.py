"""Logical type system for igloo-tpu.

The reference engine uses Arrow's type system throughout (RecordBatch is the universal
data representation — see reference crates/engine/src/physical_plan.rs:10-17). We keep
Arrow at the host edges but narrow the *device* representation to types the TPU handles
natively:

- integers      -> int32 / int64 lanes
- floats        -> float32 / float64 lanes (TPC-H decimals are computed as float64)
- bool          -> bool lanes
- date32        -> int32 days-since-epoch
- timestamp     -> int64 micros
- string        -> dictionary-encoded int32 ids; the dictionary itself stays host-side
                   (strings never touch HBM — string functions run over the small
                   dictionary on host, comparisons become id-set membership on device)

Every column carries an optional validity (null) mask as a separate bool lane.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class TypeId(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"       # device repr: int32 dictionary ids
    DATE32 = "date32"       # device repr: int32 days since epoch
    TIMESTAMP = "timestamp"  # device repr: int64 microseconds since epoch
    NULL = "null"


@dataclass(frozen=True)
class DataType:
    id: TypeId

    @property
    def is_numeric(self) -> bool:
        return self.id in (TypeId.INT32, TypeId.INT64, TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_integer(self) -> bool:
        return self.id in (TypeId.INT32, TypeId.INT64)

    @property
    def is_float(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_string(self) -> bool:
        return self.id == TypeId.STRING

    @property
    def is_temporal(self) -> bool:
        return self.id in (TypeId.DATE32, TypeId.TIMESTAMP)

    def device_dtype(self) -> np.dtype:
        """numpy dtype of the on-device lane for this logical type."""
        return np.dtype(_DEVICE_DTYPE[self.id])

    # immutable singletons: keep identity across copy/deepcopy so `is` checks and
    # expression deep-copies in the binder stay cheap and correct
    def __copy__(self) -> "DataType":
        return self

    def __deepcopy__(self, memo) -> "DataType":
        return self

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.id.value


BOOL = DataType(TypeId.BOOL)
INT32 = DataType(TypeId.INT32)
INT64 = DataType(TypeId.INT64)
FLOAT32 = DataType(TypeId.FLOAT32)
FLOAT64 = DataType(TypeId.FLOAT64)
STRING = DataType(TypeId.STRING)
DATE32 = DataType(TypeId.DATE32)
TIMESTAMP = DataType(TypeId.TIMESTAMP)
NULL = DataType(TypeId.NULL)

_DEVICE_DTYPE = {
    TypeId.BOOL: "bool",
    TypeId.INT32: "int32",
    TypeId.INT64: "int64",
    TypeId.FLOAT32: "float32",
    TypeId.FLOAT64: "float64",
    TypeId.STRING: "int32",
    TypeId.DATE32: "int32",
    TypeId.TIMESTAMP: "int64",
    TypeId.NULL: "int32",
}

_NUMERIC_RANK = {TypeId.BOOL: 0, TypeId.INT32: 1, TypeId.INT64: 2, TypeId.FLOAT32: 3, TypeId.FLOAT64: 4}


def common_type(a: DataType, b: DataType) -> DataType:
    """Binary-op result type (SQL-ish numeric promotion)."""
    if a == b:
        return a
    if a.id == TypeId.NULL:
        return b
    if b.id == TypeId.NULL:
        return a
    if a.id in _NUMERIC_RANK and b.id in _NUMERIC_RANK:
        ra, rb = _NUMERIC_RANK[a.id], _NUMERIC_RANK[b.id]
        # int64 (+) float32 -> float64 to avoid precision loss
        if {a.id, b.id} == {TypeId.INT64, TypeId.FLOAT32}:
            return FLOAT64
        return a if ra >= rb else b
    if a.is_temporal and b.is_temporal:
        return TIMESTAMP
    if (a.id == TypeId.DATE32 and b.is_integer) or (b.id == TypeId.DATE32 and a.is_integer):
        return DATE32  # date +/- int days
    raise TypeError(f"no common type for {a} and {b}")


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True


class Schema:
    """Ordered, named, typed columns. Mirrors Arrow's Schema but engine-owned."""

    def __init__(self, fields: list[Field]):
        # a tuple, not a list: Schema rides in jit static aux data and keys
        # compile caches, so its hash must not be able to drift after the
        # first use (igloo-lint cache-key: hash over mutable state)
        self.fields = tuple(fields)
        self._index: dict[str, int] = {}
        for i, f in enumerate(self.fields):
            # last-wins on duplicate names (SQL allows dup output names)
            self._index[f.name] = i
        self._hash = hash(self.fields)

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        return self.fields[self._index[name]]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self) -> int:
        # Schema rides in jit static aux data (pytree aux of DeviceBatch)
        return self._hash

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "Schema(" + ", ".join(f"{f.name}: {f.dtype}" for f in self.fields) + ")"
