"""Unified error hierarchy (reference: crates/common/src/error.rs:6-21 — a thiserror
enum with Unknown + SqlParser variants). Ours is richer because the engine surface is
bigger; everything raised to users derives from IglooError so `QueryEngine.execute`
reports failures instead of panicking (closes reference gap G9, engine/src/lib.rs:55-56
uses `.expect`)."""
from __future__ import annotations


class IglooError(Exception):
    """Base for all engine errors."""


class CatalogError(IglooError):
    """Unknown table / registration conflicts."""


class SqlParseError(IglooError):
    """SQL lex/parse failures (reference: Error::SqlParser, error.rs:14-16)."""


class PlanError(IglooError):
    """Binder/planner failures: unknown column, ambiguous name, type mismatch."""


class ExecError(IglooError):
    """Runtime execution failures."""


class ConnectorError(IglooError):
    """Source-format failures (Parquet/CSV/Iceberg/JDBC-ish)."""


class TransportError(IglooError):
    """RPC / serialization failures in the distributed tier."""


class DeadlineExceededError(IglooError):
    """A query (or RPC) exhausted its deadline budget before completing."""


class QueryCancelledError(IglooError):
    """Query cancelled via its cancellation token / `cancel_query`."""


class NotSupportedError(IglooError):
    """Feature declared by SQL but outside the engine's dialect."""
