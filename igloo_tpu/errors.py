"""Unified error hierarchy (reference: crates/common/src/error.rs:6-21 — a thiserror
enum with Unknown + SqlParser variants). Ours is richer because the engine surface is
bigger; everything raised to users derives from IglooError so `QueryEngine.execute`
reports failures instead of panicking (closes reference gap G9, engine/src/lib.rs:55-56
uses `.expect`)."""
from __future__ import annotations


class IglooError(Exception):
    """Base for all engine errors."""


class CatalogError(IglooError):
    """Unknown table / registration conflicts."""


class SqlParseError(IglooError):
    """SQL lex/parse failures (reference: Error::SqlParser, error.rs:14-16)."""


class PlanError(IglooError):
    """Binder/planner failures: unknown column, ambiguous name, type mismatch."""


class ExecError(IglooError):
    """Runtime execution failures."""


class ConnectorError(IglooError):
    """Source-format failures (Parquet/CSV/Iceberg/JDBC-ish)."""


class StorageError(ConnectorError):
    """Object-store I/O failure (igloo_tpu/storage): a read/head/list/put
    that stayed failed after its StoragePolicy retry budget, or was
    classified fatal outright. Subclasses ConnectorError so every existing
    source-failure handler treats it as one."""


class SnapshotChanged(StorageError):
    """The source mutated under a running query: a pinned etag/version no
    longer matches what the store serves (or a pinned file vanished). The
    engine converts this into ONE bounded re-plan at the new snapshot
    (counter `storage.snapshot_retry`) instead of returning a torn result."""

    def __init__(self, msg: str, table: str = "", key: str = ""):
        super().__init__(msg)
        self.table = table
        self.key = key


class CorruptObjectError(StorageError):
    """Checksum/parse failure pinned to one object (and row group): fatal
    for that object, negative-cached by the quarantine registry so the
    engine never re-reads known-bad bytes (counter `storage.corrupt`)."""

    def __init__(self, msg: str, key: str = "", row_group: int = -1):
        super().__init__(msg)
        self.key = key
        self.row_group = row_group


class TransportError(IglooError):
    """RPC / serialization failures in the distributed tier."""


class DeadlineExceededError(IglooError):
    """A query (or RPC) exhausted its deadline budget before completing."""


class QueryCancelledError(IglooError):
    """Query cancelled via its cancellation token / `cancel_query`."""


class NotSupportedError(IglooError):
    """Feature declared by SQL but outside the engine's dialect."""
