"""Shared Flight RPC plumbing for the cluster package.

SECURITY MODEL: the cluster transports are designed for a TRUSTED network.
Control actions (register_table, do_put) accept provider specs naming
filesystem paths, so anyone who can reach the port can read files the process
can. The defaults bind loopback only; before binding a non-loopback host set
IGLOO_TPU_AUTH_TOKEN on every process (coordinator, workers, clients) — all
Flight calls then carry the token in an `x-igloo-token` header and servers
reject calls without it. The token is a shared secret over plaintext gRPC:
it gates access, it is not wire encryption; use a private network or mTLS
termination in front for anything stronger.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import pyarrow.flight as flight

AUTH_TOKEN_ENV = "IGLOO_TPU_AUTH_TOKEN"
_HEADER = "x-igloo-token"


def auth_token() -> Optional[str]:
    return os.environ.get(AUTH_TOKEN_ENV) or None


def call_options() -> Optional[flight.FlightCallOptions]:
    """FlightCallOptions carrying the shared token (None when unset)."""
    tok = auth_token()
    if tok is None:
        return None
    return flight.FlightCallOptions(
        headers=[(_HEADER.encode(), tok.encode())])


class TokenMiddlewareFactory(flight.ServerMiddlewareFactory):
    """Rejects any call not presenting the shared token."""

    def __init__(self, token: str):
        self._token = token

    def start_call(self, info, headers):
        vals = []
        for k, vs in headers.items():
            key = k.decode() if isinstance(k, bytes) else k
            if key.lower() == _HEADER:
                vals.extend(v.decode() if isinstance(v, bytes) else v
                            for v in vs)
        if self._token not in vals:
            raise flight.FlightUnauthenticatedError(
                "missing or invalid x-igloo-token (set IGLOO_TPU_AUTH_TOKEN)")
        return None


def server_middleware() -> Optional[dict]:
    """Middleware dict for FlightServerBase when a token is configured."""
    tok = auth_token()
    if tok is None:
        return None
    return {"auth": TokenMiddlewareFactory(tok)}


def warn_if_open_bind(host: str, what: str) -> None:
    if host.strip("[]") not in ("127.0.0.1", "localhost", "::1") \
            and auth_token() is None:
        import sys
        print(f"WARNING: {what} binding non-loopback host {host} with NO "
              f"auth token; anyone reaching the port can register tables "
              f"over arbitrary local paths. Set {AUTH_TOKEN_ENV}.",
              file=sys.stderr)


def normalize(addr: str) -> str:
    return addr if "://" in addr else f"grpc+tcp://{addr}"


def flight_action(addr: str, name: str, payload: Optional[dict] = None) -> dict:
    """One-shot action RPC: connect, act, close. Returns the decoded first
    result (or {})."""
    client = flight.connect(normalize(addr))
    try:
        body = json.dumps(payload).encode() if payload is not None else b""
        results = list(client.do_action(flight.Action(name, body),
                                        call_options()))
    finally:
        client.close()
    return json.loads(results[0].body.to_pybytes()) if results else {}


def flight_get_table(addr: str, ticket: str):
    """One-shot do_get RPC returning the full Arrow table."""
    client = flight.connect(normalize(addr))
    try:
        return client.do_get(flight.Ticket(ticket.encode()),
                             call_options()).read_all()
    finally:
        client.close()
