"""Shared Flight RPC plumbing for the cluster package.

SECURITY MODEL: the cluster transports are designed for a TRUSTED network.
Control actions (register_table, do_put) accept provider specs naming
filesystem paths, so anyone who can reach the port can read files the process
can. The defaults bind loopback only; before binding a non-loopback host set
IGLOO_TPU_AUTH_TOKEN on every process (coordinator, workers, clients) — all
Flight calls then carry the token in an `x-igloo-token` header and servers
reject calls without it. The token is a shared secret over plaintext gRPC:
it gates access, it is not wire encryption; use a private network or mTLS
termination in front for anything stronger.

FAILURE MODEL: every helper here runs under an `RpcPolicy` — per-call
deadline, bounded connect probe for streams, retry with exponential backoff +
jitter — so a hung peer (TCP accepts, never answers) costs a bounded timeout
instead of a wedged thread, and transient unavailability is retried instead
of failing the query. Classification: `FlightUnavailableError` and timeouts
are RETRYABLE (the peer may come back, or the coordinator will re-dispatch);
`FlightUnauthenticatedError` and `FlightServerError` (a server-side
application error) are FATAL — retrying a query that *failed* would mask
bugs as flakes. Knobs: `IGLOO_RPC_*` env vars or `[rpc]` config
(docs/distributed.md#failure-model). This module is the package's ONLY
Flight connection site — the igloo-lint `rpc-policy` checker flags
`flight.connect` anywhere else, so no code path can bypass the deadlines.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.cluster import faults
from igloo_tpu.errors import DeadlineExceededError
from igloo_tpu.utils import flight_recorder, tracing

AUTH_TOKEN_ENV = "IGLOO_TPU_AUTH_TOKEN"
_HEADER = "x-igloo-token"


def auth_token() -> Optional[str]:
    return os.environ.get(AUTH_TOKEN_ENV) or None


def call_options(timeout_s: Optional[float] = None
                 ) -> Optional[flight.FlightCallOptions]:
    """FlightCallOptions carrying the shared token and/or a gRPC deadline
    (None when neither applies)."""
    kw: dict = {}
    tok = auth_token()
    if tok is not None:
        kw["headers"] = [(_HEADER.encode(), tok.encode())]
    if timeout_s is not None:
        # a deadline already in the past must still produce a DEADLINE_
        # EXCEEDED status, not an invalid-argument error
        kw["timeout"] = max(float(timeout_s), 0.001)
    return flight.FlightCallOptions(**kw) if kw else None


class TokenMiddlewareFactory(flight.ServerMiddlewareFactory):
    """Rejects any call not presenting the shared token."""

    def __init__(self, token: str):
        self._token = token

    def start_call(self, info, headers):
        if info.method == flight.FlightMethod.HANDSHAKE:
            return None  # the auth handler itself validates the handshake
        vals = []
        for k, vs in headers.items():
            key = k.decode() if isinstance(k, bytes) else k
            # handshake-authenticated clients (TokenServerAuthHandler) carry
            # the session token as gRPC call credentials: pyarrow surfaces
            # them as auth-token-bin (or authorization: Bearer <tok>)
            if key.lower() not in (_HEADER, "authorization",
                                   "auth-token-bin"):
                continue
            for v in vs:
                v = v.decode() if isinstance(v, bytes) else v
                if key.lower() == "authorization":
                    v = v.split(" ", 1)[-1]
                vals.append(v)
        if self._token not in vals:
            raise flight.FlightUnauthenticatedError(
                "missing or invalid x-igloo-token (set IGLOO_TPU_AUTH_TOKEN)")
        return None


def server_middleware() -> Optional[dict]:
    """Middleware dict for FlightServerBase when a token is configured."""
    tok = auth_token()
    if tok is None:
        return None
    return {"auth": TokenMiddlewareFactory(tok)}


class TokenServerAuthHandler(flight.ServerAuthHandler):
    """Handshake (reference proto flight.proto:42) wired to the shared
    token: the client's handshake payload must equal the token; the returned
    session token is the same secret (carried by pyarrow on later calls as
    the authorization header). The per-call x-igloo-token middleware stays
    the primary gate — handshake is the protocol-parity path for stock
    clients that use `FlightClient.authenticate`."""

    def __init__(self, token: str):
        super().__init__()
        self._token = token.encode()

    def authenticate(self, outgoing, incoming):
        buf = incoming.read()
        if buf != self._token:
            raise flight.FlightUnauthenticatedError("bad handshake token")
        outgoing.write(self._token)

    def is_valid(self, token):
        if token == self._token:
            return b"igloo"
        # middleware-authenticated calls present no handshake session token
        return b""


class TokenClientAuthHandler(flight.ClientAuthHandler):
    def __init__(self, token: str):
        super().__init__()
        self._token = token.encode()

    def authenticate(self, outgoing, incoming):
        outgoing.write(self._token)
        self._session = incoming.read()

    def get_token(self):
        return self._session


def server_auth_handler() -> Optional[flight.ServerAuthHandler]:
    tok = auth_token()
    return TokenServerAuthHandler(tok) if tok is not None else None


def warn_if_open_bind(host: str, what: str) -> None:
    if host.strip("[]") not in ("127.0.0.1", "localhost", "::1") \
            and auth_token() is None:
        import sys
        print(f"WARNING: {what} binding non-loopback host {host} with NO "
              f"auth token; anyone reaching the port can register tables "
              f"over arbitrary local paths. Set {AUTH_TOKEN_ENV}.",
              file=sys.stderr)


def normalize(addr: str) -> str:
    return addr if "://" in addr else f"grpc+tcp://{addr}"


# --- RPC policy: deadlines, retry, error classification ----------------------


@dataclass(frozen=True)
class RpcPolicy:
    """Failure budget for one RPC: how long each attempt may take, how many
    retryable failures to absorb, and how to back off between them.
    Immutable — derive variants with `with_(...)`."""
    connect_timeout_s: float = 5.0     # stream-open liveness probe bound
    call_timeout_s: float = 120.0      # per-attempt gRPC deadline (actions)
    stream_timeout_s: float = 600.0    # whole-stream gRPC deadline (do_get)
    retries: int = 2                   # retryable-failure budget (attempts-1)
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_jitter: float = 0.25       # +-fraction of the backoff step

    def with_(self, **kw) -> "RpcPolicy":
        return dataclasses.replace(self, **kw)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry `attempt` (1-based): exponential, capped,
        jittered so a wave of retries against one recovering server spreads
        out instead of stampeding."""
        import random
        base = min(self.backoff_base_s * (2 ** (attempt - 1)),
                   self.backoff_max_s)
        if self.backoff_jitter <= 0:
            return base
        return base * (1.0 + random.uniform(-self.backoff_jitter,
                                            self.backoff_jitter))


_ENV_FIELDS = (("connect_timeout_s", "IGLOO_RPC_CONNECT_TIMEOUT_S"),
               ("call_timeout_s", "IGLOO_RPC_CALL_TIMEOUT_S"),
               ("stream_timeout_s", "IGLOO_RPC_STREAM_TIMEOUT_S"),
               ("retries", "IGLOO_RPC_RETRIES"),
               ("backoff_base_s", "IGLOO_RPC_BACKOFF_BASE_S"),
               ("backoff_max_s", "IGLOO_RPC_BACKOFF_MAX_S"),
               ("backoff_jitter", "IGLOO_RPC_BACKOFF_JITTER"))


def policy_from_env(base: Optional[RpcPolicy] = None) -> RpcPolicy:
    base = base or RpcPolicy()
    kw = {}
    for fld, env in _ENV_FIELDS:
        v = os.environ.get(env)
        if v:
            kw[fld] = int(v) if fld == "retries" else float(v)
    return base.with_(**kw) if kw else base


_default_policy: Optional[RpcPolicy] = None
# the process-wide policy cache is read by every RPC-issuing thread (worker
# heartbeat loops, coordinator dispatch pool, Flight handlers forwarding
# fragments) while config loading may install a policy concurrently — the
# lazy init below would otherwise race and hand two threads different
# policies built from a half-read environment
_policy_lock = threading.Lock()

_GUARDED_BY = {"_policy_lock": ("_default_policy",)}


def default_policy() -> RpcPolicy:
    global _default_policy
    with _policy_lock:
        if _default_policy is None:
            _default_policy = policy_from_env()
        return _default_policy


def set_default_policy(policy: Optional[RpcPolicy]) -> None:
    """Install a process-wide default (config loading); None re-reads env."""
    global _default_policy
    with _policy_lock:
        _default_policy = policy


def retryable(ex: BaseException) -> bool:
    """Retryable-vs-fatal error classification. Unavailable peers and
    deadline-exceeded attempts may succeed elsewhere or later; auth failures
    and server-side APPLICATION errors (the query itself failed) never will."""
    if isinstance(ex, (flight.FlightUnauthenticatedError,
                       flight.FlightServerError)):
        return False
    if isinstance(ex, (flight.FlightUnavailableError,
                       flight.FlightTimedOutError)):
        return True
    if isinstance(ex, flight.FlightError):
        return False  # internal / cancelled / unknown: do not mask
    return isinstance(ex, (ConnectionError, OSError))


def remaining_s(deadline: Optional[float]) -> Optional[float]:
    """Seconds left until an absolute `time.time()` deadline (None = none)."""
    return None if deadline is None else deadline - time.time()


def check_deadline(deadline: Optional[float], what: str) -> None:
    if deadline is not None and time.time() >= deadline:
        tracing.counter("rpc.deadline_exceeded")
        raise DeadlineExceededError(f"deadline exceeded before {what}")


def _effective_timeout(base: float, deadline: Optional[float]) -> float:
    """Per-attempt gRPC deadline: the policy bound, clamped to whatever is
    left of the caller's absolute deadline."""
    rem = remaining_s(deadline)
    return base if rem is None else max(min(base, rem), 0.001)


def connect(addr: str) -> flight.FlightClient:
    """The package's ONE Flight connection site (gRPC connects lazily; the
    per-call deadline in `call_options` bounds establishment + call). Every
    other module must come through here or the `flight_*` helpers — enforced
    by the igloo-lint `rpc-policy` checker."""
    return flight.connect(normalize(addr))


def _run_attempts(addr: str, what: str, fn, policy: Optional[RpcPolicy],
                  deadline: Optional[float], close_on_success: bool = True):
    """The ONE retry loop: connect per attempt, run `fn(client)`, classify-
    then-retry with backoff, never past the caller's deadline. With
    `close_on_success=False` the client survives a successful attempt (the
    stream-open path — the connection must outlive the call); every failure
    path still closes it."""
    policy = policy or default_policy()
    attempt = 0
    # timeline: inside an active flight-recorder scope each ATTEMPT is a
    # span (attrs carry the retry ordinal), so retries/backoff against a
    # flaky peer are visible on the stitched trace; outside a scope the
    # recorder stays entirely out of the way
    traced = flight_recorder.current() is not None
    while True:
        check_deadline(deadline, what)
        client = None
        ok = False
        try:
            span_cm = tracing.span("rpc", what=what, attempt=attempt) \
                if traced else contextlib.nullcontext()
            with span_cm:
                faults.inject(f"client.{what}")
                client = connect(addr)
                out = fn(client)
            ok = True
            return out
        except Exception as ex:
            if isinstance(ex, flight.FlightTimedOutError):
                tracing.counter("rpc.timeouts")
            if attempt >= policy.retries or not retryable(ex):
                raise
            attempt += 1
            tracing.counter("rpc.retries")
            delay = policy.backoff_s(attempt)
            rem = remaining_s(deadline)
            if rem is not None and rem <= delay:
                # sleeping would burn the rest of the budget and the next
                # loop's check_deadline would mask THIS error with a generic
                # DeadlineExceededError — surface the real failure now
                raise
            time.sleep(delay)
        finally:
            if client is not None and not (ok and not close_on_success):
                client.close()


def _with_retry(addr: str, what: str, fn, policy: Optional[RpcPolicy],
                deadline: Optional[float],
                timeout_s: Optional[float] = None):
    """Run `fn(client, options)` under the policy: per-attempt deadline
    (recomputed each attempt as the caller's absolute deadline shrinks),
    classify-then-retry with backoff."""
    policy = policy or default_policy()

    def attempt(client):
        t = _effective_timeout(timeout_s or policy.call_timeout_s, deadline)
        return fn(client, call_options(timeout_s=t))
    return _run_attempts(addr, what, attempt, policy, deadline)


def flight_action(addr: str, name: str, payload: Optional[dict] = None,
                  policy: Optional[RpcPolicy] = None,
                  deadline: Optional[float] = None,
                  timeout_s: Optional[float] = None) -> dict:
    """One-shot action RPC: connect, act, close — under the RPC policy
    (per-call deadline, retry/backoff on retryable failures). Returns the
    decoded first result (or {}). `deadline` is an absolute `time.time()`
    bound the whole call (retries included) must respect."""
    body = flight_action_raw(addr, name, payload, policy=policy,
                             deadline=deadline, timeout_s=timeout_s)
    return json.loads(body) if body else {}


def flight_action_raw(addr: str, name: str,
                      payload: Optional[dict] = None,
                      policy: Optional[RpcPolicy] = None,
                      deadline: Optional[float] = None,
                      timeout_s: Optional[float] = None) -> bytes:
    """One-shot action RPC returning the raw first-result bytes — for
    actions whose payload is NOT JSON (the `metrics` Prometheus text)."""
    body = json.dumps(payload).encode() if payload is not None else b""

    def call(client, options):
        results = list(client.do_action(flight.Action(name, body), options))
        return results[0].body.to_pybytes() if results else b""
    return _with_retry(addr, f"action.{name}", call, policy, deadline,
                       timeout_s)


def flight_actions_raw(addr: str, actions,
                       policy: Optional[RpcPolicy] = None):
    """Run several action RPCs over ONE connection, yielding each action's
    raw first-result bytes in order. `actions` iterates (name, payload)
    pairs. The connection closes when the generator is exhausted or closed —
    the worker's registration pre-warm pulls hundreds of compile-cache
    entries and must not pay a TCP connect/teardown per entry. Each call
    carries the policy's per-call deadline but is NOT retried (callers — the
    compile-cache push/pull loops — already have per-entry retry logic, and
    replaying the already-consumed prefix of `actions` is impossible)."""
    policy = policy or default_policy()
    client = connect(addr)
    try:
        for name, payload in actions:
            faults.inject(f"client.action.{name}")
            body = json.dumps(payload).encode() if payload is not None else b""
            results = list(client.do_action(
                flight.Action(name, body),
                call_options(timeout_s=policy.call_timeout_s)))
            yield results[0].body.to_pybytes() if results else b""
    finally:
        client.close()


def flight_stream_response(schema, gen):
    """Server-side half of a streaming do_get. Two stream shapes, because
    pyarrow makes each wrong in a different way:

    - GeneratorStream(schema, gen) preserves Flight error STATUSES raised
      mid-generator (a FlightUnavailableError stays UNAVAILABLE on the wire,
      which the client-side peer-loss classification depends on) — but its
      IPC writer never emits dictionary batches, so any dictionary-bearing
      schema dies at the peer's reader with "expected number (1) of
      dictionaries at the start of the stream".
    - A RecordBatchReader-backed RecordBatchStream writes dictionary batches
      correctly and still pulls one batch at a time (spilled fragments
      stream straight off their IPC spill files) — but a mid-generator
      exception crosses the C++ reader boundary and degrades to a generic
      FlightServerError.

    So: reader-backed only when the schema actually carries dictionaries
    (encoded exchange slices), GeneratorStream everywhere else."""
    if any(pa.types.is_dictionary(f.type) for f in schema):
        return flight.RecordBatchStream(
            pa.RecordBatchReader.from_batches(schema, gen))
    return flight.GeneratorStream(schema, gen)


def flight_stream_batches(addr: str, ticket,
                          policy: Optional[RpcPolicy] = None,
                          deadline: Optional[float] = None):
    """Streaming do_get: returns (schema, record-batch generator). The
    connection stays open until the generator is exhausted (or closed), so
    the consumer holds at most one in-flight batch instead of the whole
    result — the data-plane half of the fragment tier's streaming transfers.
    `ticket` may be str or bytes (bucketed exchange tickets are JSON).

    Failure model: the OPEN (probe + do_get + schema) retries under the
    policy; the stream itself runs under a gRPC deadline of
    `stream_timeout_s` clamped to the caller's `deadline` and is never
    retried mid-flight (the consumer re-fetches from scratch — batches
    already yielded cannot be un-consumed). A bounded `ping` probe
    (connect_timeout_s) catches a HUNG peer at open time; without it a
    worker that accepts TCP but never answers would hold do_get for the
    full stream timeout. The connection is also closed by a weakref
    finalizer when a consumer ABANDONS the generator without closing it —
    a never-started generator's close() does not run its finally block, and
    before this fix each abandoned stream leaked one Flight connection."""
    raw = ticket if isinstance(ticket, bytes) else ticket.encode()
    policy = policy or default_policy()

    def open_stream(c):
        probe_t = _effective_timeout(policy.connect_timeout_s, deadline)
        list(c.do_action(flight.Action("ping", b""),
                         call_options(timeout_s=probe_t)))
        t = _effective_timeout(policy.stream_timeout_s, deadline)
        reader = c.do_get(flight.Ticket(raw), call_options(timeout_s=t))
        # the schema read is where a hung/failed do_get actually surfaces —
        # it must happen inside the retried attempt
        return c, reader, reader.schema

    client, reader, schema = _run_attempts(addr, "do_get", open_stream,
                                           policy, deadline,
                                           close_on_success=False)

    done = [False]

    def cleanup():
        # idempotent: the generator's finally on the normal path, the
        # weakref finalizer when the consumer drops an unstarted generator
        if done[0]:
            return
        done[0] = True
        try:
            client.close()
        except Exception:
            pass

    def gen():
        try:
            for chunk in reader:
                if chunk.data is not None:
                    yield chunk.data
        finally:
            cleanup()
    g = gen()
    weakref.finalize(g, cleanup)
    return schema, g
