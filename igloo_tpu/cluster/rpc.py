"""Shared Flight RPC plumbing for the cluster package.

SECURITY MODEL: the cluster transports are designed for a TRUSTED network.
Control actions (register_table, do_put) accept provider specs naming
filesystem paths, so anyone who can reach the port can read files the process
can. The defaults bind loopback only; before binding a non-loopback host set
IGLOO_TPU_AUTH_TOKEN on every process (coordinator, workers, clients) — all
Flight calls then carry the token in an `x-igloo-token` header and servers
reject calls without it. The token is a shared secret over plaintext gRPC:
it gates access, it is not wire encryption; use a private network or mTLS
termination in front for anything stronger.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import pyarrow.flight as flight

AUTH_TOKEN_ENV = "IGLOO_TPU_AUTH_TOKEN"
_HEADER = "x-igloo-token"


def auth_token() -> Optional[str]:
    return os.environ.get(AUTH_TOKEN_ENV) or None


def call_options() -> Optional[flight.FlightCallOptions]:
    """FlightCallOptions carrying the shared token (None when unset)."""
    tok = auth_token()
    if tok is None:
        return None
    return flight.FlightCallOptions(
        headers=[(_HEADER.encode(), tok.encode())])


class TokenMiddlewareFactory(flight.ServerMiddlewareFactory):
    """Rejects any call not presenting the shared token."""

    def __init__(self, token: str):
        self._token = token

    def start_call(self, info, headers):
        if info.method == flight.FlightMethod.HANDSHAKE:
            return None  # the auth handler itself validates the handshake
        vals = []
        for k, vs in headers.items():
            key = k.decode() if isinstance(k, bytes) else k
            # handshake-authenticated clients (TokenServerAuthHandler) carry
            # the session token as gRPC call credentials: pyarrow surfaces
            # them as auth-token-bin (or authorization: Bearer <tok>)
            if key.lower() not in (_HEADER, "authorization",
                                   "auth-token-bin"):
                continue
            for v in vs:
                v = v.decode() if isinstance(v, bytes) else v
                if key.lower() == "authorization":
                    v = v.split(" ", 1)[-1]
                vals.append(v)
        if self._token not in vals:
            raise flight.FlightUnauthenticatedError(
                "missing or invalid x-igloo-token (set IGLOO_TPU_AUTH_TOKEN)")
        return None


def server_middleware() -> Optional[dict]:
    """Middleware dict for FlightServerBase when a token is configured."""
    tok = auth_token()
    if tok is None:
        return None
    return {"auth": TokenMiddlewareFactory(tok)}


class TokenServerAuthHandler(flight.ServerAuthHandler):
    """Handshake (reference proto flight.proto:42) wired to the shared
    token: the client's handshake payload must equal the token; the returned
    session token is the same secret (carried by pyarrow on later calls as
    the authorization header). The per-call x-igloo-token middleware stays
    the primary gate — handshake is the protocol-parity path for stock
    clients that use `FlightClient.authenticate`."""

    def __init__(self, token: str):
        super().__init__()
        self._token = token.encode()

    def authenticate(self, outgoing, incoming):
        buf = incoming.read()
        if buf != self._token:
            raise flight.FlightUnauthenticatedError("bad handshake token")
        outgoing.write(self._token)

    def is_valid(self, token):
        if token == self._token:
            return b"igloo"
        # middleware-authenticated calls present no handshake session token
        return b""


class TokenClientAuthHandler(flight.ClientAuthHandler):
    def __init__(self, token: str):
        super().__init__()
        self._token = token.encode()

    def authenticate(self, outgoing, incoming):
        outgoing.write(self._token)
        self._session = incoming.read()

    def get_token(self):
        return self._session


def server_auth_handler() -> Optional[flight.ServerAuthHandler]:
    tok = auth_token()
    return TokenServerAuthHandler(tok) if tok is not None else None


def warn_if_open_bind(host: str, what: str) -> None:
    if host.strip("[]") not in ("127.0.0.1", "localhost", "::1") \
            and auth_token() is None:
        import sys
        print(f"WARNING: {what} binding non-loopback host {host} with NO "
              f"auth token; anyone reaching the port can register tables "
              f"over arbitrary local paths. Set {AUTH_TOKEN_ENV}.",
              file=sys.stderr)


def normalize(addr: str) -> str:
    return addr if "://" in addr else f"grpc+tcp://{addr}"


def flight_action(addr: str, name: str, payload: Optional[dict] = None) -> dict:
    """One-shot action RPC: connect, act, close. Returns the decoded first
    result (or {})."""
    body = flight_action_raw(addr, name, payload)
    return json.loads(body) if body else {}


def flight_action_raw(addr: str, name: str,
                      payload: Optional[dict] = None) -> bytes:
    """One-shot action RPC returning the raw first-result bytes — for
    actions whose payload is NOT JSON (the `metrics` Prometheus text)."""
    client = flight.connect(normalize(addr))
    try:
        body = json.dumps(payload).encode() if payload is not None else b""
        results = list(client.do_action(flight.Action(name, body),
                                        call_options()))
    finally:
        client.close()
    return results[0].body.to_pybytes() if results else b""


def flight_actions_raw(addr: str, actions):
    """Run several action RPCs over ONE connection, yielding each action's
    raw first-result bytes in order. `actions` iterates (name, payload)
    pairs. The connection closes when the generator is exhausted or closed —
    the worker's registration pre-warm pulls hundreds of compile-cache
    entries and must not pay a TCP connect/teardown per entry."""
    client = flight.connect(normalize(addr))
    try:
        for name, payload in actions:
            body = json.dumps(payload).encode() if payload is not None else b""
            results = list(client.do_action(flight.Action(name, body),
                                            call_options()))
            yield results[0].body.to_pybytes() if results else b""
    finally:
        client.close()


def flight_stream_batches(addr: str, ticket):
    """Streaming do_get: returns (schema, record-batch generator). The
    connection stays open until the generator is exhausted (or closed), so
    the consumer holds at most one in-flight batch instead of the whole
    result — the data-plane half of the fragment tier's streaming transfers.
    `ticket` may be str or bytes (bucketed exchange tickets are JSON)."""
    raw = ticket if isinstance(ticket, bytes) else ticket.encode()
    client = flight.connect(normalize(addr))
    try:
        reader = client.do_get(flight.Ticket(raw), call_options())
        schema = reader.schema
    except Exception:
        client.close()
        raise

    def gen():
        try:
            for chunk in reader:
                if chunk.data is not None:
                    yield chunk.data
        finally:
            client.close()
    return schema, gen()
