"""Shared Flight RPC plumbing for the cluster package."""
from __future__ import annotations

import json
from typing import Optional

import pyarrow.flight as flight


def normalize(addr: str) -> str:
    return addr if "://" in addr else f"grpc+tcp://{addr}"


def flight_action(addr: str, name: str, payload: Optional[dict] = None) -> dict:
    """One-shot action RPC: connect, act, close. Returns the decoded first
    result (or {})."""
    client = flight.connect(normalize(addr))
    try:
        body = json.dumps(payload).encode() if payload is not None else b""
        results = list(client.do_action(flight.Action(name, body)))
    finally:
        client.close()
    return json.loads(results[0].body.to_pybytes()) if results else {}


def flight_get_table(addr: str, ticket: str):
    """One-shot do_get RPC returning the full Arrow table."""
    client = flight.connect(normalize(addr))
    try:
        return client.do_get(flight.Ticket(ticket.encode())).read_all()
    finally:
        client.close()
