"""Distributed client: Arrow Flight SQL against the coordinator.

Fills two reference stubs at once: `crates/client/src/main.rs:1-4` (an empty
binary that was meant to speak Flight SQL) and `pyigloo` (an empty PyO3 crate).
Any stock Arrow Flight client interoperates — this class is convenience, not
protocol: a stock client's `do_get(ticket=sql)` works from any language.

Every call carries the RPC policy's per-call deadline, so a hung coordinator
costs a bounded timeout instead of a wedged client; pass `deadline_s` to
`execute` for a per-query budget the COORDINATOR also enforces (it stops
dispatching fragments and releases worker results at the deadline), and
`qid` to make the query addressable by `cancel`.
"""
from __future__ import annotations

import json
import time
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.cluster import protocol, rpc, serving
from igloo_tpu.cluster.rpc import call_options as _call_options
from igloo_tpu.cluster.rpc import normalize as _normalize
from igloo_tpu.errors import IglooError
from igloo_tpu.utils import tracing


class DistributedClient:
    def __init__(self, addr: str, policy: Optional[rpc.RpcPolicy] = None):
        self.addr = _normalize(addr)
        self._policy = policy or rpc.default_policy()
        self._client = rpc.connect(self.addr)

    # --- health / metadata ---

    def ping(self) -> dict:
        return self._action("ping")

    def cluster_status(self) -> dict:
        return self._action("cluster_status")

    def last_metrics(self) -> dict:
        """Per-fragment metrics of the last distributed query (worker, rows,
        elapsed_s per fragment + totals), typed through the registry schema
        (cluster/protocol.py LAST_METRICS)."""
        return protocol.LAST_METRICS.parse(self._action("last_metrics"))

    def tables(self) -> list[str]:
        return self.cluster_status()["tables"]

    def active_queries(self) -> list[str]:
        """qids of in-flight distributed queries (cancel targets)."""
        return self._action("active_queries").get("queries", [])

    def serving_status(self) -> dict:
        """Admission queue / concurrency / HBM-reservation snapshot
        (docs/serving.md; shape: cluster/protocol.py SERVING_STATUS)."""
        return self._action("serving_status")

    def trace(self, trace_id: Optional[str] = None,
              qid: Optional[str] = None, fmt: str = "chrome") -> dict:
        """Stitched flight-recorder timeline by trace_id or qid (neither =
        the most recent query): Chrome-trace/Perfetto JSON by default,
        the raw span record with fmt="raw"
        (docs/observability.md#distributed-tracing)."""
        return self._action("trace", protocol.TRACE_REQUEST.build(
            trace_id=trace_id, qid=qid, format=fmt))

    def metrics_text(self) -> str:
        """Coordinator process + worker-aggregated fragment metrics,
        Prometheus text exposition."""
        return rpc.flight_action_raw(
            self.addr, "metrics",
            policy=self._policy).decode()

    def poll_info(self, sql: str) -> dict:
        """PollFlightInfo equivalent: planning completes eagerly, so the
        reply is always {"progress": 1.0, "complete": true}."""
        return self._action("poll_flight_info",
                            protocol.POLL_FLIGHT_INFO.build(sql=sql))

    # --- watchtower (docs/observability.md#watchtower) ---

    def metrics_history(self) -> list:
        """The fleet's sampler rings, source-labeled and merged by
        timestamp: the coordinator's own plus every live worker's."""
        return protocol.METRICS_HISTORY.parse(
            self._action("metrics_history"))["samples"]

    def events(self, min_severity: str = "info",
               limit: Optional[int] = None) -> list:
        """Cluster event journal, oldest first, at or above
        `min_severity` ("info" | "warn" | "error")."""
        return protocol.EVENTS_REPLY.parse(self._action(
            "events", protocol.EVENTS_REQUEST.build(
                min_severity=min_severity, limit=limit)))["events"]

    def slow_queries(self) -> list:
        """Baseline-anomaly escalation records (system.slow_queries)."""
        return protocol.SLOW_QUERIES_REPLY.parse(
            self._action("slow_queries"))["slow_queries"]

    def watch_status(self) -> dict:
        """One-call ops snapshot behind `igloo top`: qps/latency
        quantiles, admission state, workers, active queries, recent
        journal events and sampler rows."""
        return protocol.WATCH_STATUS.parse(self._action("watch_status"))

    # --- queries ---

    def execute(self, sql: str, deadline_s: Optional[float] = None,
                qid: Optional[str] = None, priority: Optional[int] = None,
                session: Optional[str] = None,
                busy_wait_s: Optional[float] = None,
                trace_id: Optional[str] = None) -> pa.Table:
        """One round trip: the ticket IS the SQL (do_get executes once).
        `deadline_s` bounds the query server-side (and this call, slightly
        padded so the coordinator's deadline fires first and reports
        properly); `qid` names it for `cancel`; `priority` (0 = interactive
        ... lower tiers) and `session` feed the coordinator's admission
        controller (docs/serving.md); `trace_id` names the query's stitched
        flight-recorder timeline (fetch it back with the `trace` action —
        docs/observability.md#distributed-tracing).

        Retry model: a SHED query (the coordinator's admission queue was
        full — `IGLOO_BUSY` marker) is retried with backoff honoring the
        server's retry-after hint until `busy_wait_s` (default 60 s, or the
        query deadline when one is set) — overload means bounded extra
        latency, not a failure. Other RETRYABLE transport failures
        (unavailable peer, timeout) use the policy's normal retry budget;
        fatal errors (the query itself failed) surface immediately.
        Retrying from scratch is safe: results materialize via read_all(),
        so no partial batches were consumed."""
        # the registry coerces HERE, so a mistyped field fails client-side
        # with a ProtocolError naming it instead of round-tripping to an
        # opaque server error; unset fields are omitted and a bare ticket
        # collapses to the SQL itself (stock-client wire compatibility)
        body = protocol.QUERY_TICKET.build(sql=sql, deadline_s=deadline_s,
                                           qid=qid, priority=priority,
                                           session=session,
                                           trace_id=trace_id)
        ticket = protocol.encode_query_ticket(body, sql)
        timeout = self._policy.stream_timeout_s if deadline_s is None \
            else deadline_s + min(5.0, self._policy.connect_timeout_s)
        if busy_wait_s is None:
            busy_wait_s = deadline_s if deadline_s is not None else 60.0
        busy_deadline = time.time() + busy_wait_s
        # SEPARATE budgets: sheds are bounded by busy_deadline only and must
        # not consume the transport retry budget — a client shed twice under
        # load still deserves its full policy budget for an unrelated
        # transient transport failure afterwards
        busy_attempt = 0
        attempt = 0
        while True:
            try:
                reader = self._client.do_get(
                    flight.Ticket(ticket.encode()),
                    _call_options(timeout_s=timeout))
                return reader.read_all()
            except flight.FlightError as ex:
                msg = str(ex)
                if serving.BUSY_MARKER in msg:
                    # load shed: bounded-latency retry, not a failure
                    hint = serving.parse_retry_after(msg)
                    delay = hint if hint is not None \
                        else self._policy.backoff_s(busy_attempt + 1)
                    if time.time() + delay >= busy_deadline:
                        raise IglooError(_strip_flight(msg)) from None
                    busy_attempt += 1
                    tracing.counter("client.busy_retries")
                    time.sleep(delay)
                    continue
                if rpc.retryable(ex) and attempt < self._policy.retries:
                    attempt += 1
                    tracing.counter("rpc.retries")
                    time.sleep(self._policy.backoff_s(attempt))
                    continue
                raise IglooError(_strip_flight(msg)) from None

    sql = execute

    def cancel(self, qid: str) -> bool:
        """Cancel a running distributed query by the qid passed to
        `execute`; False when the coordinator no longer knows it."""
        return bool(self._action(
            "cancel_query",
            protocol.CANCEL_QUERY.build(qid=qid)).get("cancelled"))

    def schema(self, sql: str) -> pa.Schema:
        """Result schema WITHOUT executing (the reference runs the query to
        answer this — crates/api/src/lib.rs:90-98)."""
        desc = flight.FlightDescriptor.for_command(sql.encode())
        try:
            return self._client.get_schema(
                desc, _call_options(
                    timeout_s=self._policy.call_timeout_s)).schema
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None

    # --- registration ---

    def register_table(self, name: str, table: pa.Table) -> None:
        """Upload an in-memory table (Flight do_put; reference: unimplemented)."""
        desc = flight.FlightDescriptor.for_path(name)
        writer, _ = self._client.do_put(
            desc, table.schema,
            _call_options(timeout_s=self._policy.stream_timeout_s))
        writer.write_table(table)
        writer.close()

    def register_parquet(self, name: str, path: str) -> None:
        self._action("register_table", protocol.REGISTER_TABLE.build(
            name=name, spec={"kind": "parquet", "path": path}))

    def register_csv(self, name: str, path: str, has_header: bool = True,
                     delimiter: str = ",") -> None:
        self._action("register_table", protocol.REGISTER_TABLE.build(
            name=name, spec={"kind": "csv", "path": path,
                             "has_header": has_header,
                             "delimiter": delimiter}))

    # --- plumbing ---

    def _action(self, name: str, payload: Optional[dict] = None) -> dict:
        body = json.dumps(payload).encode() if payload is not None else b""
        try:
            results = list(self._client.do_action(
                flight.Action(name, body),
                _call_options(timeout_s=self._policy.call_timeout_s)))
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None
        return json.loads(results[0].body.to_pybytes()) if results else {}

    def close(self) -> None:
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _strip_flight(msg: str) -> str:
    # flight errors carry transport prefixes; keep the engine's message
    for marker in ("detail: ", "message: "):
        if marker in msg:
            msg = msg.split(marker, 1)[1]
    return msg.split(". gRPC client debug context")[0].strip()
