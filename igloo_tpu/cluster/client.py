"""Distributed client: Arrow Flight SQL against the coordinator.

Fills two reference stubs at once: `crates/client/src/main.rs:1-4` (an empty
binary that was meant to speak Flight SQL) and `pyigloo` (an empty PyO3 crate).
Any stock Arrow Flight client interoperates — this class is convenience, not
protocol: `flight.connect(addr).do_get(ticket=sql)` works from any language.
"""
from __future__ import annotations

import json
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.errors import IglooError


from igloo_tpu.cluster.rpc import call_options as _call_options
from igloo_tpu.cluster.rpc import normalize as _normalize


class DistributedClient:
    def __init__(self, addr: str):
        self.addr = _normalize(addr)
        self._client = flight.connect(self.addr)

    # --- health / metadata ---

    def ping(self) -> dict:
        return self._action("ping")

    def cluster_status(self) -> dict:
        return self._action("cluster_status")

    def last_metrics(self) -> dict:
        """Per-fragment metrics of the last distributed query (worker, rows,
        elapsed_s per fragment + totals)."""
        return self._action("last_metrics")

    def tables(self) -> list[str]:
        return self.cluster_status()["tables"]

    # --- queries ---

    def execute(self, sql: str) -> pa.Table:
        """One round trip: the ticket IS the SQL (do_get executes once)."""
        try:
            reader = self._client.do_get(flight.Ticket(sql.encode()),
                                         _call_options())
            return reader.read_all()
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None

    sql = execute

    def schema(self, sql: str) -> pa.Schema:
        """Result schema WITHOUT executing (the reference runs the query to
        answer this — crates/api/src/lib.rs:90-98)."""
        desc = flight.FlightDescriptor.for_command(sql.encode())
        try:
            return self._client.get_schema(desc, _call_options()).schema
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None

    # --- registration ---

    def register_table(self, name: str, table: pa.Table) -> None:
        """Upload an in-memory table (Flight do_put; reference: unimplemented)."""
        desc = flight.FlightDescriptor.for_path(name)
        writer, _ = self._client.do_put(desc, table.schema, _call_options())
        writer.write_table(table)
        writer.close()

    def register_parquet(self, name: str, path: str) -> None:
        self._action("register_table",
                     {"name": name, "spec": {"kind": "parquet", "path": path}})

    def register_csv(self, name: str, path: str, has_header: bool = True,
                     delimiter: str = ",") -> None:
        self._action("register_table",
                     {"name": name, "spec": {"kind": "csv", "path": path,
                                             "has_header": has_header,
                                             "delimiter": delimiter}})

    # --- plumbing ---

    def _action(self, name: str, payload: Optional[dict] = None) -> dict:
        body = json.dumps(payload).encode() if payload is not None else b""
        try:
            results = list(self._client.do_action(flight.Action(name, body),
                                                  _call_options()))
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None
        return json.loads(results[0].body.to_pybytes()) if results else {}

    def close(self) -> None:
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _strip_flight(msg: str) -> str:
    # flight errors carry transport prefixes; keep the engine's message
    for marker in ("detail: ", "message: "):
        if marker in msg:
            msg = msg.split(marker, 1)[1]
    return msg.split(". gRPC client debug context")[0].strip()
