"""Distributed client: Arrow Flight SQL against the coordinator.

Fills two reference stubs at once: `crates/client/src/main.rs:1-4` (an empty
binary that was meant to speak Flight SQL) and `pyigloo` (an empty PyO3 crate).
Any stock Arrow Flight client interoperates — this class is convenience, not
protocol: a stock client's `do_get(ticket=sql)` works from any language.

Every call carries the RPC policy's per-call deadline, so a hung coordinator
costs a bounded timeout instead of a wedged client; pass `deadline_s` to
`execute` for a per-query budget the COORDINATOR also enforces (it stops
dispatching fragments and releases worker results at the deadline), and
`qid` to make the query addressable by `cancel`.
"""
from __future__ import annotations

import json
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.cluster import rpc
from igloo_tpu.cluster.rpc import call_options as _call_options
from igloo_tpu.cluster.rpc import normalize as _normalize
from igloo_tpu.errors import IglooError


class DistributedClient:
    def __init__(self, addr: str, policy: Optional[rpc.RpcPolicy] = None):
        self.addr = _normalize(addr)
        self._policy = policy or rpc.default_policy()
        self._client = rpc.connect(self.addr)

    # --- health / metadata ---

    def ping(self) -> dict:
        return self._action("ping")

    def cluster_status(self) -> dict:
        return self._action("cluster_status")

    def last_metrics(self) -> dict:
        """Per-fragment metrics of the last distributed query (worker, rows,
        elapsed_s per fragment + totals)."""
        return self._action("last_metrics")

    def tables(self) -> list[str]:
        return self.cluster_status()["tables"]

    # --- queries ---

    def execute(self, sql: str, deadline_s: Optional[float] = None,
                qid: Optional[str] = None) -> pa.Table:
        """One round trip: the ticket IS the SQL (do_get executes once).
        `deadline_s` bounds the query server-side (and this call, slightly
        padded so the coordinator's deadline fires first and reports
        properly); `qid` names it for `cancel`."""
        ticket = sql
        if deadline_s is not None or qid is not None:
            body = {"sql": sql}
            if deadline_s is not None:
                body["deadline_s"] = deadline_s
            if qid is not None:
                body["qid"] = qid
            ticket = json.dumps(body)
        timeout = self._policy.stream_timeout_s if deadline_s is None \
            else deadline_s + min(5.0, self._policy.connect_timeout_s)
        try:
            reader = self._client.do_get(flight.Ticket(ticket.encode()),
                                         _call_options(timeout_s=timeout))
            return reader.read_all()
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None

    sql = execute

    def cancel(self, qid: str) -> bool:
        """Cancel a running distributed query by the qid passed to
        `execute`; False when the coordinator no longer knows it."""
        return bool(self._action("cancel_query",
                                 {"qid": qid}).get("cancelled"))

    def schema(self, sql: str) -> pa.Schema:
        """Result schema WITHOUT executing (the reference runs the query to
        answer this — crates/api/src/lib.rs:90-98)."""
        desc = flight.FlightDescriptor.for_command(sql.encode())
        try:
            return self._client.get_schema(
                desc, _call_options(
                    timeout_s=self._policy.call_timeout_s)).schema
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None

    # --- registration ---

    def register_table(self, name: str, table: pa.Table) -> None:
        """Upload an in-memory table (Flight do_put; reference: unimplemented)."""
        desc = flight.FlightDescriptor.for_path(name)
        writer, _ = self._client.do_put(
            desc, table.schema,
            _call_options(timeout_s=self._policy.stream_timeout_s))
        writer.write_table(table)
        writer.close()

    def register_parquet(self, name: str, path: str) -> None:
        self._action("register_table",
                     {"name": name, "spec": {"kind": "parquet", "path": path}})

    def register_csv(self, name: str, path: str, has_header: bool = True,
                     delimiter: str = ",") -> None:
        self._action("register_table",
                     {"name": name, "spec": {"kind": "csv", "path": path,
                                             "has_header": has_header,
                                             "delimiter": delimiter}})

    # --- plumbing ---

    def _action(self, name: str, payload: Optional[dict] = None) -> dict:
        body = json.dumps(payload).encode() if payload is not None else b""
        try:
            results = list(self._client.do_action(
                flight.Action(name, body),
                _call_options(timeout_s=self._policy.call_timeout_s)))
        except flight.FlightError as ex:
            raise IglooError(_strip_flight(str(ex))) from None
        return json.loads(results[0].body.to_pybytes()) if results else {}

    def close(self) -> None:
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _strip_flight(msg: str) -> str:
    # flight errors carry transport prefixes; keep the engine's message
    for marker in ("detail: ", "message: "):
        if marker in msg:
            msg = msg.split(marker, 1)[1]
    return msg.split(". gRPC client debug context")[0].strip()
