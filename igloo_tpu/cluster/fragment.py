"""Query fragments + the distributed planner.

Parity: the reference's `QueryFragment` (crates/coordinator/src/fragment.rs:
7-56 — id / FragmentType / plan / worker / dependencies) and
`DistributedPlanner` (distributed_planner.rs:25-150). Two reference flaws are
fixed by design:

- fragments no longer re-plan whole subtrees (gap G10: each reference fragment
  calls create_physical_plan on the FULL node, duplicating work) — a fragment's
  plan references its dependencies' results as `__frag_<id>` tables;
- aggregation is decomposed into per-worker partial fragments + one final
  merge fragment (the reference ships the whole aggregate to one place), so
  scan+reduce parallelizes across workers the way partial->shuffle->final
  aggregation parallelizes across chips in parallel/executor.py.

Placement: scan fragments stride provider partitions across workers (data
partition parallelism — the latent axis the reference never exploits, SURVEY
§2 parallelism table); non-leaf fragments round-robin across workers instead
of always running on the coordinator (distributed_planner.rs:65-92 pins every
join to "coordinator").

Shuffle joins (the reference's declared-but-dead FragmentType::Shuffle,
fragment.rs:12): an equi-join whose sides are both local subtrees becomes a
HASH-PARTITIONED EXCHANGE instead of a union onto one worker. Each side's
scan fragments get an `Exchange` root (the worker hash-partitions the result
by the join keys into B buckets at store time), and B per-bucket join
fragments — spread across workers — each read only bucket b of EVERY input
fragment via bucketed do_get tickets. Join compute and network traffic both
scale with worker count; the consumer unions the B join-fragment results.
"""
from __future__ import annotations

import itertools
import os
import uuid
from dataclasses import dataclass, field
from typing import Optional

from igloo_tpu import types as T
from igloo_tpu.cluster import serde
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType

FRAG_PREFIX = "__frag_"

# join types a hash-partitioned exchange preserves: every row routes to
# exactly one bucket and matching keys co-locate, so inner/outer/semi/anti
# semantics are all per-bucket local. CROSS has no keys to partition by.
_SHUFFLE_JOIN_TYPES = {JoinType.INNER, JoinType.LEFT, JoinType.RIGHT,
                       JoinType.FULL, JoinType.SEMI, JoinType.ANTI}


@dataclass
class QueryFragment:
    """One unit of distributed work: a serialized plan whose `__frag_*` scans
    name the results of `deps`, placed on `worker` (an address)."""
    id: str
    plan: dict                       # serde.plan_to_json output
    worker: str = ""
    deps: list[str] = field(default_factory=list)
    schema: Optional[T.Schema] = None
    kind: str = ""                   # "scan" | "exchange" | "join" | "root"
    bucket: Optional[int] = None     # per-bucket join fragment's bucket id

    def is_ready(self, completed: set[str]) -> bool:
        return all(d in completed for d in self.deps)


def _frag_scan(frag: "QueryFragment") -> L.LogicalPlan:
    """A plan node reading a dependency fragment's result."""
    s = L.Scan(table=FRAG_PREFIX + frag.id, provider=None)
    s.schema = frag.schema
    return s


def _bucket_scan(frag: "QueryFragment", bucket: int, buckets: int
                 ) -> L.LogicalPlan:
    """A plan node reading ONE hash bucket of a dependency fragment's
    Exchange-partitioned result."""
    s = L.Scan(table=FRAG_PREFIX + frag.id, provider=None,
               bucket=bucket, buckets=buckets)
    s.schema = frag.schema
    return s


def _bucket_union(side_frags: list, bucket: int, buckets: int,
                  schema: T.Schema) -> L.LogicalPlan:
    children = [_bucket_scan(f, bucket, buckets) for f in side_frags]
    if len(children) == 1:
        return children[0]
    u = L.Union(inputs=children)
    u.schema = schema
    return u


def _plain_key_indices(keys: list, schema: T.Schema) -> Optional[list[int]]:
    """Join keys as plain column indices into the side's output schema, or
    None when any key is a computed expression (then the two sides' raw
    column bytes need not agree and hash co-partitioning is unsound)."""
    idxs = []
    for k in keys:
        if type(k) is not E.Column or k.index is None or \
                not 0 <= k.index < len(schema.fields):
            return None
        idxs.append(k.index)
    return idxs


def _copy_expr(e):
    import copy
    return copy.deepcopy(e) if e is not None else None


def _col(i: int, dtype: T.DataType, name: str = "") -> E.Expr:
    c = E.Column(name=name or f"c{i}", index=i)
    c.dtype = dtype
    return c


def _is_local(p: L.LogicalPlan) -> bool:
    """True if the subtree is scan/filter/project/values only — safe to ship
    whole to a worker and, for scans, to stride by partition."""
    if isinstance(p, (L.Scan, L.Values)):
        return True
    if isinstance(p, (L.Filter, L.Project)):
        return _is_local(p.input)
    return False


def _subtree_scan(p: L.LogicalPlan) -> Optional[L.Scan]:
    if isinstance(p, L.Scan):
        return p
    if isinstance(p, (L.Filter, L.Project)):
        return _subtree_scan(p.input)
    return None


def _with_partition(p: L.LogicalPlan, part: tuple[int, ...]) -> L.LogicalPlan:
    """Copy of the subtree with its scan restricted to `part`, capturing the
    provider's partition-index fingerprint so reads fail loudly if the index
    is rebuilt (re-glob) between planning and execution."""
    n = L.copy_plan(p)
    sc = _subtree_scan(n)
    assert sc is not None
    sc.partition = part
    tok = getattr(sc.provider, "partition_token", None)
    if tok is not None:
        try:
            sc.partition_token = tok()
        except Exception:
            sc.partition_token = None
    return n


_DECOMPOSABLE = {E.AggFunc.SUM, E.AggFunc.MIN, E.AggFunc.MAX, E.AggFunc.COUNT,
                 E.AggFunc.COUNT_STAR, E.AggFunc.AVG}


class DistributedPlanner:
    """Fragments an optimized plan across `workers` (list of addresses)."""

    def __init__(self, workers: list[str], partitions_per_worker: int = 1,
                 shuffle_buckets: Optional[int] = None):
        if not workers:
            raise ValueError("no workers")
        self.workers = list(workers)
        self.ppw = partitions_per_worker
        self._rr = itertools.cycle(range(len(workers)))
        if shuffle_buckets is None:
            env = os.environ.get("IGLOO_SHUFFLE_BUCKETS")
            shuffle_buckets = int(env) if env else \
                len(self.workers) * self.ppw
        self.shuffle_buckets = max(1, shuffle_buckets)
        # kill switch for A/B against the union-onto-one-worker plan shape
        self.shuffle_enabled = \
            os.environ.get("IGLOO_SHUFFLE_JOIN", "1") != "0"

    def plan(self, plan: L.LogicalPlan) -> list[QueryFragment]:
        """-> fragments in dependency-safe order; the LAST one is the root."""
        frags: list[QueryFragment] = []
        root_plan = self._split(plan, frags)
        self._make_fragment(root_plan, frags_out=frags)  # appends the root
        return frags

    # --- internals ---

    def _next_worker(self) -> str:
        return self.workers[next(self._rr)]

    def _make_fragment(self, plan: L.LogicalPlan,
                       frags_out: list[QueryFragment],
                       deps: Optional[list[str]] = None,
                       worker: Optional[str] = None,
                       kind: str = "",
                       bucket: Optional[int] = None) -> QueryFragment:
        plan_json = serde.plan_to_json(plan)
        if deps is None:
            # dedupe, preserving order: a per-bucket join fragment references
            # the same dependency once per side scan
            seen: dict[str, None] = {}
            for d in _frag_refs(plan_json):
                seen.setdefault(d["table"][len(FRAG_PREFIX):])
            deps = list(seen)
        f = QueryFragment(id=uuid.uuid4().hex[:12], plan=plan_json,
                          worker=worker or self._next_worker(),
                          deps=deps, schema=plan.schema, kind=kind,
                          bucket=bucket)
        frags_out.append(f)
        return f

    def _split(self, p: L.LogicalPlan,
               frags: list[QueryFragment]) -> L.LogicalPlan:
        """Post-order: replace distributable subtrees with fragment scans;
        return the plan the root fragment executes."""
        if isinstance(p, L.Aggregate) and _is_local(p.input) and \
                not any(a.distinct for a in p.aggs) and \
                all(a.func in _DECOMPOSABLE for a in p.aggs):
            return self._split_aggregate(p, frags)
        # recurse into children; large local subtrees under joins become
        # their own (partitioned) fragments
        for name in ("input", "left", "right"):
            ch = getattr(p, name, None)
            if isinstance(ch, L.LogicalPlan):
                setattr(p, name, self._split(ch, frags))
        if isinstance(p, L.Union):
            p.inputs = [self._split(c, frags) for c in p.inputs]
        if isinstance(p, L.Join):
            shuffled = self._try_shuffle_join(p, frags)
            if shuffled is not None:
                return shuffled
            for name in ("left", "right"):
                ch = getattr(p, name)
                if _is_local(ch) and not isinstance(ch, L.Values):
                    setattr(p, name, self._scan_fragments(ch, frags))
        return p

    # --- hash-partitioned shuffle joins ---

    def _try_shuffle_join(self, p: L.Join,
                          frags: list[QueryFragment]
                          ) -> Optional[L.LogicalPlan]:
        """Join over two local subtrees -> per-bucket join fragments reading
        bucket slices of Exchange-partitioned side fragments; returns the
        Union the consumer executes, or None when ineligible (the caller
        falls back to the union-of-scan-fragments shape)."""
        if not self.shuffle_enabled or len(self.workers) < 2 \
                or self.shuffle_buckets < 2:
            return None
        if p.join_type not in _SHUFFLE_JOIN_TYPES or not p.left_keys:
            return None
        for side in (p.left, p.right):
            if not _is_local(side) or isinstance(side, L.Values) \
                    or side.schema is None:
                return None
        lkeys = _plain_key_indices(p.left_keys, p.left.schema)
        rkeys = _plain_key_indices(p.right_keys, p.right.schema)
        if lkeys is None or rkeys is None:
            return None
        # both sides must hash the same value domain: binder coercion casts
        # (non-Column keys) are already rejected above, this guards direct
        # Column pairs of unequal dtype
        for lk, rk in zip(p.left_keys, p.right_keys):
            if lk.dtype is None or rk.dtype is None or \
                    lk.dtype.id is not rk.dtype.id:
                return None
        B = self.shuffle_buckets
        left_frags = self._exchange_fragments(p.left, lkeys, B, frags)
        right_frags = self._exchange_fragments(p.right, rkeys, B, frags)
        join_scans: list[L.LogicalPlan] = []
        for b in range(B):
            jb = L.Join(left=_bucket_union(left_frags, b, B, p.left.schema),
                        right=_bucket_union(right_frags, b, B, p.right.schema),
                        join_type=p.join_type,
                        left_keys=[_copy_expr(k) for k in p.left_keys],
                        right_keys=[_copy_expr(k) for k in p.right_keys],
                        residual=_copy_expr(p.residual))
            jb.schema = p.schema
            jf = self._make_fragment(
                jb, frags, worker=self.workers[b % len(self.workers)],
                kind="join", bucket=b)
            join_scans.append(_frag_scan(jf))
        if len(join_scans) == 1:
            return join_scans[0]
        u = L.Union(inputs=join_scans)
        u.schema = p.schema
        return u

    def _exchange_fragments(self, side: L.LogicalPlan, keys: list[int],
                            buckets: int,
                            frags: list[QueryFragment]) -> list[QueryFragment]:
        """One Exchange-rooted fragment per scan partition set of `side`."""
        out = []
        for part in self._partition_sets(side):
            sub = _with_partition(side, part) if part else L.copy_plan(side)
            ex = L.Exchange(input=sub, keys=list(keys), buckets=buckets)
            ex.schema = sub.schema
            out.append(self._make_fragment(ex, frags, deps=[],
                                           kind="exchange"))
        return out

    def _scan_fragments(self, subtree: L.LogicalPlan,
                        frags: list[QueryFragment]) -> L.LogicalPlan:
        """Partition a local subtree across workers; consumer unions results."""
        parts = self._partition_sets(subtree)
        if len(parts) <= 1:
            f = self._make_fragment(subtree, frags, deps=[])
            return _frag_scan(f)
        children = []
        for part in parts:
            f = self._make_fragment(_with_partition(subtree, part), frags,
                                    deps=[])
            children.append(_frag_scan(f))
        u = L.Union(inputs=children)
        u.schema = subtree.schema
        return u

    def _partition_sets(self, subtree: L.LogicalPlan) -> list[tuple[int, ...]]:
        sc = _subtree_scan(subtree)
        if sc is None or sc.provider is None:
            return [()]
        try:
            n_parts = sc.provider.num_partitions()
        except Exception:
            n_parts = 1
        n_frag = min(len(self.workers) * self.ppw, max(n_parts, 1))
        if n_parts <= 1 or n_frag <= 1:
            return [()]
        return [tuple(range(i, n_parts, n_frag)) for i in range(n_frag)]

    def _split_aggregate(self, agg: L.Aggregate,
                         frags: list[QueryFragment]) -> L.LogicalPlan:
        """agg over a local subtree -> per-partition partial fragments +
        final merge plan (returned for the parent fragment to execute)."""
        parts = self._partition_sets(agg.input)
        partial_schema, partial_aggs, partial_names, final_plan = \
            decompose_aggregate(agg)

        children = []
        for part in parts:
            sub = _with_partition(agg.input, part) if part else \
                L.copy_plan(agg.input)
            node = partial_aggregate_node(agg, sub, partial_schema,
                                          partial_aggs, partial_names)
            f = self._make_fragment(node, frags, deps=[])
            children.append(_frag_scan(f))
        if len(children) == 1:
            merged: L.LogicalPlan = children[0]
        else:
            merged = L.Union(inputs=children)
            merged.schema = partial_schema
        return final_merge_plan(agg, merged, final_plan)


def decompose_aggregate(agg: L.Aggregate):
    """Decompose a DECOMPOSABLE aggregate into per-chunk partials: returns
    (partial_schema, partial_aggs, partial_names, final_plan) where
    final_plan records how final_merge_plan recombines partial columns.
    Shared by the distributed planner, the chunked executor, and the
    out-of-core grace join (exec/grace.py)."""
    k = len(agg.group_exprs)
    partial_aggs: list[E.Aggregate] = []
    partial_names: list[str] = []
    final_plan: list[tuple] = []  # (kind, partial col index, orig agg)
    pi = k
    for a in agg.aggs:
        if a.func in (E.AggFunc.COUNT, E.AggFunc.COUNT_STAR):
            partial_aggs.append(a)
            partial_names.append(f"p{pi}")
            final_plan.append(("sum0", pi, a))
            pi += 1
        elif a.func is E.AggFunc.AVG:
            s = E.Aggregate(func=E.AggFunc.SUM, arg=a.arg)
            s.dtype = T.FLOAT64
            c = E.Aggregate(func=E.AggFunc.COUNT, arg=a.arg)
            c.dtype = T.INT64
            partial_aggs.extend([s, c])
            partial_names.extend([f"p{pi}", f"p{pi + 1}"])
            final_plan.append(("avg", pi, a))
            pi += 2
        else:  # SUM / MIN / MAX: associative
            partial_aggs.append(a)
            partial_names.append(f"p{pi}")
            final_plan.append(("assoc", pi, a))
            pi += 1

    partial_fields = [T.Field(n, g.dtype, True)
                      for n, g in zip(agg.group_names, agg.group_exprs)]
    partial_fields += [T.Field(n, a.dtype, True)
                       for n, a in zip(partial_names, partial_aggs)]
    return T.Schema(partial_fields), partial_aggs, partial_names, final_plan


def partial_aggregate_node(agg: L.Aggregate, inp: L.LogicalPlan,
                           partial_schema, partial_aggs,
                           partial_names) -> L.Aggregate:
    node = L.Aggregate(input=inp,
                       group_exprs=[g for g in agg.group_exprs],
                       group_names=list(agg.group_names),
                       aggs=list(partial_aggs),
                       agg_names=list(partial_names))
    node.schema = partial_schema
    return node


def final_merge_plan(agg: L.Aggregate, merged: L.LogicalPlan,
                     final_plan: list[tuple]) -> L.LogicalPlan:
    """Final re-aggregation of partial rows + projection back to the
    aggregate's declared output schema."""
    k = len(agg.group_exprs)
    # final merge: re-aggregate partials by the group columns
    final_groups = [_col(i, g.dtype, agg.group_names[i])
                    for i, g in enumerate(agg.group_exprs)]
    final_aggs: list[E.Aggregate] = []
    final_names: list[str] = []
    for kind, pi_, a in final_plan:
        if kind == "avg":
            for j, dt in ((pi_, T.FLOAT64), (pi_ + 1, T.INT64)):
                fa = E.Aggregate(func=E.AggFunc.SUM, arg=_col(j, dt))
                fa.dtype = dt
                final_aggs.append(fa)
                final_names.append(f"f{j}")
        else:
            fn = E.AggFunc.SUM if kind == "sum0" else a.func
            fa = E.Aggregate(func=fn, arg=_col(pi_, a.dtype))
            fa.dtype = a.dtype
            final_aggs.append(fa)
            final_names.append(f"f{pi_}")
    merge = L.Aggregate(input=merged, group_exprs=final_groups,
                        group_names=list(agg.group_names),
                        aggs=final_aggs, agg_names=final_names)
    merge.schema = T.Schema(
        [T.Field(n, g.dtype, True)
         for n, g in zip(agg.group_names, final_groups)] +
        [T.Field(n, a.dtype, True)
         for n, a in zip(final_names, final_aggs)])

    # project back to the aggregate's declared output (AVG division,
    # COUNT null->0 on empty-side sums)
    out_exprs: list[E.Expr] = [
        _col(i, g.dtype, agg.group_names[i])
        for i, g in enumerate(agg.group_exprs)]
    fi = k
    for kind, _pi, a in final_plan:
        if kind == "avg":
            s = _col(fi, T.FLOAT64)
            c = _col(fi + 1, T.INT64)
            zero = E.Literal(value=0)
            zero.dtype = T.INT64
            cast = E.Cast(operand=c, to=T.FLOAT64)
            cast.dtype = T.FLOAT64
            div = E.Binary(op=E.BinOp.DIV, left=s, right=cast)
            div.dtype = T.FLOAT64
            isz = E.Binary(op=E.BinOp.EQ, left=c, right=zero)
            isz.dtype = T.BOOL
            nul = E.Literal(value=None, literal_type=T.FLOAT64)
            nul.dtype = T.FLOAT64
            case = E.Case(whens=[(isz, nul)], else_=div)
            case.dtype = T.FLOAT64
            out_exprs.append(case)
            fi += 2
        elif kind == "sum0":
            s = _col(fi, T.INT64)
            zero = E.Literal(value=0)
            zero.dtype = T.INT64
            isn = E.IsNull(operand=s)
            isn.dtype = T.BOOL
            case = E.Case(whens=[(isn, zero)], else_=s)
            case.dtype = T.INT64
            out_exprs.append(case)
            fi += 1
        else:
            out_exprs.append(_col(fi, a.dtype))
            fi += 1
    proj = L.Project(input=merge, exprs=out_exprs,
                     names=list(agg.schema.names))
    proj.schema = agg.schema
    return proj


def _frag_refs(plan_json: dict) -> list[dict]:
    """All Scan nodes referencing fragment results, by tree walk."""
    out = []

    def walk(d):
        if isinstance(d, dict):
            if d.get("t") == "Scan" and str(d.get("table", "")).startswith(
                    FRAG_PREFIX):
                out.append(d)
            for v in d.values():
                walk(v)
        elif isinstance(d, list):
            for v in d:
                walk(v)
    walk(plan_json)
    return out
