"""Query fragments + the distributed planner.

Parity: the reference's `QueryFragment` (crates/coordinator/src/fragment.rs:
7-56 — id / FragmentType / plan / worker / dependencies) and
`DistributedPlanner` (distributed_planner.rs:25-150). Two reference flaws are
fixed by design:

- fragments no longer re-plan whole subtrees (gap G10: each reference fragment
  calls create_physical_plan on the FULL node, duplicating work) — a fragment's
  plan references its dependencies' results as `__frag_<id>` tables;
- aggregation is decomposed into per-worker partial fragments + one final
  merge fragment (the reference ships the whole aggregate to one place), so
  scan+reduce parallelizes across workers the way partial->shuffle->final
  aggregation parallelizes across chips in parallel/executor.py.

Placement: scan fragments stride provider partitions across workers (data
partition parallelism — the latent axis the reference never exploits, SURVEY
§2 parallelism table); non-leaf fragments round-robin across workers instead
of always running on the coordinator (distributed_planner.rs:65-92 pins every
join to "coordinator").

Shuffle joins (the reference's declared-but-dead FragmentType::Shuffle,
fragment.rs:12): an equi-join whose sides are both local subtrees becomes a
HASH-PARTITIONED EXCHANGE instead of a union onto one worker. Each side's
scan fragments get an `Exchange` root (the worker hash-partitions the result
by the join keys into B buckets at store time), and B per-bucket join
fragments — spread across workers — each read only bucket b of EVERY input
fragment via bucketed do_get tickets. Join compute and network traffic both
scale with worker count; the consumer unions the B join-fragment results.
"""
from __future__ import annotations

import itertools
import os
import uuid
from dataclasses import dataclass, field
from typing import Optional

from igloo_tpu import types as T
from igloo_tpu.cluster import serde
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import tracing

FRAG_PREFIX = "__frag_"

# join types a hash-partitioned exchange preserves: every row routes to
# exactly one bucket and matching keys co-locate, so inner/outer/semi/anti
# semantics are all per-bucket local. CROSS has no keys to partition by.
_SHUFFLE_JOIN_TYPES = {JoinType.INNER, JoinType.LEFT, JoinType.RIGHT,
                       JoinType.FULL, JoinType.SEMI, JoinType.ANTI}


@dataclass
class QueryFragment:
    """One unit of distributed work: a serialized plan whose `__frag_*` scans
    name the results of `deps`, placed on `worker` (an address)."""
    id: str
    plan: dict                       # serde.plan_to_json output
    worker: str = ""
    deps: list[str] = field(default_factory=list)
    schema: Optional[T.Schema] = None
    kind: str = ""                   # "scan" | "exchange" | "join" | "root"
    bucket: Optional[int] = None     # per-bucket join fragment's bucket id
    # AdaptiveStats digest of the join SIDE this fragment materializes: the
    # coordinator sums rows/bytes/bucket counts across fragments sharing a
    # key at query end and records them for the next plan (docs/adaptive.md)
    stats_key: Optional[str] = None

    def is_ready(self, completed: set[str]) -> bool:
        return all(d in completed for d in self.deps)


def _frag_scan(frag: "QueryFragment") -> L.LogicalPlan:
    """A plan node reading a dependency fragment's result."""
    s = L.Scan(table=FRAG_PREFIX + frag.id, provider=None)
    s.schema = frag.schema
    return s


def _bucket_scan(frag: "QueryFragment", bucket: int, buckets: int
                 ) -> L.LogicalPlan:
    """A plan node reading ONE hash bucket of a dependency fragment's
    Exchange-partitioned result."""
    s = L.Scan(table=FRAG_PREFIX + frag.id, provider=None,
               bucket=bucket, buckets=buckets)
    s.schema = frag.schema
    return s


def _bucket_union(side_frags: list, bucket: int, buckets: int,
                  schema: T.Schema) -> L.LogicalPlan:
    children = [_bucket_scan(f, bucket, buckets) for f in side_frags]
    if len(children) == 1:
        return children[0]
    u = L.Union(inputs=children)
    u.schema = schema
    return u


def _whole_union(side_frags: list, schema: T.Schema) -> L.LogicalPlan:
    """Union of WHOLE fragment results (the broadcast build side)."""
    children: list[L.LogicalPlan] = [_frag_scan(f) for f in side_frags]
    if len(children) == 1:
        return children[0]
    u = L.Union(inputs=children)
    u.schema = schema
    return u


def _plain_key_indices(keys: list, schema: T.Schema) -> Optional[list[int]]:
    """Join keys as plain column indices into the side's output schema, or
    None when any key is a computed expression (then the two sides' raw
    column bytes need not agree and hash co-partitioning is unsound)."""
    idxs = []
    for k in keys:
        if type(k) is not E.Column or k.index is None or \
                not 0 <= k.index < len(schema.fields):
            return None
        idxs.append(k.index)
    return idxs


def _copy_expr(e):
    import copy
    return copy.deepcopy(e) if e is not None else None


def _rewrap(nodes: list, inner: L.LogicalPlan) -> L.LogicalPlan:
    """Re-apply upper-path nodes (root-first, as find_grace_join peeled them)
    over `inner`: shallow node copies with the input swapped — expressions
    stay shared, which is safe because _make_fragment serializes each
    fragment's plan to JSON at creation time."""
    import copy
    for nd in reversed(nodes):
        c = copy.copy(nd)
        c.input = inner
        inner = c
    return inner


def _col(i: int, dtype: T.DataType, name: str = "") -> E.Expr:
    c = E.Column(name=name or f"c{i}", index=i)
    c.dtype = dtype
    return c


def _is_local(p: L.LogicalPlan) -> bool:
    """True if the subtree is scan/filter/project/values only — safe to ship
    whole to a worker and, for scans, to stride by partition."""
    if isinstance(p, (L.Scan, L.Values)):
        return True
    if isinstance(p, (L.Filter, L.Project)):
        return _is_local(p.input)
    return False


def _subtree_scan(p: L.LogicalPlan) -> Optional[L.Scan]:
    if isinstance(p, L.Scan):
        return p
    if isinstance(p, (L.Filter, L.Project)):
        return _subtree_scan(p.input)
    return None


def _with_partition(p: L.LogicalPlan, part: tuple[int, ...]) -> L.LogicalPlan:
    """Copy of the subtree with its scan restricted to `part`, capturing the
    provider's partition-index fingerprint so reads fail loudly if the index
    is rebuilt (re-glob) between planning and execution."""
    n = L.copy_plan(p)
    sc = _subtree_scan(n)
    assert sc is not None
    sc.partition = part
    tok = getattr(sc.provider, "partition_token", None)
    if tok is not None:
        try:
            sc.partition_token = tok()
        except Exception:
            sc.partition_token = None
    return n


_DECOMPOSABLE = {E.AggFunc.SUM, E.AggFunc.MIN, E.AggFunc.MAX, E.AggFunc.COUNT,
                 E.AggFunc.COUNT_STAR, E.AggFunc.AVG}


class DistributedPlanner:
    """Fragments an optimized plan across `workers` (list of addresses).

    Adaptive decisions (docs/adaptive.md, behind IGLOO_ADAPTIVE=0): when the
    process-wide AdaptiveStats store holds OBSERVED statistics for a join
    side (recorded by the coordinator from a previous run of the same side
    fingerprint), the planner may replace the hash exchange with a
    BROADCAST plan (replicating the small build side ships fewer bytes than
    exchanging both sides — the mesh tier's `should_broadcast` rule promoted
    to the fragment tier) or SALT a pathologically skewed exchange (split
    the hot bucket's probe rows across extra buckets, replicate the matching
    build bucket — the escape hatch docs/distributed.md used to document as
    unwinnable). First runs carry no observations and keep the plain
    exchange shape, so behavior only changes once telemetry justifies it."""

    def __init__(self, workers: list[str], partitions_per_worker: int = 1,
                 shuffle_buckets: Optional[int] = None,
                 topology: Optional[dict] = None,
                 budget_bytes: Optional[int] = None):
        if not workers:
            raise ValueError("no workers")
        self.workers = list(workers)
        self.ppw = partitions_per_worker
        # addr -> local mesh device count, from registration/heartbeat
        # reports (cluster/serde.py worker_info_*). Two-level sizing rule:
        # BUCKET COUNT scales with hosts (workers x ppw below — a bucket is
        # a unit of cross-worker exchange), SHARD COUNT scales with chips
        # (each bucket fragment row-shards across its worker's mesh), so a
        # B-bucket join on W workers x D devices runs W x D-way without the
        # planner over-bucketing to W x D fragments (which would multiply
        # exchange slices and per-fragment overhead, not parallelism).
        self.topology = {a: max(int(d), 1)
                         for a, d in (topology or {}).items()}
        self.total_shards = sum(self.topology.get(a, 1)
                                for a in self.workers)
        self._rr = itertools.cycle(range(len(workers)))
        if shuffle_buckets is None:
            env = os.environ.get("IGLOO_SHUFFLE_BUCKETS")
            shuffle_buckets = int(env) if env else \
                len(self.workers) * self.ppw
        self.shuffle_buckets = max(1, shuffle_buckets)
        # kill switch for A/B against the union-onto-one-worker plan shape
        self.shuffle_enabled = \
            os.environ.get("IGLOO_SHUFFLE_JOIN", "1") != "0"
        from igloo_tpu.exec.hints import adaptive_enabled
        self.adaptive_enabled = adaptive_enabled()
        # per-join decision records, published into last_metrics["adaptive"]
        # and the sweep JSON so every plan choice is attributable
        self.adaptive_info: list[dict] = []
        # distributed out-of-core (docs/out_of_core.md): with a per-host
        # budget, an over-budget join tree fragments into per-GRACE-partition
        # bucket joins spread across the fleet instead of demoting to the
        # single-node ladder. IGLOO_GRACE_DISTRIBUTED=0 preserves today's
        # plans bit-identically (the coordinator never passes a budget).
        self.budget_bytes = budget_bytes
        self.grace_enabled = \
            os.environ.get("IGLOO_GRACE_DISTRIBUTED", "1") != "0"
        # set when plan() took the grace path: {"buckets", "partitioned_
        # leaves", "replicated_leaves", "budget_bytes"} — the coordinator
        # publishes it as the query's `oversized` metrics block
        self.grace_info: Optional[dict] = None

    def plan(self, plan: L.LogicalPlan) -> list[QueryFragment]:
        """-> fragments in dependency-safe order; the LAST one is the root."""
        frags: list[QueryFragment] = []
        if self.budget_bytes and self.grace_enabled and \
                len(self.workers) >= 2:
            root_plan = self._try_grace_distributed(plan, frags)
            if root_plan is not None:
                self._make_fragment(root_plan, frags_out=frags)
                return frags
            frags.clear()
        root_plan = self._split(plan, frags)
        self._make_fragment(root_plan, frags_out=frags)  # appends the root
        return frags

    # --- internals ---

    def _next_worker(self) -> str:
        return self.workers[next(self._rr)]

    def _bucket_placement(self, n_buckets: int) -> list[str]:
        """Bucket -> worker assignment. Homogeneous topologies keep the
        round-robin stride; a heterogeneous cluster (workers with unequal
        mesh sizes) gets largest-remainder proportional shares — a 4-chip
        worker takes 4x the buckets of a 1-chip worker, since each of its
        buckets runs 4-way inside the mesh — interleaved so consecutive
        buckets still spread across workers."""
        W = len(self.workers)
        devs = [self.topology.get(a, 1) for a in self.workers]
        if len(set(devs)) <= 1:
            return [self.workers[b % W] for b in range(n_buckets)]
        total = sum(devs)
        quota = [n_buckets * d / total for d in devs]
        counts = [int(q) for q in quota]
        for i in sorted(range(W), key=lambda i: quota[i] - counts[i],
                        reverse=True)[:n_buckets - sum(counts)]:
            counts[i] += 1
        out: list[str] = []
        while len(out) < n_buckets:
            for i in range(W):
                if counts[i]:
                    counts[i] -= 1
                    out.append(self.workers[i])
        return out

    def _make_fragment(self, plan: L.LogicalPlan,
                       frags_out: list[QueryFragment],
                       deps: Optional[list[str]] = None,
                       worker: Optional[str] = None,
                       kind: str = "",
                       bucket: Optional[int] = None,
                       stats_key: Optional[str] = None) -> QueryFragment:
        plan_json = serde.plan_to_json(plan)
        if deps is None:
            # dedupe, preserving order: a per-bucket join fragment references
            # the same dependency once per side scan
            seen: dict[str, None] = {}
            for d in _frag_refs(plan_json):
                seen.setdefault(d["table"][len(FRAG_PREFIX):])
            deps = list(seen)
        f = QueryFragment(id=uuid.uuid4().hex[:12], plan=plan_json,
                          worker=worker or self._next_worker(),
                          deps=deps, schema=plan.schema, kind=kind,
                          bucket=bucket, stats_key=stats_key)
        frags_out.append(f)
        return f

    def _split(self, p: L.LogicalPlan,
               frags: list[QueryFragment]) -> L.LogicalPlan:
        """Post-order: replace distributable subtrees with fragment scans;
        return the plan the root fragment executes."""
        if isinstance(p, L.Aggregate) and _is_local(p.input) and \
                not any(a.distinct for a in p.aggs) and \
                all(a.func in _DECOMPOSABLE for a in p.aggs):
            return self._split_aggregate(p, frags)
        # recurse into children; large local subtrees under joins become
        # their own (partitioned) fragments
        for name in ("input", "left", "right"):
            ch = getattr(p, name, None)
            if isinstance(ch, L.LogicalPlan):
                setattr(p, name, self._split(ch, frags))
        if isinstance(p, L.Union):
            p.inputs = [self._split(c, frags) for c in p.inputs]
        if isinstance(p, L.Join):
            shuffled = self._try_shuffle_join(p, frags)
            if shuffled is not None:
                return shuffled
            for name in ("left", "right"):
                ch = getattr(p, name)
                if _is_local(ch) and not isinstance(ch, L.Values):
                    setattr(p, name, self._scan_fragments(ch, frags))
        return p

    # --- hash-partitioned shuffle joins ---

    def _try_shuffle_join(self, p: L.Join,
                          frags: list[QueryFragment]
                          ) -> Optional[L.LogicalPlan]:
        """Join over two local subtrees -> per-bucket join fragments reading
        bucket slices of Exchange-partitioned side fragments; returns the
        Union the consumer executes, or None when ineligible (the caller
        falls back to the union-of-scan-fragments shape)."""
        if not self.shuffle_enabled or len(self.workers) < 2 \
                or self.shuffle_buckets < 2:
            return None
        if p.join_type not in _SHUFFLE_JOIN_TYPES or not p.left_keys:
            return None
        for side in (p.left, p.right):
            if not _is_local(side) or isinstance(side, L.Values) \
                    or side.schema is None:
                return None
        lkeys = _plain_key_indices(p.left_keys, p.left.schema)
        rkeys = _plain_key_indices(p.right_keys, p.right.schema)
        if lkeys is None or rkeys is None:
            return None
        # both sides must hash the same value domain: binder coercion casts
        # (non-Column keys) are already rejected above, this guards direct
        # Column pairs of unequal dtype
        for lk, rk in zip(p.left_keys, p.right_keys):
            if lk.dtype is None or rk.dtype is None or \
                    lk.dtype.id is not rk.dtype.id:
                return None
        B = self.shuffle_buckets
        lkey, rkey, lobs, robs = self._side_observations(p)
        # --- broadcast-vs-shuffle switch (observed stats only) ---
        bcast = self._choose_broadcast(p, lobs, robs)
        if bcast is not None:
            return self._broadcast_join(p, frags, bcast, lkey, rkey)
        # --- hot-key salting of a pathologically skewed exchange ---
        salt = self._choose_salt(p, B, lobs, robs)
        lsalt = rsalt = None
        if salt is not None:
            hot, S, probe_left = salt
            lsalt = (hot, S, "probe" if probe_left else "build")
            rsalt = (hot, S, "build" if probe_left else "probe")
            B_total = B + S - 1
        else:
            B_total = B
        left_frags = self._exchange_fragments(p.left, lkeys, B, frags,
                                              stats_key=lkey, salt=lsalt)
        right_frags = self._exchange_fragments(p.right, rkeys, B, frags,
                                               stats_key=rkey, salt=rsalt)
        join_scans: list[L.LogicalPlan] = []
        W = len(self.workers)
        placement = self._bucket_placement(B)
        for b in range(B_total):
            jb = L.Join(left=_bucket_union(left_frags, b, B_total,
                                           p.left.schema),
                        right=_bucket_union(right_frags, b, B_total,
                                            p.right.schema),
                        join_type=p.join_type,
                        left_keys=[_copy_expr(k) for k in p.left_keys],
                        right_keys=[_copy_expr(k) for k in p.right_keys],
                        residual=_copy_expr(p.residual))
            jb.schema = p.schema
            if salt is not None and b >= B:
                # salted extra buckets hold slices of the HOT bucket's work:
                # rotate them onto workers AFTER the one the hot bucket was
                # PLACED on (the weighted placement, not the bucket index —
                # a heterogeneous placement can put bucket `hot` anywhere),
                # or the split re-serializes on one worker (host-rotated,
                # not device-weighted: they are slices of ONE bucket, and
                # spreading across hosts is the whole point)
                hot_i = self.workers.index(placement[salt[0]])
                worker = self.workers[(hot_i + 1 + (b - B)) % W]
            else:
                worker = placement[b]
            jf = self._make_fragment(jb, frags, worker=worker,
                                     kind="join", bucket=b)
            join_scans.append(_frag_scan(jf))
        if salt is None and self.adaptive_enabled:
            self.adaptive_info.append({
                "strategy": "shuffle", "buckets": B,
                "total_shards": self.total_shards,
                "adaptive_source": "observed" if (lobs or robs)
                else "estimated"})
        if len(join_scans) == 1:
            return join_scans[0]
        u = L.Union(inputs=join_scans)
        u.schema = p.schema
        return u

    # --- distributed out-of-core GRACE (docs/out_of_core.md) ---

    def _try_grace_distributed(self, plan: L.LogicalPlan,
                               frags: list[QueryFragment]
                               ) -> Optional[L.LogicalPlan]:
        """Over-budget join tree -> per-bucket join fragments whose buckets
        ARE the GRACE partitions: exec/grace.py's partition scheme (key
        equivalence classes + anchor-analysis validity + budget-derived
        partition count) lifted to the fleet. Every partitioned leaf becomes
        Exchange fragments hash-routing into B buckets (streamed +
        spill-backed on the worker, cluster/exchange.py StreamingPut);
        replicated leaves ship whole; bucket b's join fragment unions bucket
        b of every partitioned side and runs wherever the device-weighted
        placement puts it. Returns the root plan, or None when the plan does
        not qualify — the caller falls back to the normal split (and the
        coordinator to the single-node demote ladder)."""
        from igloo_tpu.exec import grace
        gp = grace.find_grace_join(plan, self.budget_bytes)
        if gp is None:
            return None
        part = [lf for lf in gp.leaves if lf.key_col is not None]
        rep = [lf for lf in gp.leaves if lf.key_col is None]
        if not part:
            return None
        if any(lf.node.schema is None for lf in gp.leaves):
            return None
        for lf in part:
            # partitioned leaves must be shippable scan chains (the Exchange
            # fragment re-executes them partition-at-a-time on the worker)
            if not _is_local(lf.node) or isinstance(lf.node, L.Values):
                return None
        B = min(max(gp.n_parts, len(self.workers) * self.ppw),
                grace.MAX_GRACE_PARTITIONS)
        with tracing.span("grace.distributed", buckets=B,
                          partitioned=len(part), replicated=len(rep),
                          budget=int(self.budget_bytes)):
            leaf_sub: dict[int, tuple] = {}
            for lf in rep:
                f = self._make_fragment(L.copy_plan(lf.node), frags,
                                        deps=[], kind="scan")
                leaf_sub[id(lf.node)] = (False, [f])
            for lf in part:
                lfr = self._exchange_fragments(lf.node, [lf.key_col], B,
                                               frags)
                leaf_sub[id(lf.node)] = (True, lfr)

            def rebuild(n: L.LogicalPlan, b: int) -> L.LogicalPlan:
                if id(n) in leaf_sub:
                    bucketed, lfr = leaf_sub[id(n)]
                    if bucketed:
                        return _bucket_union(lfr, b, B, n.schema)
                    return _whole_union(lfr, n.schema)
                if isinstance(n, L.Filter):
                    f = L.Filter(input=rebuild(n.input, b),
                                 predicate=_copy_expr(n.predicate))
                    f.schema = n.schema
                    return f
                j = L.Join(left=rebuild(n.left, b),
                           right=rebuild(n.right, b),
                           join_type=n.join_type,
                           left_keys=[_copy_expr(k) for k in n.left_keys],
                           right_keys=[_copy_expr(k) for k in n.right_keys],
                           residual=_copy_expr(n.residual))
                j.schema = n.schema
                return j

            # the upper path splits at the aggregate: nodes BELOW it run
            # inside every bucket fragment (ahead of the partial aggregate),
            # nodes ABOVE it wrap the final merge in the root fragment
            above, below = gp.path, []
            partial_schema = partial_aggs = partial_names = final_plan = None
            if gp.agg is not None:
                ai = gp.path.index(gp.agg)
                above, below = gp.path[:ai], gp.path[ai + 1:]
                partial_schema, partial_aggs, partial_names, final_plan = \
                    decompose_aggregate(gp.agg)
            placement = self._bucket_placement(B)
            bucket_scans: list[L.LogicalPlan] = []
            for b in range(B):
                body = _rewrap(below, rebuild(gp.root, b))
                if gp.agg is not None:
                    body = partial_aggregate_node(
                        gp.agg, body, partial_schema, partial_aggs,
                        partial_names)
                bf = self._make_fragment(body, frags, worker=placement[b],
                                         kind="join", bucket=b)
                bucket_scans.append(_frag_scan(bf))
            if len(bucket_scans) == 1:
                merged: L.LogicalPlan = bucket_scans[0]
            else:
                merged = L.Union(inputs=bucket_scans)
                merged.schema = partial_schema if gp.agg is not None \
                    else gp.root.schema
            root = final_merge_plan(gp.agg, merged, final_plan) \
                if gp.agg is not None else merged
            root = _rewrap(above, root)
        tracing.counter("grace.remote_partitions", B)
        self.grace_info = {
            "buckets": B, "partitioned_leaves": len(part),
            "replicated_leaves": len(rep),
            "budget_bytes": int(self.budget_bytes)}
        if self.adaptive_enabled:
            self.adaptive_info.append({
                "strategy": "grace_distributed", "buckets": B,
                "partitioned_leaves": len(part),
                "adaptive_source": "estimated"})
        return root

    # --- adaptive decisions (docs/adaptive.md) ---

    def _side_observations(self, p: L.Join):
        """(left digest, right digest, left obs, right obs) for the join's
        side fingerprints; digests tag this query's fragments so the
        coordinator records what actually happened under the same keys the
        NEXT planning reads."""
        if not self.adaptive_enabled:
            return None, None, None, None
        from igloo_tpu.exec.hints import adaptive_store, digest_key, plan_fp
        store = adaptive_store()
        out = []
        for side in (p.left, p.right):
            fp = plan_fp(side)
            if fp is None:
                out.extend([None, None])
            else:
                out.extend([digest_key(fp), store.observed(fp)])
        if out[0] is not None and out[0] == out[2]:
            # self-join: both sides share one fingerprint, so per-side
            # recording would SUM the two sides into one record (2x rows,
            # merged sketches) — a systematic bias, not tolerable staleness.
            # Skip observation and recording for this join entirely.
            return None, None, None, None
        return out[0], out[2], out[1], out[3]

    @staticmethod
    def _replicable(jt: JoinType, build_left: bool) -> bool:
        """True when replicating the build side cannot duplicate output:
        build-side unmatched rows are never emitted for these types, and
        probe rows still appear exactly once (same validity rule as the mesh
        tier's broadcast join, parallel/shuffle.py)."""
        if jt is JoinType.INNER:
            return True
        if jt is JoinType.LEFT:
            return not build_left
        if jt is JoinType.RIGHT:
            return build_left
        if jt in (JoinType.SEMI, JoinType.ANTI):
            return not build_left   # build is always the right side
        return False                # FULL: both sides preserved

    @staticmethod
    def _obs_bytes(side: L.LogicalPlan, obs: Optional[dict]) -> Optional[int]:
        """Observed side size in bytes: exchange result bytes when recorded,
        else observed rows x estimated row width."""
        if not obs:
            return None
        if obs.get("bytes"):
            return int(obs["bytes"])
        if obs.get("rows") is not None:
            from igloo_tpu.exec.hints import row_width_bytes
            return int(obs["rows"]) * row_width_bytes(side.schema.fields)
        return None

    def _choose_broadcast(self, p: L.Join, lobs, robs) -> Optional[str]:
        """"left"/"right" build side to replicate, or None. Fires only on
        OBSERVED sizes: replicating on a bad estimate ships build x W bytes,
        while a missed broadcast merely keeps the exchange — asymmetric risk,
        so the first run always observes.

        Two-level composition: this rule decides HOST-level replication
        (W - 1 extra network copies), independently of the mesh tier's
        `should_broadcast` (parallel/shuffle.py), which decides CHIP-level
        distribution of whatever one worker holds. They cannot
        double-broadcast: a side replicated here arrives on each worker
        once, and the worker's mesh then either all-gathers that one copy
        across its chips (chip broadcast) or hash-shuffles it (chip
        exchange) — each level moves only its own minimum, and salting
        stays a fragment-level concern (the mesh tier's escape hatch is
        broadcast, see PATHOLOGICAL SKEW RULE)."""
        if not self.adaptive_enabled:
            return None
        lb = self._obs_bytes(p.left, lobs)
        rb = self._obs_bytes(p.right, robs)
        if lb is None or rb is None:
            return None
        W = len(self.workers)
        floor = 64 * 1024 * W  # tiny build sides always broadcast
        cand = []
        if self._replicable(p.join_type, True) and \
                lb * (W - 1) <= max(rb, floor):
            cand.append(("left", lb))
        if self._replicable(p.join_type, False) and \
                rb * (W - 1) <= max(lb, floor):
            cand.append(("right", rb))
        if not cand:
            return None
        return min(cand, key=lambda c: c[1])[0]

    def _choose_salt(self, p: L.Join, B: int, lobs, robs):
        """(hot_bucket, S, probe_is_left) when one side's skew sketch crossed
        the pathological bound at THIS bucket count and the other side may
        replicate, else None."""
        if not self.adaptive_enabled or B < 2:
            return None
        from igloo_tpu.parallel.shuffle import pathological_share
        bound = pathological_share(B)
        env = os.environ.get("IGLOO_SALT_BUCKETS")
        S = int(env) if env else max(2, len(self.workers))
        for obs, probe_left in ((lobs, True), (robs, False)):
            if not obs or obs.get("max_share") is None or \
                    obs.get("hot_bucket") is None:
                continue
            if obs.get("nbuckets") != B:
                continue  # sketch taken at another bucket count: not mappable
            if obs["max_share"] <= bound:
                continue
            if not self._replicable(p.join_type, build_left=not probe_left):
                continue
            self.adaptive_info.append({
                "strategy": "salted", "buckets": B, "salt": S,
                "hot_bucket": int(obs["hot_bucket"]),
                "probe": "left" if probe_left else "right",
                "max_share": round(float(obs["max_share"]), 4),
                "adaptive_source": "observed"})
            tracing.counter("adaptive.salted")
            from igloo_tpu.cluster import events
            events.emit("exchange_salted", hot_bucket=int(obs["hot_bucket"]),
                        salt=S, max_share=round(float(obs["max_share"]), 4))
            return int(obs["hot_bucket"]), S, probe_left
        return None

    def _broadcast_join(self, p: L.Join, frags: list[QueryFragment],
                        build_side: str, lkey, rkey) -> L.LogicalPlan:
        """Replicate the build side instead of exchanging both: probe scan
        fragments keep their data in place, one join fragment per probe
        fragment runs CO-LOCATED with it and fetches the (small) build
        result — the only bytes that move."""
        build_left = build_side == "left"
        build = p.left if build_left else p.right
        probe = p.right if build_left else p.left
        build_frags = self._side_fragments(
            build, frags, stats_key=lkey if build_left else rkey)
        probe_frags = self._side_fragments(
            probe, frags, stats_key=rkey if build_left else lkey)
        tracing.counter("adaptive.broadcast")
        from igloo_tpu.cluster import events
        events.emit("broadcast_join", build=build_side,
                    probe_fragments=len(probe_frags))
        self.adaptive_info.append({
            "strategy": "broadcast", "build": build_side,
            "probe_fragments": len(probe_frags),
            "adaptive_source": "observed"})
        join_scans: list[L.LogicalPlan] = []
        for pf in probe_frags:
            bunion = _whole_union(build_frags, build.schema)
            pscan = _frag_scan(pf)
            left, right = (bunion, pscan) if build_left else (pscan, bunion)
            jb = L.Join(left=left, right=right, join_type=p.join_type,
                        left_keys=[_copy_expr(k) for k in p.left_keys],
                        right_keys=[_copy_expr(k) for k in p.right_keys],
                        residual=_copy_expr(p.residual))
            jb.schema = p.schema
            jf = self._make_fragment(jb, frags, worker=pf.worker, kind="join")
            join_scans.append(_frag_scan(jf))
        if len(join_scans) == 1:
            return join_scans[0]
        u = L.Union(inputs=join_scans)
        u.schema = p.schema
        return u

    def _side_fragments(self, side: L.LogicalPlan,
                        frags: list[QueryFragment],
                        stats_key: Optional[str] = None
                        ) -> list[QueryFragment]:
        """Plain (un-exchanged) fragments for a join side, one per scan
        partition set."""
        out = []
        for part in self._partition_sets(side):
            sub = _with_partition(side, part) if part else L.copy_plan(side)
            out.append(self._make_fragment(sub, frags, deps=[], kind="scan",
                                           stats_key=stats_key))
        return out

    def _exchange_fragments(self, side: L.LogicalPlan, keys: list[int],
                            buckets: int,
                            frags: list[QueryFragment],
                            stats_key: Optional[str] = None,
                            salt: Optional[tuple] = None
                            ) -> list[QueryFragment]:
        """One Exchange-rooted fragment per scan partition set of `side`.
        `salt` = (hot_bucket, S, role) adds the salted-bucket spread/
        replication at the worker's partition step (cluster/exchange.py)."""
        out = []
        for part in self._partition_sets(side):
            sub = _with_partition(side, part) if part else L.copy_plan(side)
            ex = L.Exchange(input=sub, keys=list(keys), buckets=buckets)
            if salt is not None:
                ex.salt_bucket, ex.salt, ex.salt_role = salt
            ex.schema = sub.schema
            out.append(self._make_fragment(ex, frags, deps=[],
                                           kind="exchange",
                                           stats_key=stats_key))
        return out

    def _scan_fragments(self, subtree: L.LogicalPlan,
                        frags: list[QueryFragment]) -> L.LogicalPlan:
        """Partition a local subtree across workers; consumer unions results."""
        parts = self._partition_sets(subtree)
        if len(parts) <= 1:
            f = self._make_fragment(subtree, frags, deps=[])
            return _frag_scan(f)
        children = []
        for part in parts:
            f = self._make_fragment(_with_partition(subtree, part), frags,
                                    deps=[])
            children.append(_frag_scan(f))
        u = L.Union(inputs=children)
        u.schema = subtree.schema
        return u

    def _partition_sets(self, subtree: L.LogicalPlan) -> list[tuple[int, ...]]:
        sc = _subtree_scan(subtree)
        if sc is None or sc.provider is None:
            return [()]
        try:
            n_parts = sc.provider.num_partitions()
        except Exception:
            n_parts = 1
        n_frag = min(len(self.workers) * self.ppw, max(n_parts, 1))
        if n_parts <= 1 or n_frag <= 1:
            return [()]
        return [tuple(range(i, n_parts, n_frag)) for i in range(n_frag)]

    def _split_aggregate(self, agg: L.Aggregate,
                         frags: list[QueryFragment]) -> L.LogicalPlan:
        """agg over a local subtree -> per-partition partial fragments +
        final merge plan (returned for the parent fragment to execute)."""
        parts = self._partition_sets(agg.input)
        partial_schema, partial_aggs, partial_names, final_plan = \
            decompose_aggregate(agg)

        children = []
        for part in parts:
            sub = _with_partition(agg.input, part) if part else \
                L.copy_plan(agg.input)
            node = partial_aggregate_node(agg, sub, partial_schema,
                                          partial_aggs, partial_names)
            f = self._make_fragment(node, frags, deps=[])
            children.append(_frag_scan(f))
        if len(children) == 1:
            merged: L.LogicalPlan = children[0]
        else:
            merged = L.Union(inputs=children)
            merged.schema = partial_schema
        return final_merge_plan(agg, merged, final_plan)


def decompose_aggregate(agg: L.Aggregate):
    """Decompose a DECOMPOSABLE aggregate into per-chunk partials: returns
    (partial_schema, partial_aggs, partial_names, final_plan) where
    final_plan records how final_merge_plan recombines partial columns.
    Shared by the distributed planner, the chunked executor, and the
    out-of-core grace join (exec/grace.py)."""
    k = len(agg.group_exprs)
    partial_aggs: list[E.Aggregate] = []
    partial_names: list[str] = []
    final_plan: list[tuple] = []  # (kind, partial col index, orig agg)
    pi = k
    for a in agg.aggs:
        if a.func in (E.AggFunc.COUNT, E.AggFunc.COUNT_STAR):
            partial_aggs.append(a)
            partial_names.append(f"p{pi}")
            final_plan.append(("sum0", pi, a))
            pi += 1
        elif a.func is E.AggFunc.AVG:
            s = E.Aggregate(func=E.AggFunc.SUM, arg=a.arg)
            s.dtype = T.FLOAT64
            c = E.Aggregate(func=E.AggFunc.COUNT, arg=a.arg)
            c.dtype = T.INT64
            partial_aggs.extend([s, c])
            partial_names.extend([f"p{pi}", f"p{pi + 1}"])
            final_plan.append(("avg", pi, a))
            pi += 2
        else:  # SUM / MIN / MAX: associative
            partial_aggs.append(a)
            partial_names.append(f"p{pi}")
            final_plan.append(("assoc", pi, a))
            pi += 1

    partial_fields = [T.Field(n, g.dtype, True)
                      for n, g in zip(agg.group_names, agg.group_exprs)]
    partial_fields += [T.Field(n, a.dtype, True)
                       for n, a in zip(partial_names, partial_aggs)]
    return T.Schema(partial_fields), partial_aggs, partial_names, final_plan


def partial_aggregate_node(agg: L.Aggregate, inp: L.LogicalPlan,
                           partial_schema, partial_aggs,
                           partial_names) -> L.Aggregate:
    node = L.Aggregate(input=inp,
                       group_exprs=[g for g in agg.group_exprs],
                       group_names=list(agg.group_names),
                       aggs=list(partial_aggs),
                       agg_names=list(partial_names))
    node.schema = partial_schema
    return node


def final_merge_plan(agg: L.Aggregate, merged: L.LogicalPlan,
                     final_plan: list[tuple]) -> L.LogicalPlan:
    """Final re-aggregation of partial rows + projection back to the
    aggregate's declared output schema."""
    k = len(agg.group_exprs)
    # final merge: re-aggregate partials by the group columns
    final_groups = [_col(i, g.dtype, agg.group_names[i])
                    for i, g in enumerate(agg.group_exprs)]
    final_aggs: list[E.Aggregate] = []
    final_names: list[str] = []
    for kind, pi_, a in final_plan:
        if kind == "avg":
            for j, dt in ((pi_, T.FLOAT64), (pi_ + 1, T.INT64)):
                fa = E.Aggregate(func=E.AggFunc.SUM, arg=_col(j, dt))
                fa.dtype = dt
                final_aggs.append(fa)
                final_names.append(f"f{j}")
        else:
            fn = E.AggFunc.SUM if kind == "sum0" else a.func
            fa = E.Aggregate(func=fn, arg=_col(pi_, a.dtype))
            fa.dtype = a.dtype
            final_aggs.append(fa)
            final_names.append(f"f{pi_}")
    merge = L.Aggregate(input=merged, group_exprs=final_groups,
                        group_names=list(agg.group_names),
                        aggs=final_aggs, agg_names=final_names)
    merge.schema = T.Schema(
        [T.Field(n, g.dtype, True)
         for n, g in zip(agg.group_names, final_groups)] +
        [T.Field(n, a.dtype, True)
         for n, a in zip(final_names, final_aggs)])

    # project back to the aggregate's declared output (AVG division,
    # COUNT null->0 on empty-side sums)
    out_exprs: list[E.Expr] = [
        _col(i, g.dtype, agg.group_names[i])
        for i, g in enumerate(agg.group_exprs)]
    fi = k
    for kind, _pi, a in final_plan:
        if kind == "avg":
            s = _col(fi, T.FLOAT64)
            c = _col(fi + 1, T.INT64)
            zero = E.Literal(value=0)
            zero.dtype = T.INT64
            cast = E.Cast(operand=c, to=T.FLOAT64)
            cast.dtype = T.FLOAT64
            div = E.Binary(op=E.BinOp.DIV, left=s, right=cast)
            div.dtype = T.FLOAT64
            isz = E.Binary(op=E.BinOp.EQ, left=c, right=zero)
            isz.dtype = T.BOOL
            nul = E.Literal(value=None, literal_type=T.FLOAT64)
            nul.dtype = T.FLOAT64
            case = E.Case(whens=[(isz, nul)], else_=div)
            case.dtype = T.FLOAT64
            out_exprs.append(case)
            fi += 2
        elif kind == "sum0":
            s = _col(fi, T.INT64)
            zero = E.Literal(value=0)
            zero.dtype = T.INT64
            isn = E.IsNull(operand=s)
            isn.dtype = T.BOOL
            case = E.Case(whens=[(isn, zero)], else_=s)
            case.dtype = T.INT64
            out_exprs.append(case)
            fi += 1
        else:
            out_exprs.append(_col(fi, a.dtype))
            fi += 1
    proj = L.Project(input=merge, exprs=out_exprs,
                     names=list(agg.schema.names))
    proj.schema = agg.schema
    return proj


def _frag_refs(plan_json: dict) -> list[dict]:
    """All Scan nodes referencing fragment results, by tree walk."""
    out = []

    def walk(d):
        if isinstance(d, dict):
            if d.get("t") == "Scan" and str(d.get("table", "")).startswith(
                    FRAG_PREFIX):
                out.append(d)
            for v in d.values():
                walk(v)
        elif isinstance(d, list):
            for v in d:
                walk(v)
    walk(plan_json)
    return out
