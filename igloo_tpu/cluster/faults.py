"""Deterministic fault injection for the cluster tier.

The failure-model layer (cluster/rpc.py policies, coordinator recovery,
query deadlines) is only trustworthy if its paths actually run, and real
clusters fail too rarely — and too irreproducibly — to exercise them. This
module injects failures at named points wrapped around every server handler
(``worker.do_action.<type>``, ``worker.do_get``, ``coordinator.do_action.
<type>``, ...), around the client-side RPC policy (``client.action.
<name>``, ``client.do_get``), and inside the serving front door
(``serving.admit`` on every submission — an injected error counts as a
shed — and ``serving.dequeue`` on every admission grant), driven by a spec:

    IGLOO_FAULTS="<point-glob>:<mode>:<prob>[:<count>][,<rule>...]"

- ``point-glob``  fnmatch glob over injection-point names
                  (``worker.do_action.execute_fragment``, ``worker.*``, ...)
- ``mode``        ``error``  raise FlightUnavailableError (retryable class)
                  ``delay``  sleep IGLOO_FAULTS_DELAY_S (default 0.05 s)
                  ``hang``   sleep IGLOO_FAULTS_HANG_S (default 3600 s) — the
                             "TCP accepts, never answers" worker
                  ``drop-mid-stream``  for the streaming points
                             (``worker.do_get``, ``coordinator.do_get``):
                             serve one batch, then fail the stream
                  ``corrupt``  for the data points that pass payload bytes
                             through ``corrupt_data()`` (``storage.
                             get_range``): flip bytes in the returned
                             buffer — silent bitrot, same etag
- ``prob``        per-call injection probability in [0, 1]
- ``count``       optional cap on total injections for the rule

Runs REPLAY: each rule draws from its own ``random.Random`` seeded from
(IGLOO_FAULTS_SEED, rule index, rule text), so the Nth call matching a rule
gets the same decision in every run — chaos tests can assert exact fault
schedules instead of flaking.

Off by default and zero-overhead when unset: with no spec installed,
``inject()`` is one module-global ``is None`` check. Servers re-read the
environment at construction (``refresh()``), so in-process test clusters
created after ``monkeypatch.setenv`` see the spec without a respawn.
"""
from __future__ import annotations

import fnmatch
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from igloo_tpu.utils import tracing

FAULTS_ENV = "IGLOO_FAULTS"
SEED_ENV = "IGLOO_FAULTS_SEED"
DELAY_ENV = "IGLOO_FAULTS_DELAY_S"
HANG_ENV = "IGLOO_FAULTS_HANG_S"

MODES = ("error", "delay", "hang", "drop-mid-stream", "corrupt")


class FaultSpecError(ValueError):
    """Malformed IGLOO_FAULTS spec (raised at install time, never mid-RPC)."""


@dataclass
class FaultRule:
    pattern: str
    mode: str
    prob: float
    count: Optional[int] = None        # remaining injection budget
    rng: object = None                 # per-rule random.Random
    fired: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def decide(self) -> bool:
        """One seeded draw; True = inject (and consume budget)."""
        with self._lock:
            if self.count is not None and self.fired >= self.count:
                return False
            if self.rng.random() >= self.prob:
                return False
            self.fired += 1
            return True


class FaultInjector:
    def __init__(self, spec: str, seed: int = 0,
                 delay_s: Optional[float] = None,
                 hang_s: Optional[float] = None):
        self.spec = spec
        self.seed = seed
        self.delay_s = delay_s if delay_s is not None else \
            float(os.environ.get(DELAY_ENV, "0.05"))
        self.hang_s = hang_s if hang_s is not None else \
            float(os.environ.get(HANG_ENV, "3600"))
        self.rules = self._parse(spec, seed)

    @staticmethod
    def _parse(spec: str, seed: int) -> list:
        import random
        rules = []
        for i, part in enumerate(p.strip() for p in spec.split(",")):
            if not part:
                continue
            bits = part.split(":")
            if len(bits) not in (3, 4):
                raise FaultSpecError(
                    f"bad fault rule {part!r}: want "
                    "<glob>:<mode>:<prob>[:<count>]")
            pattern, mode, prob = bits[0], bits[1], bits[2]
            if mode not in MODES:
                raise FaultSpecError(
                    f"bad fault mode {mode!r} in {part!r} "
                    f"(one of {'|'.join(MODES)})")
            try:
                p_ = float(prob)
            except ValueError:
                raise FaultSpecError(f"bad probability {prob!r} in {part!r}")
            if not 0.0 <= p_ <= 1.0:
                raise FaultSpecError(f"probability {p_} not in [0,1]")
            count = None
            if len(bits) == 4:
                try:
                    count = int(bits[3])
                except ValueError:
                    raise FaultSpecError(f"bad count {bits[3]!r} in {part!r}")
            # string seeds hash deterministically in random.Random — every
            # process with the same spec+seed replays the same schedule
            rng = random.Random(f"{seed}:{i}:{part}")
            rules.append(FaultRule(pattern=pattern, mode=mode, prob=p_,
                                   count=count, rng=rng))
        return rules

    def match(self, point: str, stream: bool = False,
              corrupt: bool = False) -> Optional[FaultRule]:
        """First firing rule for `point`. Stream points only take
        drop-mid-stream rules, data points only corrupt rules; call points
        take everything else."""
        for r in self.rules:
            if (r.mode == "drop-mid-stream") is not stream:
                continue
            if (r.mode == "corrupt") is not corrupt:
                continue
            if fnmatch.fnmatchcase(point, r.pattern) and r.decide():
                return r
        return None


_INJECTOR: Optional[FaultInjector] = None
_LOADED = False
# serializes the INSTALLERS only: refresh() can run from any server
# constructor (Flight handler threads re-registering in tests) and must
# swap (_INJECTOR, _LOADED) as a unit. Readers (inject()/active()) stay
# lockless by design — one atomic reference load, stale for at most the
# call that raced the install.
_faults_lock = threading.Lock()


def refresh() -> Optional[FaultInjector]:
    """(Re-)install the injector from the environment. Called by server
    constructors and the CLI entries; tests that set IGLOO_FAULTS after
    import call this (or construct a server, which does)."""
    global _INJECTOR, _LOADED
    spec = os.environ.get(FAULTS_ENV, "")
    inj = FaultInjector(spec, int(os.environ.get(SEED_ENV, "0"))) \
        if spec else None
    with _faults_lock:
        _INJECTOR = inj
        _LOADED = True
    return inj


def install(spec: str, seed: int = 0, **kw) -> FaultInjector:
    """Programmatic install (tests); `clear()` to remove."""
    global _INJECTOR, _LOADED
    inj = FaultInjector(spec, seed, **kw)
    with _faults_lock:
        _INJECTOR = inj
        _LOADED = True
    return inj


def clear() -> None:
    global _INJECTOR, _LOADED
    with _faults_lock:
        _INJECTOR = None
        _LOADED = True


def active() -> bool:
    return _INJECTOR is not None


def inject(point: str) -> None:
    """The per-call injection hook. No spec installed = one None check."""
    inj = _INJECTOR
    if inj is None:
        if _LOADED:
            return
        try:
            inj = refresh()
        except FaultSpecError as ex:
            # lazy load happens inside an RPC (a client-only process never
            # runs a server constructor): a malformed spec must not surface
            # as a failure of an unrelated query — disable with one loud
            # line. Servers and CLIs still fail fast: their refresh() at
            # construction raises at install time.
            import sys
            print(f"igloo faults: ignoring malformed {FAULTS_ENV}: {ex}",
                  file=sys.stderr)
            clear()
            return
        if inj is None:
            return
    rule = inj.match(point)
    if rule is None:
        return
    tracing.counter("faults.injected")
    if rule.mode == "delay":
        time.sleep(inj.delay_s)
        return
    if rule.mode == "hang":
        time.sleep(inj.hang_s)
        return
    import pyarrow.flight as flight
    raise flight.FlightUnavailableError(
        f"igloo fault injection: {rule.pattern}:{rule.mode} at {point}")


def corrupt_data(point: str, data: bytes) -> bytes:
    """Apply a matching ``corrupt`` rule to a payload: flips a byte run in
    the middle of the buffer (silent bitrot — the object's etag is
    untouched, so only checksum/parse validation can catch it). No rule, no
    copy; empty payloads pass through untouched."""
    inj = _INJECTOR
    if inj is None or not data:
        return data
    rule = inj.match(point, corrupt=True)
    if rule is None:
        return data
    tracing.counter("faults.injected")
    buf = bytearray(data)
    start = len(buf) // 2
    for i in range(start, min(start + 64, len(buf))):
        buf[i] ^= 0xFF
    return bytes(buf)


def wrap_stream(point: str, batches: Iterator) -> Iterator:
    """Apply a drop-mid-stream rule to a batch stream: decided ONCE when the
    stream opens (seeded draw), the wrapped stream serves exactly one batch
    and then dies the way a vanished peer does."""
    inj = _INJECTOR
    if inj is None:
        return batches
    rule = inj.match(point, stream=True)
    if rule is None:
        return batches

    def dropped():
        import pyarrow.flight as flight
        tracing.counter("faults.injected")
        for b in batches:
            yield b
            break
        raise flight.FlightUnavailableError(
            f"igloo fault injection: {rule.pattern}:drop-mid-stream "
            f"at {point}")
    return dropped()
