"""Worker daemon: registers with the coordinator, heartbeats, executes
fragments, and serves results to peers.

Parity: the reference worker (crates/worker/src/main.rs:14-52 — uuid identity,
register, 5 s heartbeat loop, task service) — but where the reference's
`execute_task` logs and returns "SUBMITTED" and its shuffle fetch returns empty
bytes (crates/worker/src/service.rs:14-32, both stubs), this worker REALLY
executes: it deserializes the fragment's plan, resolves dependency results
(from its own store or by fetching from the PEER worker that produced them —
the worker<->worker transport the reference declared via GetDataForTask and
never built), runs the plan on its local device tier, and serves the result as
an Arrow Flight stream.

Results live in a bytes-budgeted `FragmentStore` (cluster/exchange.py): an
`Exchange`-rooted fragment hash-partitions its result at store time, and
`do_get` tickets address either a whole fragment or ONE bucket slice — the
per-bucket transport that lets a join fragment fetch only its bucket of each
peer's result instead of the whole table. Transfers stream record-batch-wise
in both directions.

Transport is Arrow Flight end-to-end (one stack for control actions and data
streams) instead of the reference's parallel tonic-gRPC + Flight pair.

Flight serves every RPC on its own thread; `execute_fragment` actions are
additionally bounded by a slot semaphore (IGLOO_WORKER_SLOTS, default a
small multiple of the local device count) so concurrent fragment executions
queue instead of racing the device into OOM — the worker-side half of the
serving story (docs/serving.md). `worker.slots_busy` gauges the occupancy.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.catalog import Catalog, MemTable
from igloo_tpu.cluster import events, exchange, faults, protocol, serde
from igloo_tpu.cluster.fragment import (FRAG_PREFIX, _frag_refs,
                                        _subtree_scan, _with_partition)
from igloo_tpu.exec import encoded
from igloo_tpu.storage import prefetch as _prefetch
from igloo_tpu.cluster import rpc
from igloo_tpu.cluster.rpc import flight_action, flight_stream_batches
from igloo_tpu.cluster.rpc import normalize as _normalize
from igloo_tpu.errors import IglooError
from igloo_tpu.plan import logical as L
from igloo_tpu.utils import flight_recorder, timeseries, tracing


# lock discipline (checked by igloo-lint lock-discipline): Flight serves
# every RPC on its own thread, so two concurrent execute_fragment actions
# race the WorkerServer's lazy mesh resolution — `_mesh`/`_mesh_setting`
# must be read and written under the server lock (the fragment store has its
# own internal lock, see cluster/exchange.py)
_GUARDED_BY = {"_lock": ("_mesh", "_mesh_setting")}


#: worker-side fragment-execution slot bound: Flight runs every RPC on its
#: own thread, so without this two concurrent execute_fragment actions race
#: each other into device OOM. Default = a small multiple of the number of
#: INDEPENDENT execution units the worker has: local devices for a
#: single-device worker (fragments on one device mostly serialize on it
#: anyway; a little oversubscription overlaps host-side decode with device
#: work), but local_devices / mesh_devices for a MESH worker — a sharded
#: fragment occupies every chip of the mesh at once, so 2 x device_count
#: slots would admit 16 whole-mesh fragments against HBM sized for ~2 and
#: invalidate the coordinator's per-host HBM predictions (docs/serving.md).
WORKER_SLOTS_ENV = "IGLOO_WORKER_SLOTS"


def _default_slots(mesh_devices: int = 1) -> int:
    try:
        import jax
        local = jax.local_device_count()
    except Exception:
        return 2
    units = max(1, local // max(mesh_devices, 1))
    return max(2, 2 * units)


def _plan_wants_mesh(plan) -> bool:
    """True when a fragment's plan carries a blocking operator the LOCAL mesh
    tier accelerates (join / aggregate / set op / distinct / window / sort):
    those route through the ShardedExecutor so the fragment runs D-way across
    the worker's chips — the inner level of the two-level parallelism
    (docs/distributed.md). Scan/filter/project (and Exchange-rooted partition)
    fragments stay single-device: their output is gathered host-side for the
    store anyway, and the sharded tier's padded per-device capacities only
    add upload overhead there."""
    return any(isinstance(n, (L.Join, L.Aggregate, L.SetOpJoin, L.Distinct,
                              L.Window, L.Sort))
               for n in L.walk_plan(plan))


def _dep_key(frag_id: str, bucket) -> str:
    """FragmentStore key for a peer-fetched dependency slice. With
    bucket=None this is both the whole-result key and the prefix every slice
    of that dependency shares (how `release` finds them); real fragment ids
    are hex, so `__dep_*` keys cannot collide with produced results."""
    base = f"__dep_{frag_id}:"
    return base if bucket is None else f"{base}{bucket}"


class _OverlayCatalog:
    """Base catalog + per-fragment `__frag_*` dependency tables."""

    def __init__(self, base: Catalog, extra: dict):
        self._base = base
        self._extra = extra

    def get(self, name: str):
        key = name.lower()
        if key in self._extra:
            return self._extra[key]
        return self._base.get(name)


class WorkerServer(flight.FlightServerBase):
    """Flight server half of the worker. Thread-safe: Flight handles each RPC
    on its own thread; the fragment store and engine state are lock-guarded."""

    def __init__(self, location: str, worker_id: Optional[str] = None,
                 use_jit: bool = True, mesh: object = "default",
                 store_budget_bytes: Optional[int] = None,
                 slots: Optional[int] = None, **kw):
        mw = rpc.server_middleware()
        if mw is not None:
            kw.setdefault("middleware", mw)
        ah = rpc.server_auth_handler()
        if ah is not None:
            kw.setdefault("auth_handler", ah)
        rpc.warn_if_open_bind(location.split("://")[-1].rsplit(":", 1)[0],
                              "worker")
        # pick up IGLOO_FAULTS set after import (in-process test clusters)
        faults.refresh()
        super().__init__(location, **kw)
        self.worker_id = worker_id or uuid.uuid4().hex[:12]
        self.advertise: str = location
        self._catalog = Catalog()
        # own results AND peer-fetched dependency slices (under `__dep_*`
        # keys): one bucketed, bytes-budgeted, spill-backed store, so fetched
        # slices count against the same RSS budget as produced results
        self._store = exchange.FragmentStore(store_budget_bytes)
        self._use_jit = use_jit
        self._jit_cache: dict = {}
        self._lock = threading.Lock()
        self._mesh_setting = mesh  # same rule as QueryEngine (resolve_mesh)
        self._mesh = None
        # devices one fragment will occupy (the LOCAL mesh tier): reported to
        # the coordinator at registration/heartbeat so the planner sizes
        # bucket counts with hosts and shard counts with chips
        # (docs/distributed.md "Two-level topology"). Computed once from the
        # SETTING — the lazily resolved mesh spans the same devices.
        from igloo_tpu.parallel.mesh import mesh_device_count
        self.mesh_devices = mesh_device_count(mesh)
        from igloo_tpu.exec.cache import BatchCache
        self._batch_cache = BatchCache(1 << 30)
        # fragment-execution slot bound (env > constructor > device-derived
        # default): concurrent execute_fragment RPCs queue on the semaphore
        # instead of racing the device into OOM (docs/serving.md)
        env = os.environ.get(WORKER_SLOTS_ENV)
        if env:
            slots = int(env)
        if slots is None:
            slots = _default_slots(self.mesh_devices)
        self.slots = max(1, slots)
        self._slots = threading.BoundedSemaphore(self.slots)

    # --- execution ---

    def _executor(self, plan=None):
        # multi-chip worker hosts row-shard fragments across their local
        # devices; same mesh-resolution rule as QueryEngine (so tests pin
        # DEFAULT_MESH and production configures via the constructor).
        # Lazy resolution holds the server lock: Flight runs each RPC on its
        # own thread, and two concurrent fragments must not resolve (and
        # assign) the mesh twice
        with self._lock:
            if self._mesh is None and self._mesh_setting is not None:
                from igloo_tpu.parallel.mesh import resolve_mesh
                self._mesh = resolve_mesh(self._mesh_setting)
                if self._mesh is None:
                    self._mesh_setting = None
            mesh = self._mesh
        if mesh is not None and (plan is None or _plan_wants_mesh(plan)):
            from igloo_tpu.parallel.executor import ShardedExecutor
            return ShardedExecutor(self._jit_cache, use_jit=self._use_jit,
                                   batch_cache=self._batch_cache,
                                   mesh=mesh)
        from igloo_tpu.exec.executor import Executor
        return Executor(self._jit_cache, use_jit=self._use_jit,
                        batch_cache=self._batch_cache)

    def _fetch_dep(self, frag_id: str, addr: str,
                   bucket: Optional[int] = None,
                   nbuckets: Optional[int] = None,
                   deadline: Optional[float] = None) -> pa.Table:
        # own store first: a co-located dependency (or its bucket slice) is a
        # zero-copy local read, not a transfer
        if frag_id in self._store:
            try:
                # partitioned slices are stored in carrier form
                # (cluster/exchange.py put) — widen at the consumption edge
                return encoded.decode_table(
                    self._store.get_table(frag_id, bucket, nbuckets))
            except (KeyError, ValueError) as ex:
                raise IglooError(f"DEP_UNAVAILABLE:{frag_id} local: {ex}")
        dep_key = _dep_key(frag_id, bucket)
        if dep_key in self._store:
            return self._store.get_table(dep_key)
        # peer fetch: the worker that executed the dependency streams it
        # batch-wise; an unreachable peer is reported with a marker the
        # coordinator recognizes (it requeues the dependency on a live
        # worker). `deadline` is the query's remaining budget (shipped by the
        # coordinator as a relative timeout_s) — a HUNG peer becomes
        # DEP_UNAVAILABLE at the deadline instead of wedging the fragment.
        try:
            with tracing.span("exchange.fetch", frag=frag_id,
                              bucket=bucket, addr=addr) as sp:
                ticket = exchange.make_ticket(frag_id, bucket, nbuckets)
                schema, batch_iter = flight_stream_batches(addr, ticket,
                                                           deadline=deadline)
                batches = []
                nbytes = 0
                for batch in batch_iter:
                    batches.append(batch)
                    nbytes += batch.nbytes
                    tracing.counter("exchange.fetch_rows", batch.num_rows)
                    tracing.counter("exchange.fetch_bytes", batch.nbytes)
                # fetch counters above price the WIRE (carrier) bytes; the
                # dep cache below holds the decoded table so co-located
                # dependents never re-widen
                table = encoded.decode_table(
                    pa.Table.from_batches(batches, schema=schema))
                sp.attrs.update(rows=table.num_rows, bytes=nbytes)
        except Exception as ex:
            raise IglooError(f"DEP_UNAVAILABLE:{frag_id} peer {addr}: {ex}")
        # keep the slice in the budgeted store: co-located dependents reuse
        # it instead of re-downloading (it may spill under memory pressure);
        # the coordinator's final "release" drops it
        self._store.put(dep_key, table)
        return table

    def _execute_fragment(self, frag_id: str, plan_json: dict,
                          addr_of: dict, deadline: Optional[float],
                          budget: Optional[int] = None) -> dict:
        """Execute one deserialized dispatch (protocol fields already parsed
        out by `_handle_execute_fragment` — this method is wire-format-free):
        resolve dependencies, run the plan, store the result, and return the
        fragment_stats report. A dispatch carrying `budget` is part of an
        OVERSIZED query (docs/out_of_core.md): Exchange fragments stream
        their scan piece-wise into per-bucket spill segments, and join
        fragments get the worker-local GRACE ladder for residual skew."""
        overlay: dict = {}
        input_rows = 0
        # per-fragment counter delta: thread-isolated, so concurrent
        # fragments on this worker report only their own transfers/compiles
        with tracing.counter_delta() as delta:
            t_dep0 = time.perf_counter()
            for ref in _frag_refs(plan_json):
                dep_id = ref["table"][len(FRAG_PREFIX):]
                name = ref["table"].lower()
                if name in overlay:
                    continue
                t = self._fetch_dep(dep_id, addr_of.get(dep_id, ""),
                                    ref.get("bucket"), ref.get("buckets"),
                                    deadline=deadline)
                input_rows += t.num_rows
                overlay[name] = MemTable(t)
            dep_s = time.perf_counter() - t_dep0
            catalog = _OverlayCatalog(self._catalog, overlay)
            plan = serde.plan_from_json(plan_json, catalog)
            partition = salt = None
            if isinstance(plan, L.Exchange):
                # fragment-root exchange: execute the input, hash-partition
                # the result at store time (per-bucket slices + metadata);
                # a salted exchange spreads/replicates the flagged hot
                # bucket (docs/adaptive.md)
                partition = (plan.keys, plan.buckets)
                if plan.salt_role is not None:
                    salt = (plan.salt_bucket, plan.salt, plan.salt_role)
                plan = plan.input
            t0 = time.perf_counter()
            streamed = None
            if partition is not None and budget:
                streamed = self._try_stream_exchange(
                    frag_id, plan, partition, salt, budget, deadline)
            if streamed is not None:
                ex, ent, nrows = streamed
                elapsed = time.perf_counter() - t0
            else:
                with tracing.span("fragment.execute") as sp:
                    ex = self._executor(plan)
                    table = self._run_plan(ex, plan, catalog, budget)
                    sp.attrs = {"rows": table.num_rows,
                                "mesh_devices": int(getattr(ex, "n_dev", 1))}
                nrows = table.num_rows
                elapsed = time.perf_counter() - t0
                with tracing.span("fragment.store"):
                    ent = self._store.put(frag_id, table,
                                          partition=partition, salt=salt)
        tracing.counter("worker.fragments")
        # local mesh-tier attribution: how many chips this fragment ran
        # across (1 = single-device) and its result rows per chip — the
        # per-fragment numbers last_metrics / EXPLAIN ANALYZE surface so the
        # two-level W x D parallelism is verifiable, not assumed
        mesh_devices = int(getattr(ex, "n_dev", 1))
        if mesh_devices > 1:
            tracing.counter("mesh.sharded_fragments")
        # the fragment_stats report, typed through the registry (None deltas
        # are omitted on the wire — consumers read sparsely); result_bytes is
        # the Arrow size of the stored result, which the coordinator's
        # adaptive recording sums per join side
        # a streamed (spilled) entry keeps only the resident tail in
        # `nbytes`; its true result size is the per-bucket meta sum
        result_bytes = ent.nbytes
        if getattr(ent, "bucket_files", None):
            result_bytes = sum(int(m.get("bytes", 0)) for m in ent.meta or [])
        out = protocol.FRAGMENT_STATS.build(
            id=frag_id, rows=nrows,
            elapsed_s=round(elapsed, 6), worker=self.worker_id,
            dep_fetch_s=round(dep_s, 6),
            input_rows=input_rows,
            mesh_devices=mesh_devices,
            mesh_rows_per_device=nrows // mesh_devices,
            result_bytes=result_bytes,
            h2d_bytes=delta.get("xfer.h2d_bytes"),
            d2h_bytes=delta.get("xfer.d2h_bytes"),
            jit_misses=delta.get("jit.miss"),
            cache_hits=delta.get("cache.hit"),
            exchange_rows=delta.get("exchange.fetch_rows"),
            exchange_bytes=delta.get("exchange.fetch_bytes"))
        if partition is not None:
            out["buckets"] = partition[1]
            # UNSALTED per-bucket rows: the coordinator's skew sketch must
            # see the key distribution, not the salted layout
            out["bucket_rows"] = ent.base_rows
            if salt is not None:
                out["salted"] = True
        return out

    def _try_stream_exchange(self, frag_id: str, plan, partition, salt,
                             budget: int, deadline: Optional[float]):
        """Streaming exchange under the out-of-core budget: instead of
        materializing the fragment's whole result and partitioning at store
        time (the classic path builds the full input in RAM first), execute
        the scan subtree ONE provider partition at a time — each piece fed
        by the storage prefetcher — and hash-route it straight into the
        store's per-bucket spill segments (cluster/exchange.py StreamingPut).
        Returns (executor, stored entry, rows) or None when the input has no
        multi-partition scan to stride, in which case the classic path runs
        unchanged."""
        sc = _subtree_scan(plan)
        if sc is None or sc.provider is None:
            return None
        if sc.partition:
            indices = [int(i) for i in sc.partition]
        else:
            try:
                indices = list(range(sc.provider.num_partitions()))
            except Exception:
                return None
        if len(indices) <= 1:
            return None
        keys, nbuckets = partition
        ex = self._executor(plan)
        handle = self._store.stream_put(frag_id, list(keys), nbuckets,
                                        salt=salt, budget_bytes=budget)
        items = [(sc.provider, i, sc.projection, sc.pushed_filters)
                 for i in indices]
        rows = 0
        try:
            with tracing.span("exchange.stream", frag=frag_id,
                              pieces=len(indices), buckets=nbuckets) as sp, \
                    _prefetch.scan_prefetch(items, deadline=deadline):
                for i in indices:
                    piece = _with_partition(plan, (i,))
                    t = ex.execute_to_arrow(piece)
                    rows += t.num_rows
                    handle.append(t)
                with tracing.span("fragment.store"):
                    ent = handle.finish()
                sp.attrs.update(rows=rows)
        except Exception:
            handle.abort()
            raise
        return ex, ent, rows

    def _run_plan(self, ex, plan, catalog, budget: Optional[int]):
        """Run one fragment plan, with the worker-local out-of-core ladder
        in front when the dispatch carries a budget: the planner's buckets
        are budget-sized by construction, so a join fragment whose inputs
        STILL exceed the per-worker budget (residual skew — one hot key
        class) recurses through the single-node GRACE loop locally instead
        of OOMing. Mesh-sharded fragments skip the ladder — row-sharding
        already bounds per-chip bytes."""
        if budget and int(getattr(ex, "n_dev", 1)) <= 1 and \
                any(isinstance(n, L.Join) for n in L.walk_plan(plan)):
            from igloo_tpu.exec.grace import (GraceJoinExecutor,
                                              find_grace_join)
            found = find_grace_join(plan, budget)
            if found is not None:
                tracing.counter("engine.grace_route")
                gx = GraceJoinExecutor(catalog, self._jit_cache,
                                       use_jit=self._use_jit,
                                       batch_cache=self._batch_cache,
                                       budget_bytes=budget)
                return gx.execute_to_arrow(plan, found)
        return ex.execute_to_arrow(plan)

    # --- Flight surface ---

    def _handle_execute_fragment(self, req: dict) -> dict:
        """The execute_fragment action body: parse the dispatch through the
        registry (a malformed payload fails HERE, naming the field), wait
        for an execution slot, run, and return the stats report. Every wire
        field is plucked in this one method — `_execute_fragment` below is
        wire-format-free."""
        disp = protocol.DISPATCH.parse(req)
        frag_id = disp["id"]
        addr_of: dict = {}
        for d in disp["deps"]:
            dep = protocol.DISPATCH_DEP.parse(d)
            addr_of[dep["id"]] = dep["addr"]
        # the coordinator ships the query's remaining budget as a RELATIVE
        # timeout (clocks differ across machines); anchor it here
        timeout_s = disp["timeout_s"]
        deadline = time.time() + timeout_s if timeout_s is not None else None
        # flight-recorder: the dispatch request carries the query's
        # trace context; this worker's span tree (rooted at a fresh
        # request scope — span hygiene for the reused gRPC thread) rides
        # back beside the fragment stats for the coordinator to stitch
        ctx = None
        if disp["trace"]:
            ctx = protocol.TRACE_CTX.parse(disp["trace"])
        trace = None
        if ctx is not None and flight_recorder.enabled():
            trace = flight_recorder.Trace(trace_id=ctx["trace_id"],
                                          qid=frag_id)
        with flight_recorder.request_scope(
                trace, "execute_fragment",
                proc=f"worker:{self.worker_id}",
                parent_id=ctx["parent_id"] if ctx is not None else None,
                frag=frag_id):
            # slot bound: a saturated worker must answer with the
            # WORKER_BUSY marker BEFORE the coordinator's dispatch RPC
            # deadline concludes it is hung (call_timeout_s=120 under a
            # query deadline, the stream bound without one) — so the
            # wait is capped at half a short bound, never the fragment's
            # full deadline. The coordinator REQUEUES a busy fragment
            # without evicting us.
            wait_s = min(timeout_s or 60.0, 60.0) / 2
            t0 = time.perf_counter()
            with tracing.span("worker.slot_wait") as sp:
                ok = self._slots.acquire(timeout=max(wait_s, 0.001))
                sp.attrs = {"acquired": ok}
            if not ok:
                tracing.counter("worker.slot_timeouts")
                raise flight.FlightUnavailableError(
                    f"WORKER_BUSY worker {self.worker_id}: all "
                    f"{self.slots} execution slots busy")
            tracing.gauge_add("worker.slots_busy", 1)
            tracing.histogram("worker.slot_wait_s",
                              time.perf_counter() - t0)
            try:
                out = self._execute_fragment(frag_id, disp["plan"], addr_of,
                                             deadline,
                                             budget=disp["budget"])
            except IglooError as ex:
                raise flight.FlightServerError(f"fragment failed: {ex}")
            finally:
                tracing.gauge_add("worker.slots_busy", -1)
                self._slots.release()
        if trace is not None:
            # read AFTER the scope exit — that is when the thread-local
            # span tree flushes into the trace
            out["spans"] = trace.spans()
        return out

    def do_action(self, context, action):
        faults.inject(f"worker.do_action.{action.type}")
        body = action.body.to_pybytes() if action.body is not None else b""
        req = json.loads(body) if body else {}
        if action.type == "execute_fragment":
            try:
                out = self._handle_execute_fragment(req)
            except protocol.ProtocolError as ex:
                raise flight.FlightServerError(f"bad dispatch payload: {ex}")
            return [json.dumps(out).encode()]
        if action.type == "register_table":
            rt = protocol.REGISTER_TABLE.parse(req)
            provider = serde.provider_from_spec(rt["spec"])
            self._catalog.register(rt["name"], provider)
            self._batch_cache.invalidate_table(rt["name"].lower())
            return [b"{}"]
        if action.type == "release":
            ids = protocol.RELEASE.parse(req)["ids"]
            deps = [k for k in self._store.ids()
                    if any(k.startswith(_dep_key(fid, None)) for fid in ids)]
            self._store.release(ids + deps)
            return [b"{}"]
        if action.type == "ping":
            own = [i for i in self._store.ids() if not i.startswith("__dep_")]
            return [json.dumps({"worker": self.worker_id,
                                "tables": sorted(self._catalog.names()),
                                "fragments": len(own),
                                "slots": self.slots,
                                "mesh_devices": self.mesh_devices}).encode()]
        if action.type == "metrics":
            # Prometheus text exposition of this worker process's registry
            # (raw bytes, not JSON — scrape via rpc.flight_action_raw)
            return [tracing.prometheus_text().encode()]
        if action.type == "metrics_history":
            # this process's watchtower sampler ring; the coordinator's
            # metrics_history action aggregates these across the fleet
            return [json.dumps(protocol.METRICS_HISTORY.build(
                samples=timeseries.samples())).encode()]
        raise flight.FlightServerError(f"unknown action {action.type}")

    def list_actions(self, context):
        # straight from the registry: the flight-actions checker holds this
        # surface and do_action's dispatch to the same declaration
        return protocol.action_doc("worker")

    def do_get(self, context, ticket):
        faults.inject("worker.do_get")
        try:
            frag_id, bucket, nbuckets = exchange.parse_ticket(ticket.ticket)
        except protocol.ProtocolError as ex:
            raise flight.FlightServerError(f"bad exchange ticket: {ex}")
        try:
            schema, batches = self._store.stream(frag_id, bucket, nbuckets)
        except KeyError:
            raise flight.FlightServerError(f"no such fragment: {frag_id}")
        except ValueError as ex:
            raise flight.FlightServerError(f"bad bucket request: {ex}")

        def counted():
            for b in batches:
                tracing.counter("exchange.rows", b.num_rows)
                tracing.counter("exchange.bytes", b.nbytes)
                yield b
        # encoded partition slices carry dictionary fields, which
        # GeneratorStream would silently drop — rpc.flight_stream_response
        # picks the stream shape that keeps both dictionaries and Flight
        # error statuses intact
        return rpc.flight_stream_response(
            schema, faults.wrap_stream("worker.do_get", counted()))


class Worker:
    """Worker lifecycle: serve + register + heartbeat (main.rs:14-52 parity)."""

    #: registration keeps retrying (with backoff) for this long before the
    #: worker gives up — a worker started BEFORE its coordinator must wait
    #: for it, not die instantly (the reference leaves this as a TODO
    #: comment, main.rs:37-38)
    REGISTER_TIMEOUT_ENV = "IGLOO_WORKER_REGISTER_TIMEOUT_S"

    def __init__(self, coordinator: str, host: str = "127.0.0.1",
                 port: int = 0, heartbeat_interval_s: float = 5.0,
                 use_jit: bool = True,
                 store_budget_bytes: Optional[int] = None,
                 register_timeout_s: Optional[float] = None):
        self.server = WorkerServer(f"grpc+tcp://{host}:{port}", use_jit=use_jit,
                                   store_budget_bytes=store_budget_bytes)
        self.server.advertise = f"grpc+tcp://{host}:{self.server.port}"
        self.coordinator = _normalize(coordinator)
        self.heartbeat_interval_s = heartbeat_interval_s
        if register_timeout_s is None:
            import os
            register_timeout_s = float(
                os.environ.get(self.REGISTER_TIMEOUT_ENV, "30"))
        self.register_timeout_s = register_timeout_s
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        # heartbeat-failure edge detector: log the FIRST consecutive failure
        # (and the recovery), never the repeats — a coordinator outage must
        # not turn every worker's log into a 5s-period spam stream
        self._hb_down = False
        # compile-cache entry names this worker knows the coordinator has
        # (seeded at registration, grown by pushes); touched only by the
        # registering thread and then the heartbeat thread, never both
        self._cache_known: set = set()
        # per-entry consecutive push failures: an entry that keeps failing
        # (e.g. bigger than the transport's message cap) is given up on after
        # a few beats instead of starving every entry that sorts after it
        self._push_failures: dict = {}
        # merge-named entries (the autotune tuning table) re-push whenever
        # their on-disk (size, mtime) moved past the last confirmed push —
        # unlike immutable XLA entries, "pushed once" is not "done"
        self._merge_pushed: dict = {}

    @property
    def address(self) -> str:
        return self.server.advertise

    def start(self) -> None:
        timeseries.start("worker")
        self._register()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._hb_thread.start()

    def _coordinator_action(self, name: str, payload: dict,
                            deadline: Optional[float] = None) -> dict:
        return flight_action(self.coordinator, name, payload,
                             deadline=deadline)

    def _register(self) -> None:
        """Register with bounded retry + backoff: each attempt already
        carries the RPC policy's own (small) retry budget, so this loop only
        spans the LONG wait — a coordinator that isn't up yet or is
        restarting. Fatal errors (auth, server-side rejection) fail fast."""
        policy = rpc.default_policy()
        deadline = time.time() + self.register_timeout_s
        attempt = 0
        while True:
            try:
                # the give-up deadline bounds each attempt's gRPC timeout
                # too: against a HUNG coordinator (accepts, never answers)
                # one un-deadlined attempt would otherwise block
                # call_timeout_s x (1 + retries) — minutes past the
                # documented register_timeout_s
                resp = self._coordinator_action(
                    "register_worker",
                    serde.worker_info_to_json(
                        self.server.worker_id, self.server.advertise,
                        devices=self.server.mesh_devices,
                        slots=self.server.slots),
                    deadline=deadline)
                break
            except Exception as ex:
                if not rpc.retryable(ex) or self._stop.is_set() or \
                        time.time() >= deadline:
                    raise
                attempt += 1
                tracing.counter("worker.register_retries")
                # cap the step so a short register_timeout still gets many
                # attempts; never sleep past the give-up deadline
                delay = min(policy.backoff_s(attempt) * 10, 2.0,
                            max(deadline - time.time(), 0.05))
                if self._stop.wait(delay):
                    raise
        try:
            self._adopt_compile_cache(resp.get("compile_cache") or {})
        except Exception:
            # pre-warm is an optimization; registration must never fail on it
            tracing.counter("compile_cache.prewarm_failed")

    def _adopt_compile_cache(self, info: dict) -> None:
        """Registration-time cache sync: adopt the coordinator's
        IGLOO_TPU_COMPILE_CACHE setting when this process has none of its
        own, then PRE-WARM by pulling every persistent-cache entry the
        coordinator has that we don't — a fresh worker serves its first
        fragment with the cluster's whole compile history on disk."""
        import os

        from igloo_tpu import compile_cache
        setting = info.get("setting")
        if setting is not None and "IGLOO_TPU_COMPILE_CACHE" not in os.environ:
            compile_cache.configure(setting)
        from igloo_tpu.exec import autotune  # noqa: F401 -- registers the
        # tuning-table merge hook before any entry lands via write_entry
        local = set(compile_cache.entry_names())
        remote = list(info.get("entries") or ())
        # only REMOTE names are "known to the coordinator": local entries the
        # coordinator lacks (compiled before registration, or a pre-seeded
        # cache) must still be pushed on the first heartbeat
        self._cache_known = set(remote)
        if compile_cache.active_dir() is None:
            return
        # merge-named entries (the autotune tuning table) re-pull even when
        # present locally: their content evolves, and write_entry merges
        merge = compile_cache.merge_names()
        missing = [n for n in remote if n not in local or n in merge]
        if not missing:
            return
        # pull in a DAEMON thread: a mature cluster's cache is hundreds of
        # entries (tens of MB each), and blocking _register on the transfer
        # would outlast the membership timeout (coordinator sweeps a worker
        # silent for 15 s) before the heartbeat thread even starts. Pulled
        # names are already in _cache_known (they came from `remote`), so
        # the thread never mutates shared state; write_entry is atomic.
        threading.Thread(target=self._prewarm_pull, args=(missing,),
                         daemon=True).start()

    def _prewarm_pull(self, missing: list) -> None:
        from igloo_tpu import compile_cache
        done = 0
        pulled = 0
        try:
            # one connection for the whole pre-warm (rpc.flight_actions_raw):
            # a connect/teardown per entry would dominate the transfer
            pulls = rpc.flight_actions_raw(
                self.coordinator,
                (("compile_cache_get", protocol.COMPILE_CACHE_GET.build(
                    name=n)) for n in missing))
            for name, data in zip(missing, pulls):
                done += 1
                if data and compile_cache.write_entry(name, data):
                    tracing.counter("compile_cache.pull")
                    pulled += 1
        except Exception:
            # the batch connection died — usually ONE entry past the
            # transport's message cap. Finish per-entry so everything after
            # it still warms (the push side has the same give-up rule);
            # per-entry failures are skipped, not fatal.
            for name in missing[done:]:
                try:
                    data = rpc.flight_action_raw(
                        self.coordinator, "compile_cache_get",
                        protocol.COMPILE_CACHE_GET.build(name=name))
                    if data and compile_cache.write_entry(name, data):
                        tracing.counter("compile_cache.pull")
                        pulled += 1
                except Exception:
                    tracing.counter("compile_cache.prewarm_failed")
        if pulled:
            # one journal event per pre-warm, not per entry
            events.emit("compile_cache_pull", worker=self.server.worker_id,
                        entries=pulled)

    def _push_compile_cache(self) -> None:
        """Heartbeat-time push of entries this worker compiled since the
        last sync, keyed by XLA cache filename — the return leg that makes
        the cache CLUSTER-wide rather than coordinator-seeded."""
        from igloo_tpu import compile_cache
        # only STABLE entries ship: XLA writes cache files non-atomically,
        # and a truncated blob pushed once would pin itself cluster-wide
        merge = compile_cache.merge_names()
        stable = compile_cache.entry_names(
            min_age_s=compile_cache.TRANSFER_MIN_AGE_S)
        candidates = [n for n in stable if n not in self._cache_known]
        merge_sigs = {}
        for name in stable:
            if name not in merge or name in candidates:
                continue
            sig = compile_cache.entry_stat(name)
            if sig is not None and self._merge_pushed.get(name) != sig:
                merge_sigs[name] = sig
                candidates.append(name)
        if not candidates:
            return
        # one connection for the whole beat: a cold bench run leaves dozens
        # of fresh entries, and a connect/teardown per entry on the heartbeat
        # thread would eat into the coordinator's 15s liveness window.
        # `attempted` is appended before each action is yielded, so when
        # result i arrives attempted[i] is its name; entries are read lazily
        # so at most one payload is in memory at a time.
        attempted: list = []

        def actions():
            for name in candidates:
                data = compile_cache.read_entry(name)
                self._cache_known.add(name)
                if data is None:
                    continue
                attempted.append(name)
                yield ("compile_cache_put", protocol.COMPILE_CACHE_PUT.build(
                    name=name, data=compile_cache.encode_entry(data)))

        confirmed = 0
        pushed = 0
        try:
            for i, body in enumerate(rpc.flight_actions_raw(
                    self.coordinator, actions())):
                name = attempted[i]
                confirmed = i + 1
                resp = json.loads(body) if body else {}
                # {"stored": false} is a real failure (coordinator disk
                # error, payload rejected) — counting it as a push would
                # drop the entry from replication forever
                if resp.get("stored"):
                    tracing.counter("compile_cache.push")
                    pushed += 1
                    self._push_failures.pop(name, None)
                    if name in merge_sigs:
                        self._merge_pushed[name] = merge_sigs[name]
                else:
                    self._note_push_failure(name)
        except Exception:
            # connection died mid-batch (coordinator restart, or one entry
            # past the transport's message cap): everything unconfirmed
            # retries next beat, with the 3-strike give-up so one poisonous
            # entry can't starve those sorting after it
            for name in attempted[confirmed:]:
                self._note_push_failure(name)
        if pushed:
            # one journal event per heartbeat sync, not per entry; `server`
            # may be absent under the push-only unit harness
            srv = getattr(self, "server", None)
            events.emit("compile_cache_push",
                        worker=srv.worker_id if srv else "", entries=pushed)

    def _note_push_failure(self, name: str) -> None:
        """3-strike bookkeeping: un-know the entry so the next beat retries
        it, until it keeps failing (e.g. past the transport's message cap) —
        then leave it known so entries sorting after it still ship."""
        fails = self._push_failures.get(name, 0) + 1
        self._push_failures[name] = fails
        if fails < 3:
            self._cache_known.discard(name)

    def _heartbeat_loop(self) -> None:
        # retry/backoff the reference leaves as a comment (main.rs:37-38):
        # a failed heartbeat retries next tick; a coordinator that no longer
        # knows us (restarted, or it evicted us during a network blip)
        # answers ok=false and we re-register
        import sys
        while not self._stop.wait(self.heartbeat_interval_s):
            # journal events ride the heartbeat (WORKER_INFO.events); on a
            # failed beat they are requeued so the journal stays lossless
            # across transient outages
            evs = events.drain_forward()
            try:
                resp = self._coordinator_action(
                    "heartbeat",
                    serde.worker_info_to_json(
                        self.server.worker_id, self.server.advertise,
                        devices=self.server.mesh_devices,
                        slots=self.server.slots, events=evs))
                if not resp.get("ok", True):
                    self._register()
                    tracing.counter("worker.reregistrations")
                self._push_compile_cache()
                if self._hb_down:
                    self._hb_down = False
                    print(f"igloo-worker {self.server.worker_id}: heartbeat "
                          f"to {self.coordinator} recovered", file=sys.stderr)
            except Exception as ex:
                events.requeue_forward(evs)
                tracing.counter("worker.heartbeat_failures")
                if not self._hb_down:
                    # log the EDGE, count the repeats: one line per outage
                    self._hb_down = True
                    print(f"igloo-worker {self.server.worker_id}: heartbeat "
                          f"to {self.coordinator} failing "
                          f"({type(ex).__name__}: {ex}); will keep retrying "
                          f"every {self.heartbeat_interval_s}s (further "
                          f"failures counted, not logged)", file=sys.stderr)

    def serve_forever(self) -> None:
        self.server.serve()  # blocks

    def shutdown(self) -> None:
        self._stop.set()
        self.server.shutdown()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="igloo-worker")
    ap.add_argument("coordinator", nargs="?", default="127.0.0.1:50051",
                    help="coordinator address (reference worker takes this "
                         "as argv[1] with the same default)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--config", default=None)
    args = ap.parse_args(argv)

    hb = 5.0
    if args.config:
        from igloo_tpu.config import Config, apply_storage, rpc_policy
        cfg = Config.load(args.config)
        hb = cfg.cluster.heartbeat_interval_s
        # [rpc] config is the base; IGLOO_RPC_* env still wins per-field
        # (the worker's registration, heartbeats, and peer dep-fetches all
        # run under this policy)
        rpc.set_default_policy(rpc.policy_from_env(rpc_policy(cfg)))
        # [storage] likewise: the worker's fragment scans read through the
        # same policy-governed object-store layer the engine uses
        apply_storage(cfg)
    w = Worker(args.coordinator, host=args.host, port=args.port,
               heartbeat_interval_s=hb)
    w.start()
    print(f"igloo-worker {w.server.worker_id} serving on {w.address}, "
          f"coordinator {w.coordinator}", flush=True)
    try:
        w.serve_forever()
    except KeyboardInterrupt:
        w.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
