"""The cluster's wire contracts, declared ONCE.

Every cross-process payload the fragment tier exchanges — the extended JSON
do_get ticket, the worker do_get exchange ticket, the execute_fragment
dispatch request (with its dependency refs and trace block), the
registration/heartbeat worker_info, the per-fragment stats report, the
last_metrics shape, and the small control-action payloads — is declared here
as a `Message` of typed `Field`s, and both Flight action surfaces
(coordinator + worker) are declared as literal name tables. Producers call
``MSG.build(...)`` and consumers call ``MSG.parse(...)``, so typed coercion,
defaults, required-field enforcement, and the unknown-field policy live in
ONE place instead of ~44 raw string literals scattered across six modules.

Why: protocol drift is this repo's costliest bug class — fused-vs-staged
overflow tag keys diverged (PR 10), legacy heartbeat payloads silently reset
topology (PR 11), and a mistyped do_get ticket field surfaced as an opaque
mid-execute TypeError (PR 7). A mistyped field is now a `ProtocolError`
naming the message and field at the wire boundary, and the igloo-lint
``wire-contract`` / ``flight-actions`` checkers statically cross-check every
build/parse site in the package against these declarations
(docs/static_analysis.md).

This module is deliberately AST-friendly: the registry assignments below are
PURE LITERALS (``Message("name", [Field(...), ...])`` and dict/list
constants), because the lint checkers extract them by parsing this file —
never importing it. Keep computed values out of the declarations.

Versioning rule: decode with tolerance (unknown fields ride through by
default, optional fields take declared defaults — an old single-device
worker_info still parses), encode strictly (a producer setting an undeclared
field is a hard error — that is how a new field is FORCED through this
registry instead of drifting in as a raw literal).
"""
from __future__ import annotations

import json

from igloo_tpu.errors import IglooError


class ProtocolError(IglooError):
    """A wire payload violated its declared contract (missing required
    field, uncoercible value, undeclared field at a build site)."""


class Field:
    """One declared wire field: name, coercion type, required/optional, and
    the default consumers see when an optional field is absent.

    `type` is one of str/int/float/bool/dict/list (coercion target) or None
    (pass through untyped — reserved for values the registry cannot
    meaningfully coerce, like plan trees that serde owns). `strict` skips
    coercion: the value must already BE the declared type (the SQL text of a
    ticket is strict — an int "coerced" to SQL would fail confusingly deep
    in the parser instead of at the wire)."""

    __slots__ = ("name", "type", "required", "default", "strict", "doc")

    def __init__(self, name: str, type=None, required: bool = False,
                 default=None, strict: bool = False, doc: str = ""):
        self.name = name
        self.type = type
        self.required = required
        self.default = default
        self.strict = strict
        self.doc = doc

    def coerce(self, value, message: str):
        if value is None or self.type is None:
            return value
        t = self.type
        try:
            if self.strict:
                if not isinstance(value, t) or \
                        (t is not bool and isinstance(value, bool)):
                    raise TypeError
                return value
            if t is bool:
                if isinstance(value, bool):
                    return value
                if isinstance(value, int):
                    return bool(value)
                raise TypeError
            if t in (int, float, str):
                if isinstance(value, (dict, list, tuple)):
                    raise TypeError
                return t(value)
            if t is dict:
                if not isinstance(value, dict):
                    raise TypeError
                return value
            if t is list:
                if isinstance(value, tuple):
                    return list(value)
                if not isinstance(value, list):
                    raise TypeError
                return value
            return value
        except (TypeError, ValueError):
            raise ProtocolError(
                f"bad {message} field {self.name!r}: expected "
                f"{t.__name__}, got {type_name(value)} ({value!r})") from None


def type_name(value) -> str:
    return type(value).__name__


class Message:
    """One cross-process contract: a named set of `Field`s plus policy.

    - ``check``: "flow" messages get the wire-contract checker's full
      produced/consumed cross-module analysis; "schema" messages are typed
      schema (build/parse still coerce and validate) without flow
      obligations — used for report shapes whose fields fan out into
      internal bookkeeping dicts.
    - ``unknown``: what `parse` does with undeclared keys — "keep" (version
      tolerance: a newer peer's extra fields ride through) or "drop".
    - ``fill``: whether `parse` materializes absent optional fields with
      their declared defaults (True for request shapes so consumers never
      `.get`-with-default again; False for sparse report shapes where an
      absent key must stay absent).
    """

    __slots__ = ("name", "fields", "check", "unknown", "fill", "doc")

    def __init__(self, name: str, fields: list, check: str = "flow",
                 unknown: str = "keep", fill: bool = True, doc: str = ""):
        self.name = name
        self.fields = {f.name: f for f in fields}
        self.check = check
        self.unknown = unknown
        self.fill = fill
        self.doc = doc

    def build(self, **values) -> dict:
        """Producer side: typed dict ready for json.dumps. `None` for an
        optional field means "not set" and is omitted; an undeclared keyword
        is a hard error (new fields must be declared here first)."""
        out: dict = {}
        for name, value in values.items():
            f = self.fields.get(name)
            if f is None:
                raise ProtocolError(
                    f"undeclared field {name!r} built for message "
                    f"{self.name!r} — declare it in cluster/protocol.py")
            if value is None:
                if f.required:
                    raise ProtocolError(
                        f"bad {self.name}: required field {name!r} is None")
                continue
            out[name] = f.coerce(value, self.name)
        for name, f in self.fields.items():
            if f.required and name not in out:
                raise ProtocolError(
                    f"bad {self.name}: missing required field {name!r}")
        return out

    def parse(self, raw) -> dict:
        """Consumer side: accepts a dict (or JSON str/bytes), returns a
        coerced dict with required fields enforced and (when `fill`) absent
        optional fields defaulted. Unknown keys follow the declared policy."""
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode()
        if isinstance(raw, str):
            try:
                raw = json.loads(raw)
            except ValueError as ex:
                raise ProtocolError(
                    f"bad {self.name}: not valid JSON ({ex})") from None
        if not isinstance(raw, dict):
            raise ProtocolError(
                f"bad {self.name}: expected a JSON object, got "
                f"{type_name(raw)}")
        out: dict = {}
        for name, f in self.fields.items():
            # an explicit JSON null is "not set", NOT a value: for a
            # required field that is a missing-field error at the wire —
            # letting {"sql": null} through would resurrect the opaque
            # mid-execute NoneType crash this registry exists to kill
            if raw.get(name) is not None:
                out[name] = f.coerce(raw[name], self.name)
            elif f.required:
                raise ProtocolError(
                    f"bad {self.name}: missing required field {name!r}")
            elif self.fill:
                default = f.default
                if isinstance(default, (list, dict)):
                    # fresh copy per parse: a consumer mutating a defaulted
                    # container must not contaminate later requests
                    default = type(default)(default)
                out[name] = default
        if self.unknown == "keep":
            for k, v in raw.items():
                if k not in self.fields:
                    out[k] = v
        return out


# ---------------------------------------------------------------------------
# The registry. PURE LITERALS ONLY — the lint checkers parse, never import.
# ---------------------------------------------------------------------------

#: extended do_get ticket the client sends the coordinator. A bare-SQL
#: ticket stays supported (SQL cannot start with "{"); `parse_query_ticket`
#: below folds both forms into this message.
QUERY_TICKET = Message("query_ticket", [
    Field("sql", str, required=True, strict=True, doc="the query"),
    Field("deadline_s", float,
          doc="server-enforced query budget; 0 = already spent"),
    Field("qid", str, doc="name for cancel_query / active_queries"),
    Field("priority", int, default=1,
          doc="admission tier (0 = interactive; docs/serving.md)"),
    Field("session", str, default="",
          doc="session id for the per-session in-flight cap"),
    Field("trace_id", str,
          doc="client-chosen flight-recorder trace identity"),
], doc="client -> coordinator do_get")

#: worker do_get ticket addressing a fragment result or one bucket slice.
#: A bare `<frag_id>` ticket addresses the whole result;
#: `parse_exchange_ticket` folds both forms into this message.
EXCHANGE_TICKET = Message("exchange_ticket", [
    Field("frag", str, required=True, doc="fragment id"),
    Field("bucket", int, doc="bucket slice (None = whole result)"),
    Field("nbuckets", int,
          doc="expected partition count (mismatch = hard error)"),
], doc="coordinator/worker -> worker do_get")

#: the trace block riding inside a dispatch: stitches the worker's span
#: tree under the coordinator's dispatch span (docs/observability.md).
TRACE_CTX = Message("trace_ctx", [
    Field("trace_id", str, required=True),
    Field("parent_id", str, doc="coordinator-side dispatch span id"),
], doc="coordinator -> worker, inside the dispatch payload")

#: one upstream dependency reference inside a dispatch payload.
DISPATCH_DEP = Message("dispatch_dep", [
    Field("id", str, required=True, doc="dependency fragment id"),
    Field("addr", str, default="", doc="worker holding its result"),
], doc="coordinator -> worker, dispatch `deps` entries")

#: the execute_fragment request.
DISPATCH = Message("dispatch", [
    Field("id", str, required=True, doc="fragment id"),
    Field("plan", dict, required=True,
          doc="serialized plan tree (cluster/serde.py owns the node schema)"),
    Field("deps", list, default=[], doc="list of dispatch_dep"),
    Field("timeout_s", float,
          doc="query budget remaining, RELATIVE (clocks differ)"),
    Field("trace", dict, doc="trace_ctx block, when tracing"),
    Field("budget", int,
          doc="per-worker out-of-core byte budget (oversized queries only): "
              "Exchange fragments stream-spill under it, join fragments run "
              "residual-skew GRACE under it (docs/out_of_core.md)"),
], doc="coordinator -> worker execute_fragment action")

#: registration/heartbeat payload. Version tolerance is the point: a worker
#: predating the topology fields registers as single-device, which keeps the
#: planner's sizing exactly as it was before two-level parallelism. (The
#: pre-PR14 heartbeat also shipped a `ts` wall-clock field no consumer ever
#: read — the coordinator's last_seen is its OWN clock, cross-host clocks
#: don't compare — so the wire-contract checker retired it; old payloads
#: carrying it still parse, the key just rides through unread.)
WORKER_INFO = Message("worker_info", [
    Field("id", str, required=True, doc="worker id (uuid hex)"),
    Field("addr", str, default="", doc="advertised Flight address"),
    Field("devices", int, default=1,
          doc="local mesh size one fragment runs across"),
    Field("slots", int, default=0, doc="execution-slot bound"),
    Field("events", list, default=[],
          doc="watchtower journal events since the last heartbeat "
              "(cluster/events.py drain_forward; coordinator ingests them "
              "under this worker's label — absent from pre-watchtower "
              "workers, which is the empty batch)"),
], doc="worker -> coordinator register_worker/heartbeat actions")

#: per-fragment stats the worker returns from execute_fragment — the shape
#: last_metrics["fragments"] entries start from, before the coordinator's
#: enrichment fields (declared below too, so the whole row is one schema).
#: `fill=False`: absent keys stay absent (bucket fields only exist for
#: Exchange-rooted fragments), and transfer/compile deltas may be None.
FRAGMENT_STATS = Message("fragment_stats", [
    Field("id", str, required=True),
    Field("rows", int, required=True, doc="result rows"),
    Field("elapsed_s", float, required=True, doc="execution wall"),
    Field("worker", str, doc="executing worker id"),
    Field("dep_fetch_s", float, doc="dependency-fetch wall"),
    Field("input_rows", int, doc="rows fetched from dependencies"),
    Field("mesh_devices", int, doc="chips the fragment ran across"),
    Field("mesh_rows_per_device", int),
    Field("result_bytes", int, doc="Arrow bytes of the stored result"),
    Field("h2d_bytes", int), Field("d2h_bytes", int),
    Field("jit_misses", int), Field("cache_hits", int),
    Field("exchange_rows", int), Field("exchange_bytes", int),
    Field("buckets", int, doc="partition count (Exchange roots only)"),
    Field("bucket_rows", list, doc="UNSALTED per-bucket rows (skew sketch)"),
    Field("salted", bool, doc="salted exchange layout"),
    Field("spans", list, doc="worker span tree for trace stitching"),
    # coordinator-side enrichment (never on the wire; part of the published
    # last_metrics fragment rows):
    Field("addr", str, doc="[coordinator] dispatch target"),
    Field("kind", str, doc="[coordinator] planner fragment kind"),
    Field("bucket", int, doc="[coordinator] shuffle bucket id"),
    Field("stats_key", str, doc="[coordinator] adaptive side digest"),
    Field("dispatch_s", float, doc="[coordinator] RPC wall minus worker"),
], check="schema", fill=False,
    doc="worker -> coordinator execute_fragment response")

#: the published per-query metrics dict (`last_metrics` action, mirrored
#: into system.query_log columns) — docs/distributed.md#telemetry.
LAST_METRICS = Message("last_metrics", [
    Field("qid", str),
    Field("status", str, doc="ok|cancelled|deadline_exceeded|error|shed"),
    Field("fragments", list, doc="fragment_stats rows"),
    Field("recoveries", int), Field("recover_s", float),
    Field("fetch_s", float), Field("deadline_s", float),
    Field("cancelled", bool), Field("deadline_exceeded", bool),
    Field("trace_id", str), Field("shuffle_buckets", int),
    Field("adaptive", list, doc="planner decision records"),
    Field("queue_wait_s", float), Field("priority", int),
    Field("demoted", int),
    Field("topology", dict, doc="{workers, devices, total_shards}"),
    Field("total_rows", int), Field("rows", int),
    Field("exchange_bytes", int), Field("execution_time_s", float),
    Field("result_cache_hit", bool),
    Field("oversized", dict,
          doc="distributed out-of-core block: {budget_bytes, buckets, "
              "partitioned_leaves, replicated_leaves} (docs/out_of_core.md)"),
], check="schema", fill=False, doc="coordinator last_metrics action reply")

#: serving_status action reply (docs/serving.md).
SERVING_STATUS = Message("serving_status", [
    Field("enabled", bool), Field("queue_depth", int),
    Field("max_concurrency", int), Field("session_inflight", int),
    Field("hbm_budget_bytes", int), Field("weights", list),
    Field("running", int), Field("hbm_reserved_bytes", int),
    Field("queued", dict, doc="priority tier -> queued count"),
    Field("sessions", dict, doc="session -> in-flight count"),
], check="schema", doc="coordinator serving_status action reply")

# --- small control-action payloads -----------------------------------------

CANCEL_QUERY = Message("cancel_query", [
    Field("qid", str, default="", doc="qid passed to execute"),
], doc="client -> coordinator cancel_query action")

REGISTER_TABLE = Message("register_table", [
    Field("name", str, required=True),
    Field("spec", dict, required=True,
          doc="provider spec (cluster/serde.py owns the kinds)"),
], doc="client/coordinator -> coordinator/worker register_table action")

COMPILE_CACHE_GET = Message("compile_cache_get", [
    Field("name", str, default="", doc="XLA cache entry filename"),
], doc="worker -> coordinator compile_cache_get action")

COMPILE_CACHE_PUT = Message("compile_cache_put", [
    Field("name", str, default=""),
    Field("data", str, default="", doc="base64 entry bytes"),
], doc="worker -> coordinator compile_cache_put action")

RELEASE = Message("release", [
    Field("ids", list, default=[], doc="fragment ids to drop"),
], doc="coordinator -> worker release action")

TRACE_REQUEST = Message("trace_request", [
    Field("trace_id", str), Field("qid", str),
    Field("format", str, default="chrome", doc="chrome | raw"),
], doc="client -> coordinator trace action")

POLL_FLIGHT_INFO = Message("poll_flight_info", [
    Field("sql", str, required=True),
], doc="client -> coordinator poll_flight_info action")

# --- watchtower payloads (docs/observability.md#watchtower) -----------------

EVENTS_REQUEST = Message("events_request", [
    Field("min_severity", str, default="info", doc="info | warn | error"),
    Field("limit", int, doc="most-recent-N cap (None = whole ring)"),
], doc="client -> coordinator events action")

#: metrics_history reply: the coordinator's own sampler ring plus every
#: live worker's, each sample labeled by its `source` field ("coordinator"
#: or the worker id).
METRICS_HISTORY = Message("metrics_history", [
    Field("samples", list, required=True,
          doc="sample dicts {ts, source, rates, gauges}, oldest first"),
], check="schema", fill=False,
    doc="coordinator/worker metrics_history action reply")

EVENTS_REPLY = Message("events_reply", [
    Field("events", list, required=True,
          doc="journal event dicts, oldest first"),
], check="schema", fill=False, doc="coordinator events action reply")

SLOW_QUERIES_REPLY = Message("slow_queries_reply", [
    Field("slow_queries", list, required=True,
          doc="escalation records, oldest first (utils/watch.py)"),
], check="schema", fill=False, doc="coordinator slow_queries action reply")

#: one-call ops snapshot behind `igloo top`.
WATCH_STATUS = Message("watch_status", [
    Field("qps", float, doc="completions/s over the recent log window"),
    Field("p50_ms", float), Field("p99_ms", float),
    Field("window_s", float, doc="the qps/quantile window width"),
    Field("serving", dict, doc="{running, queued, hbm_reserved_bytes}"),
    Field("workers", list,
          doc="per-worker {id, addr, devices, slots, age_s}"),
    Field("active", list, doc="in-flight qids"),
    Field("events", list, doc="most recent journal events"),
    Field("samples", list, doc="most recent sampler rows"),
], check="schema", fill=False, doc="coordinator watch_status action reply")


# --- Flight action-name tables ----------------------------------------------
# The flight-actions checker cross-checks each server's do_action dispatch
# AND its list_actions against these, and every flight_action*/_action call
# site in the package against their union.

COORDINATOR_ACTIONS = {
    "cancel_query": "cancel a running distributed query by qid",
    "active_queries": "qids of in-flight distributed queries",
    "register_worker": "worker membership registration (returns "
                       "compile-cache setting + entry listing for pre-warm)",
    "compile_cache_get": "persistent-compile-cache entry bytes by filename",
    "compile_cache_put": "store a worker-compiled persistent-cache entry",
    "heartbeat": "worker liveness heartbeat",
    "register_table": "register a table from a provider spec",
    "cluster_status": "membership + catalog snapshot",
    "last_metrics": "per-fragment metrics of the last query",
    "trace": "stitched query timeline by trace_id/qid as Chrome-trace/"
             "Perfetto JSON (format=raw for the span record)",
    "serving_status": "admission queue / concurrency / HBM-reservation "
                      "snapshot",
    "metrics": "process + worker-aggregated fragment metrics, Prometheus "
               "text format",
    "ping": "liveness",
    "poll_flight_info": "PollFlightInfo equivalent: serialized FlightInfo "
                        "for a SQL command, progress=1.0 (planning "
                        "completes eagerly)",
    "metrics_history": "watchtower sampler rings, coordinator + live "
                       "workers, source-labeled",
    "events": "cluster event journal (min_severity/limit filters)",
    "slow_queries": "baseline-anomaly escalation records",
    "watch_status": "one-call ops snapshot: qps/latency quantiles, "
                    "workers, active queries, recent events (igloo top)",
}

WORKER_ACTIONS = {
    "execute_fragment": "execute a serialized plan fragment",
    "register_table": "register a table from a provider spec",
    "release": "drop cached fragment results",
    "ping": "liveness + status",
    "metrics": "process metrics, Prometheus text format",
    "metrics_history": "this worker's watchtower sampler ring",
}

#: which module serves which action table (the flight-actions checker reads
#: these paths; any OTHER module defining do_action is held to the union).
ACTION_SERVERS = {
    "coordinator": "igloo_tpu/cluster/coordinator.py",
    "worker": "igloo_tpu/cluster/worker.py",
}

#: the modules where wire payloads are produced/consumed — the scope of the
#: wire-contract checker's raw-field-access rule (a json.loads'd payload
#: subscripted with a flow-message field name here must go through parse).
WIRE_MODULES = [
    "igloo_tpu/cluster/client.py",
    "igloo_tpu/cluster/coordinator.py",
    "igloo_tpu/cluster/exchange.py",
    "igloo_tpu/cluster/serde.py",
    "igloo_tpu/cluster/serving.py",
    "igloo_tpu/cluster/worker.py",
]

#: module-level helpers below that parse a message (the wire-contract
#: checker tags their call sites as consumers of the mapped message).
PARSE_HELPERS = {
    "parse_query_ticket": "query_ticket",
    "parse_exchange_ticket": "exchange_ticket",
}


# --- ticket folding helpers --------------------------------------------------


def parse_query_ticket(raw: str) -> dict:
    """Decode a coordinator do_get ticket: the extended JSON form, or a bare
    SQL string (SQL cannot start with "{", so plain tickets keep working).
    Raises ProtocolError naming the offending field — the caller maps it to
    a "bad query ticket" Flight error instead of an opaque mid-execute
    TypeError (the PR 7 bug class)."""
    if raw.lstrip().startswith("{"):
        return QUERY_TICKET.parse(raw)
    return QUERY_TICKET.parse({"sql": raw})


def encode_query_ticket(body: dict, sql: str) -> str:
    """The client-side inverse: a built query_ticket collapses to the bare
    SQL when no extended field is set (stock-client wire compatibility)."""
    return sql if list(body) == ["sql"] else json.dumps(body)


def parse_exchange_ticket(raw: bytes) -> dict:
    """Decode a worker do_get ticket: the bucketed JSON form, or a bare
    fragment id (fragment ids are hex, never "{"-prefixed)."""
    if isinstance(raw, (bytes, bytearray)):
        raw = raw.decode()
    if raw.lstrip().startswith("{"):
        return EXCHANGE_TICKET.parse(raw)
    return EXCHANGE_TICKET.parse({"frag": raw})


def action_doc(server: str) -> list:
    """(name, description) pairs for a server's list_actions, straight from
    the registry (declaration order)."""
    table = COORDINATOR_ACTIONS if server == "coordinator" else WORKER_ACTIONS
    return list(table.items())
