"""Plan serialization — the wire format the reference faked.

The reference's `serialize_plan` returns empty bytes and `deserialize_batch`
fabricates a dummy 3-row batch (crates/coordinator/src/distributed_executor.rs:
203-222, gap G1). Here the fragment payload is REAL: a bound logical plan tree
(nodes + typed expressions + schemas) round-trips through JSON; table
references resolve against the receiving side's catalog (fragment results are
registered as `__frag_<id>` tables before execution). Result batches travel as
Arrow IPC streams, matching the reference's intended RecordBatchMessage
(distributed.proto:53-57) but with a codec that actually exists.
"""
from __future__ import annotations

import io
from typing import Optional

import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.cluster import protocol
from igloo_tpu.errors import PlanError
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType

# --- types / schema ---


def dtype_to_json(d: Optional[T.DataType]) -> Optional[str]:
    return None if d is None else d.id.value


def dtype_from_json(s: Optional[str]) -> Optional[T.DataType]:
    return None if s is None else T.DataType(T.TypeId(s))


def schema_to_json(s: T.Schema) -> list:
    return [[f.name, f.dtype.id.value, f.nullable] for f in s.fields]


def schema_from_json(j: list) -> T.Schema:
    return T.Schema([T.Field(n, T.DataType(T.TypeId(t)), bool(nl))
                     for n, t, nl in j])


# --- expressions ---


def expr_to_json(e: Optional[E.Expr]):
    if e is None:
        return None
    d: dict = {"t": type(e).__name__, "dt": dtype_to_json(e.dtype)}
    if isinstance(e, E.Column):
        d.update(name=e.name, index=e.index)
    elif isinstance(e, E.Literal):
        d.update(value=e.value, lt=dtype_to_json(e.literal_type))
    elif isinstance(e, E.Interval):
        d.update(days=e.days, months=e.months)
    elif isinstance(e, E.Binary):
        d.update(op=e.op.value, left=expr_to_json(e.left),
                 right=expr_to_json(e.right))
    elif isinstance(e, (E.Not, E.Negate)):
        d.update(operand=expr_to_json(e.operand))
    elif isinstance(e, E.IsNull):
        d.update(operand=expr_to_json(e.operand), negated=e.negated)
    elif isinstance(e, E.Cast):
        d.update(operand=expr_to_json(e.operand), to=dtype_to_json(e.to))
    elif isinstance(e, E.Case):
        d.update(whens=[[expr_to_json(c), expr_to_json(v)] for c, v in e.whens],
                 else_=expr_to_json(e.else_))
    elif isinstance(e, E.InList):
        d.update(operand=expr_to_json(e.operand),
                 items=[expr_to_json(i) for i in e.items], negated=e.negated)
    elif isinstance(e, E.Like):
        d.update(operand=expr_to_json(e.operand), pattern=e.pattern,
                 negated=e.negated, ci=e.case_insensitive)
    elif isinstance(e, E.Func):
        d.update(name=e.name, args=[expr_to_json(a) for a in e.args])
    elif isinstance(e, E.Aggregate):
        d.update(func=e.func.value, arg=expr_to_json(e.arg),
                 distinct=e.distinct)
    elif isinstance(e, E.Alias):
        d.update(operand=expr_to_json(e.operand), alias=e.alias)
    elif isinstance(e, E.Window):
        d.update(func=e.func, agg=expr_to_json(e.agg),
                 args=[expr_to_json(a) for a in e.args],
                 partition=[expr_to_json(x) for x in e.partition_by],
                 order=[expr_to_json(x) for x in e.order_by],
                 ascending=e.ascending, nulls_first=e.nulls_first)
    elif isinstance(e, E.ScalarSubquery):
        if not isinstance(e.query, L.LogicalPlan):
            raise PlanError("cannot serialize unbound scalar subquery")
        d.update(plan=plan_to_json(e.query))
    else:
        raise PlanError(f"cannot serialize expression {type(e).__name__}")
    return d


def expr_from_json(d) -> Optional[E.Expr]:
    if d is None:
        return None
    t = d["t"]
    if t == "Column":
        e: E.Expr = E.Column(name=d["name"], index=d["index"])
    elif t == "Literal":
        e = E.Literal(value=d["value"], literal_type=dtype_from_json(d["lt"]))
    elif t == "Interval":
        e = E.Interval(days=d["days"], months=d["months"])
    elif t == "Binary":
        e = E.Binary(op=E.BinOp(d["op"]), left=expr_from_json(d["left"]),
                     right=expr_from_json(d["right"]))
    elif t == "Not":
        e = E.Not(operand=expr_from_json(d["operand"]))
    elif t == "Negate":
        e = E.Negate(operand=expr_from_json(d["operand"]))
    elif t == "IsNull":
        e = E.IsNull(operand=expr_from_json(d["operand"]), negated=d["negated"])
    elif t == "Cast":
        e = E.Cast(operand=expr_from_json(d["operand"]),
                   to=dtype_from_json(d["to"]))
    elif t == "Case":
        e = E.Case(whens=[(expr_from_json(c), expr_from_json(v))
                          for c, v in d["whens"]],
                   else_=expr_from_json(d["else_"]))
    elif t == "InList":
        e = E.InList(operand=expr_from_json(d["operand"]),
                     items=[expr_from_json(i) for i in d["items"]],
                     negated=d["negated"])
    elif t == "Like":
        e = E.Like(operand=expr_from_json(d["operand"]), pattern=d["pattern"],
                   negated=d["negated"], case_insensitive=d["ci"])
    elif t == "Func":
        e = E.Func(name=d["name"], args=[expr_from_json(a) for a in d["args"]])
    elif t == "Window":
        e = E.Window(func=d["func"], agg=expr_from_json(d["agg"]),
                     args=[expr_from_json(a) for a in d["args"]],
                     partition_by=[expr_from_json(x) for x in d["partition"]],
                     order_by=[expr_from_json(x) for x in d["order"]],
                     ascending=d["ascending"], nulls_first=d["nulls_first"])
    elif t == "Aggregate":
        e = E.Aggregate(func=E.AggFunc(d["func"]), arg=expr_from_json(d["arg"]),
                        distinct=d["distinct"])
    elif t == "Alias":
        e = E.Alias(operand=expr_from_json(d["operand"]), alias=d["alias"])
    elif t == "ScalarSubquery":
        e = E.ScalarSubquery(query=None)  # plan attached below
        e.query = _PLAN_PLACEHOLDER(d["plan"])
    else:
        raise PlanError(f"cannot deserialize expression {t}")
    e.dtype = dtype_from_json(d["dt"])
    return e


class _PLAN_PLACEHOLDER:
    """Deferred subquery plan: resolved by plan_from_json's catalog pass."""

    def __init__(self, json_plan):
        self.json_plan = json_plan


# --- plans ---


def plan_to_json(p: L.LogicalPlan) -> dict:
    d: dict = {"t": type(p).__name__, "schema": schema_to_json(p.schema)}
    if isinstance(p, L.Scan):
        d.update(table=p.table, projection=p.projection,
                 pushed=[expr_to_json(f) for f in p.pushed_filters],
                 partition=getattr(p, "partition", None),
                 partition_token=getattr(p, "partition_token", None))
        if getattr(p, "bucket", None) is not None:
            d.update(bucket=p.bucket, buckets=p.buckets)
    elif isinstance(p, L.Filter):
        d.update(input=plan_to_json(p.input), predicate=expr_to_json(p.predicate))
    elif isinstance(p, L.Project):
        d.update(input=plan_to_json(p.input),
                 exprs=[expr_to_json(e) for e in p.exprs], names=p.names)
    elif isinstance(p, L.Aggregate):
        d.update(input=plan_to_json(p.input),
                 groups=[expr_to_json(e) for e in p.group_exprs],
                 group_names=p.group_names,
                 aggs=[expr_to_json(a) for a in p.aggs], agg_names=p.agg_names)
    elif isinstance(p, L.Join):
        d.update(left=plan_to_json(p.left), right=plan_to_json(p.right),
                 join_type=p.join_type.value,
                 lk=[expr_to_json(e) for e in p.left_keys],
                 rk=[expr_to_json(e) for e in p.right_keys],
                 residual=expr_to_json(p.residual))
    elif isinstance(p, L.Window):
        d.update(input=plan_to_json(p.input),
                 partition=[expr_to_json(e) for e in p.partition_exprs],
                 order=[expr_to_json(e) for e in p.order_exprs],
                 ascending=p.ascending, nulls_first=p.nulls_first,
                 funcs=[expr_to_json(e) for e in p.funcs], names=p.names)
    elif isinstance(p, L.Sort):
        d.update(input=plan_to_json(p.input),
                 keys=[expr_to_json(e) for e in p.keys],
                 ascending=p.ascending, nulls_first=p.nulls_first)
    elif isinstance(p, L.Limit):
        d.update(input=plan_to_json(p.input), limit=p.limit, offset=p.offset)
    elif isinstance(p, L.Distinct):
        d.update(input=plan_to_json(p.input))
    elif isinstance(p, L.Union):
        d.update(inputs=[plan_to_json(c) for c in p.inputs])
    elif isinstance(p, L.SetOpJoin):
        d.update(left=plan_to_json(p.left), right=plan_to_json(p.right),
                 anti=p.anti)
    elif isinstance(p, L.Values):
        d.update(rows=[list(r) for r in p.rows])
    elif isinstance(p, L.Exchange):
        d.update(input=plan_to_json(p.input), keys=list(p.keys),
                 buckets=p.buckets)
        if p.salt_role is not None:
            d.update(salt_bucket=p.salt_bucket, salt=p.salt,
                     salt_role=p.salt_role)
    else:
        raise PlanError(f"cannot serialize plan node {type(p).__name__}")
    return d


def plan_from_json(d: dict, catalog) -> L.LogicalPlan:
    """JSON -> bound plan; Scan providers resolve against `catalog`."""
    t = d["t"]
    schema = schema_from_json(d["schema"])
    if t == "Scan":
        p: L.LogicalPlan = L.Scan(
            table=d["table"], provider=catalog.get(d["table"]),
            projection=d["projection"],
            pushed_filters=[expr_from_json(f) for f in d["pushed"]])
        if d.get("partition") is not None:
            p.partition = tuple(d["partition"])  # type: ignore[attr-defined]
        p.partition_token = d.get("partition_token")  # type: ignore[attr-defined]
        if d.get("bucket") is not None:
            p.bucket = d["bucket"]    # type: ignore[attr-defined]
            p.buckets = d["buckets"]  # type: ignore[attr-defined]
    elif t == "Filter":
        p = L.Filter(input=plan_from_json(d["input"], catalog),
                     predicate=_rx(d["predicate"], catalog))
    elif t == "Project":
        p = L.Project(input=plan_from_json(d["input"], catalog),
                      exprs=[_rx(e, catalog) for e in d["exprs"]],
                      names=list(d["names"]))
    elif t == "Aggregate":
        p = L.Aggregate(input=plan_from_json(d["input"], catalog),
                        group_exprs=[_rx(e, catalog) for e in d["groups"]],
                        group_names=list(d["group_names"]),
                        aggs=[_rx(a, catalog) for a in d["aggs"]],
                        agg_names=list(d["agg_names"]))
    elif t == "Join":
        p = L.Join(left=plan_from_json(d["left"], catalog),
                   right=plan_from_json(d["right"], catalog),
                   join_type=JoinType(d["join_type"]),
                   left_keys=[_rx(e, catalog) for e in d["lk"]],
                   right_keys=[_rx(e, catalog) for e in d["rk"]],
                   residual=_rx(d["residual"], catalog))
    elif t == "Window":
        p = L.Window(input=plan_from_json(d["input"], catalog),
                     partition_exprs=[_rx(e, catalog) for e in d["partition"]],
                     order_exprs=[_rx(e, catalog) for e in d["order"]],
                     ascending=d["ascending"], nulls_first=d["nulls_first"],
                     funcs=[_rx(e, catalog) for e in d["funcs"]],
                     names=d["names"])
    elif t == "Sort":
        p = L.Sort(input=plan_from_json(d["input"], catalog),
                   keys=[_rx(e, catalog) for e in d["keys"]],
                   ascending=list(d["ascending"]),
                   nulls_first=list(d["nulls_first"]))
    elif t == "Limit":
        p = L.Limit(input=plan_from_json(d["input"], catalog),
                    limit=d["limit"], offset=d["offset"])
    elif t == "Distinct":
        p = L.Distinct(input=plan_from_json(d["input"], catalog))
    elif t == "Union":
        p = L.Union(inputs=[plan_from_json(c, catalog) for c in d["inputs"]])
    elif t == "SetOpJoin":
        p = L.SetOpJoin(left=plan_from_json(d["left"], catalog),
                        right=plan_from_json(d["right"], catalog),
                        anti=d["anti"])
    elif t == "Values":
        p = L.Values(rows=[list(r) for r in d["rows"]])
    elif t == "Exchange":
        p = L.Exchange(input=plan_from_json(d["input"], catalog),
                       keys=list(d["keys"]), buckets=d["buckets"],
                       salt_bucket=d.get("salt_bucket"),
                       salt=d.get("salt", 1),
                       salt_role=d.get("salt_role"))
    else:
        raise PlanError(f"cannot deserialize plan node {t}")
    p.schema = schema
    return p


def _rx(j, catalog) -> Optional[E.Expr]:
    """expr_from_json + resolve deferred subquery plans against the catalog."""
    e = expr_from_json(j)
    if e is None:
        return None
    for n in E.walk(e):
        if isinstance(n, E.ScalarSubquery) and \
                isinstance(n.query, _PLAN_PLACEHOLDER):
            n.query = plan_from_json(n.query.json_plan, catalog)
    return e


# --- worker info (registration / heartbeat payloads) ---


def worker_info_to_json(worker_id: str, addr: str, devices: int = 1,
                        slots: int = 0, events: Optional[list] = None) -> dict:
    """The registration/heartbeat payload, built through the protocol
    registry (cluster/protocol.py WORKER_INFO) so both sides of the wire
    share one declaration: `devices` is the size of the worker's LOCAL mesh
    (1 = single-device) — the topology number the distributed planner sizes
    bucket counts and placement with (bucket count scales with hosts, shard
    count with chips, docs/distributed.md) — and `slots` its execution-slot
    bound. `events` is the watchtower journal batch riding the heartbeat
    (cluster/events.drain_forward; omitted when empty so registration and
    legacy payloads stay byte-identical). (The pre-PR14 heartbeat also
    shipped a wall-clock `ts` no consumer ever read; the wire-contract
    checker retired it.)"""
    if events:
        return protocol.WORKER_INFO.build(id=worker_id, addr=addr,
                                          devices=int(max(devices, 1)),
                                          slots=int(slots), events=events)
    return protocol.WORKER_INFO.build(id=worker_id, addr=addr,
                                      devices=int(max(devices, 1)),
                                      slots=int(slots))


def worker_info_from_json(d: dict) -> dict:
    """Decode with version tolerance (the registry's declared defaults): a
    worker predating the topology fields — or a hand-rolled client —
    registers as single-device, which keeps the planner's sizing exactly as
    it was before two-level parallelism."""
    info = protocol.WORKER_INFO.parse(d)
    return {"id": info["id"], "addr": info["addr"],
            "devices": int(info["devices"] or 1),
            "slots": int(info["slots"] or 0),
            "events": list(info["events"] or [])}


# --- provider specs (how a worker re-creates a coordinator table) ---


def provider_to_spec(provider) -> Optional[dict]:
    """Shippable description of a table provider, or None if the provider
    cannot be reconstructed remotely (then its data ships as Arrow IPC)."""
    from igloo_tpu.catalog import MemTable
    from igloo_tpu.connectors.csv import CsvTable
    from igloo_tpu.connectors.iceberg import IcebergTable
    from igloo_tpu.connectors.parquet import ParquetTable
    if isinstance(provider, ParquetTable):
        return {"kind": "parquet", "path": provider.path}
    if isinstance(provider, CsvTable):
        return {"kind": "csv", "path": provider.path,
                "has_header": provider.has_header,
                "delimiter": provider.delimiter}
    if isinstance(provider, IcebergTable):
        return {"kind": "iceberg", "path": provider.path}
    if isinstance(provider, MemTable):
        import base64
        # partition count rides along: the planner strides the COORDINATOR
        # provider's partitions, so a worker rebuilding the table must slice
        # read_partition identically or striped scans return wrong rows
        return {"kind": "ipc", "partitions": provider.num_partitions(),
                "data": base64.b64encode(table_to_ipc(provider.read())).decode()}
    return None


def provider_from_spec(spec: dict):
    kind = spec["kind"]
    if kind == "parquet":
        from igloo_tpu.connectors.parquet import ParquetTable
        return ParquetTable(spec["path"])
    if kind == "csv":
        from igloo_tpu.connectors.csv import CsvTable
        return CsvTable(spec["path"], has_header=spec.get("has_header", True),
                        delimiter=spec.get("delimiter", ","))
    if kind == "iceberg":
        from igloo_tpu.connectors.iceberg import IcebergTable
        return IcebergTable(spec["path"])
    if kind == "ipc":
        import base64
        from igloo_tpu.catalog import MemTable
        return MemTable(table_from_ipc(base64.b64decode(spec["data"])),
                        partitions=spec.get("partitions", 1))
    raise PlanError(f"unknown provider spec kind: {kind}")


# --- Arrow IPC result codec ---


def table_to_ipc(table: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def table_from_ipc(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()
