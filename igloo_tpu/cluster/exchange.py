"""Cross-worker shuffle exchange: hash partitioning + the bucketed,
bytes-budgeted fragment store.

The reference declares `FragmentType::Shuffle` (crates/coordinator/src/
fragment.rs:12) and never constructs it; its worker shuffle fetch returns
empty bytes (crates/worker/src/service.rs:26-32). This module is the real
thing for the Flight/fragment tier (the TPU mesh tier has its own all_to_all
shuffle in parallel/shuffle.py — see docs/distributed.md):

- `bucket_ids` assigns every row of an Arrow table to one of N buckets by a
  deterministic hash of its join-key columns. The hash is a pure function of
  the key BYTES (strings go through the native hash64.c dictionary path, the
  same primitive GRACE partitioning uses), so two workers hashing the two
  sides of a join agree on bucket placement without coordination.
- `FragmentStore` replaces the worker's `dict[str, pa.Table]` result map: a
  fragment result is held as a list of record batches with optional per-bucket
  partition metadata (rows/bytes per bucket), under a configurable bytes
  budget. Results that push the store over budget spill to Arrow IPC files
  and are served batch-at-a-time off disk — a multi-GB fragment
  result never needs to be resident to be transferred.
- do_get tickets address either a whole fragment (`<frag_id>`) or one bucket
  slice (JSON `{"frag": id, "bucket": b, "nbuckets": n}`) — the wire format
  of the per-bucket exchange the distributed planner emits for joins.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from igloo_tpu.cluster import protocol
from igloo_tpu.utils import tracing

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX = np.uint64(0xC2B2AE3D27D4EB4F)

# stream granularity: small enough that one in-flight batch is cheap to
# buffer on both ends, large enough that per-message overhead amortizes
BATCH_ROWS = 65536

STORE_BUDGET_ENV = "IGLOO_FRAGMENT_STORE_BYTES"
DEFAULT_STORE_BUDGET = 1 << 30

# lock discipline (checked by igloo-lint lock-discipline): FragmentStore is
# hit concurrently by Flight RPC threads (execute_fragment stores, do_get
# streams, release drops) — every access to the entry map and its spill
# bookkeeping must hold the store lock or sit in a `*_locked` method
_GUARDED_BY = {"_lock": ("_entries", "_seq", "_tmpdir", "_released")}

#: released-fragment tombstones kept (FIFO): big enough to cover every id a
#: burst of queries can release while one abandoned execution drags on,
#: small enough to never matter (ids are 12-byte hex)
TOMBSTONE_CAP = 4096


# --- deterministic key hashing ----------------------------------------------


def _column_vals(col, typ) -> np.ndarray:
    """Canonical pre-mix uint64 lane for one key column (process-independent:
    strings hash their bytes via native hash64.c, numerics use a canonical
    int64/bit pattern). Nulls read as 0 — they only need a consistent ROUTE,
    equality semantics stay with the join that consumes the bucket. The
    per-column avalanche (multiply + shift-xor) happens downstream so the
    Pallas exchange-scatter kernel can consume these same lanes and stay
    bit-identical to the numpy mix (exec/pallas_kernels.py hash_scatter)."""
    import pyarrow.compute as pc

    from igloo_tpu.exec.batch import hash64_bytes
    if pa.types.is_dictionary(typ) or pa.types.is_string(typ) or \
            pa.types.is_large_string(typ):
        if not pa.types.is_dictionary(col.type):
            col = col.dictionary_encode()
        dvals = np.asarray(col.dictionary.to_numpy(zero_copy_only=False),
                           dtype=object)
        ids = np.asarray(pc.fill_null(col.indices, 0)).astype(np.int64)
        return hash64_bytes(dvals, seed=0)[ids] if len(dvals) else \
            np.zeros(len(col), dtype=np.uint64)
    if pa.types.is_floating(typ):
        v = np.asarray(col.cast(pa.float64()).fill_null(0.0),
                       dtype=np.float64)
        # canonicalize -0.0 -> +0.0 and NaN -> one bit pattern so equal keys
        # (SQL equality) always share a bucket
        v = v + 0.0
        v = np.where(np.isnan(v), np.float64(0.0), v)
        return v.view(np.uint64)
    if pa.types.is_date32(typ):
        col = col.cast(pa.int32())
    return np.asarray(col.cast(pa.int64()).fill_null(0)).astype(np.uint64)


def _hash_column(col, typ) -> np.ndarray:
    """uint64 hash lane for one key column: canonical value + avalanche."""
    vals = _column_vals(col, typ)
    h = vals * _GOLDEN
    return h ^ (h >> np.uint64(29))


def key_hash(table: pa.Table, key_indices: list[int]) -> np.ndarray:
    """Combined uint64 hash of the key columns named by position."""
    h = np.full(table.num_rows, np.uint64(0x243F6A8885A308D3),
                dtype=np.uint64)
    for i in key_indices:
        col = table.column(i)
        col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        c = _hash_column(col, table.schema.field(i).type)
        h = (h ^ c) * _MIX
        h ^= h >> np.uint64(33)
    return h


def bucket_ids(table: pa.Table, key_indices: list[int],
               nbuckets: int) -> np.ndarray:
    """int64 bucket id per row (high-bits mix so the modulus is independent
    of the low bits local join sorts use)."""
    h = key_hash(table, key_indices)
    return ((h >> np.uint64(17)) % np.uint64(nbuckets)).astype(np.int64)


# partition shapes whose Pallas scatter program failed to lower this process
# (keyed by the plan's canonical (npad, nbuckets) — a host decision, so the
# retry recompiles straight on the numpy path)
_SCATTER_BANS: set = set()


def _partition_arrays(table: pa.Table, key_indices: list[int],
                      nbuckets: int):
    """(bucket ids, stable order or None, unsalted counts or None) for a hash
    partition. Routes through the Pallas exchange-scatter kernel when
    dispatch plans it — per-key avalanche + combine + bucket counts fused in
    one device pass over the canonical lanes, bit-identical to `bucket_ids`
    (docs/kernels.md) — and falls back to the numpy mix otherwise (kernels
    off, shapes out of range, no keys, or a prior lowering failure)."""
    if key_indices:
        try:
            from igloo_tpu.exec import dispatch
            plan = dispatch.plan_scatter(
                table.num_rows, len(key_indices), nbuckets,
                banned=_ban_key(table.num_rows, nbuckets) in _SCATTER_BANS)
        except Exception:
            plan = None
        if plan is not None:
            lanes = []
            for i in key_indices:
                col = table.column(i)
                col = col.combine_chunks() \
                    if isinstance(col, pa.ChunkedArray) else col
                lanes.append(_column_vals(col, table.schema.field(i).type))
            try:
                return dispatch.exchange_scatter(plan, lanes)
            except Exception:
                # compile-failure rung: ban this shape class and take the
                # numpy path (mirrors the executor's per-kernel rung)
                _SCATTER_BANS.add((plan[1], plan[2]))
                tracing.counter("pallas.compile_fallback")
    return bucket_ids(table, key_indices, nbuckets), None, None


def _ban_key(nrows: int, nbuckets: int):
    from igloo_tpu.exec.capacity import canonical_capacity
    return (canonical_capacity(nrows), nbuckets)


def partition_table(table: pa.Table, key_indices: list[int],
                    nbuckets: int,
                    salt: Optional[tuple] = None) -> list[pa.Table]:
    """Split `table` into bucket slices by key hash: ONE stable argsort +
    boundary slices (zero-copy views of the reordered table), the same shape
    as GRACE's `_split_by_hash`. With `salt` (see `salted_partition`) the
    result has `nbuckets + salt - 1` slices."""
    slices, _base = salted_partition(table, key_indices, nbuckets, salt)
    return slices


def salted_partition(table: pa.Table, key_indices: list[int], nbuckets: int,
                     salt: Optional[tuple] = None
                     ) -> tuple[list[pa.Table], np.ndarray]:
    """(bucket slices, BASE per-bucket row counts). `salt` is
    (hot_bucket, S, role) — the wire fields of a salted `L.Exchange`:

    - role "probe": rows of `hot_bucket` are spread round-robin across
      {hot_bucket} + S-1 extra buckets (ids nbuckets..nbuckets+S-2); every
      probe row lands in exactly ONE bucket, so probe-preserving join
      semantics (INNER/LEFT/SEMI/ANTI with the probe on the preserved side)
      are untouched.
    - role "build": rows of `hot_bucket` stay in place AND are replicated
      into each extra bucket, so every salted fragment sees every build row
      that could match its probe slice. Only the hot BUCKET replicates —
      1/nbuckets of the side per extra bucket — which is what makes salting
      affordable when the build side is too big to broadcast.

    The returned base counts are always for the UNSALTED partitioning: the
    skew sketch the coordinator records must describe the key distribution,
    not the salted layout (else one salted run would erase the very skew
    signal that justified it)."""
    if salt is not None:
        hot, s_total, role = salt
        extra = max(int(s_total) - 1, 0)
    else:
        hot, extra, role = None, 0, None
    total = nbuckets + extra
    if table.num_rows == 0:
        return ([table.slice(0, 0) for _ in range(total)],
                np.zeros(nbuckets, dtype=np.int64))
    pid, dev_order, dev_counts = _partition_arrays(table, key_indices,
                                                   nbuckets)
    base_counts = (dev_counts if dev_counts is not None else
                   np.bincount(pid, minlength=nbuckets)).astype(np.int64)
    if extra and role == "probe":
        idx = np.nonzero(pid == hot)[0]
        r = np.arange(len(idx)) % (extra + 1)
        pid = pid.copy()
        pid[idx[r > 0]] = nbuckets + r[r > 0] - 1
        dev_order = None  # salt rewrote the bucket lane: reorder on host
        tracing.counter("exchange.salted")
        tracing.counter("exchange.salted_rows", len(idx))
    elif extra and role == "build":
        rep = np.nonzero(pid == hot)[0]
        take = np.concatenate([np.arange(table.num_rows, dtype=np.int64)] +
                              [rep] * extra)
        pid = np.concatenate(
            [pid] + [np.full(len(rep), nbuckets + j, dtype=pid.dtype)
                     for j in range(extra)])
        table = table.take(take)
        dev_order = None  # replication lengthened the lane
        tracing.counter("exchange.salted")
        tracing.counter("exchange.salted_rows", len(rep) * extra)
    order = dev_order if dev_order is not None \
        else np.argsort(pid, kind="stable")
    sorted_tbl = table.take(order)
    counts = np.bincount(pid, minlength=total)
    out, off = [], 0
    for b in range(total):
        c = int(counts[b])
        out.append(sorted_tbl.slice(off, c))
        off += c
    return out, base_counts


# --- do_get ticket codec -----------------------------------------------------


def make_ticket(frag_id: str, bucket: Optional[int] = None,
                nbuckets: Optional[int] = None) -> bytes:
    """Encode through the registry (cluster/protocol.py EXCHANGE_TICKET); a
    whole-fragment request stays the bare id so stock clients keep working."""
    if bucket is None:
        return frag_id.encode()
    return json.dumps(protocol.EXCHANGE_TICKET.build(
        frag=frag_id, bucket=bucket, nbuckets=nbuckets)).encode()


def parse_ticket(raw: bytes) -> tuple[str, Optional[int], Optional[int]]:
    t = protocol.parse_exchange_ticket(raw)
    return t["frag"], t["bucket"], t["nbuckets"]


# --- the bytes-budgeted fragment store --------------------------------------


@dataclass
class _Stored:
    schema: pa.Schema
    batches: Optional[list]            # list[pa.RecordBatch]; None = spilled
    nbytes: int
    nbuckets: Optional[int] = None     # hash-partition bucket count (incl. salt)
    ranges: Optional[list] = None      # per-bucket (start, count) batch ranges
    meta: Optional[list] = None        # per-bucket {"rows": .., "bytes": ..}
    spill_path: Optional[str] = None
    seq: int = 0                       # insertion order (spill oldest first)
    rows: int = 0
    # UNSALTED per-bucket row counts: the skew sketch the coordinator
    # records into AdaptiveStats (salting must not mask the skew signal)
    base_rows: Optional[list] = None
    # streaming entries (StreamingPut): per-bucket lists of spill SEGMENT
    # paths written while the result was still arriving — a bucket's full
    # content is its segments' batches followed by its resident range
    bucket_files: Optional[list] = None


def _chunk(table: pa.Table) -> list:
    return table.to_batches(max_chunksize=BATCH_ROWS)


def measured_nbytes(batches) -> int:
    """Resident bytes of a batch list with shared buffers counted ONCE.
    Bucket slices of one reordered table share its physical buffers, and
    every slice of a dictionary column references the WHOLE unified
    dictionary — so summing per-batch `nbytes` prices that dictionary once
    PER BUCKET and the spill budget evicts 3-4x early on dictionary/
    carrier-heavy results. Buffer-address dedupe measures what is actually
    resident."""
    seen: set = set()
    total = 0

    def add(arr):
        nonlocal total
        for buf in arr.buffers():
            if buf is not None and buf.address not in seen:
                seen.add(buf.address)
                total += buf.size
    for b in batches:
        for col in b.columns:
            d = getattr(col, "dictionary", None)
            if d is not None:
                add(d)
            add(col)
    return total


def _plain(table: pa.Table) -> pa.Table:
    """Dictionary columns cast to their value type. Streaming spill segments
    are written incrementally to Arrow IPC files, and the FILE format forbids
    the dictionary replacement that per-chunk dictionaries would need — so
    the streaming path stores plain lanes and leaves dictionary unification
    to the no-spill finish (which rides the classic encoded path)."""
    if not any(pa.types.is_dictionary(f.type) for f in table.schema):
        return table
    cols, fields = [], []
    for i, f in enumerate(table.schema):
        col = table.column(i)
        if pa.types.is_dictionary(f.type):
            col = col.cast(f.type.value_type)
            f = pa.field(f.name, f.type.value_type, f.nullable)
        cols.append(col)
        fields.append(f)
    return pa.table(cols, schema=pa.schema(fields))


class FragmentStore:
    """Thread-safe fragment-result store with a resident-bytes budget.

    `put` accepts an optional partition spec (key column indices, bucket
    count): the result is hash-partitioned ONCE at store time and per-bucket
    rows/bytes metadata recorded, so every later bucket request is a slice,
    not a scan. When resident bytes exceed the budget, whole results spill
    (oldest first) to Arrow IPC files in a private temp dir and are served
    batch-at-a-time off disk — the budget bounds worker RSS, not result size."""

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            budget_bytes = int(os.environ.get(STORE_BUDGET_ENV,
                                              DEFAULT_STORE_BUDGET))
        self.budget_bytes = max(budget_bytes, 1 << 20)
        self._entries: dict[str, _Stored] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._tmpdir: Optional[str] = None
        # release tombstones: a dispatch the coordinator timed out (hung
        # worker) or cancelled keeps RUNNING server-side — gRPC deadlines
        # cancel the call, not the handler. When it finally finishes, its
        # `put` must not resurrect a result the query already released (the
        # coordinator will never release it again -> permanent RSS leak).
        # Fragment ids are per-query uuids, never reused, so dropping any
        # put of a released id is always correct.
        self._released: OrderedDict = OrderedDict()

    # --- writes ---

    def put(self, frag_id: str, table: pa.Table,
            partition: Optional[tuple[list[int], int]] = None,
            salt: Optional[tuple] = None) -> _Stored:
        if partition is not None:
            from igloo_tpu.exec import encoded
            keys, nb = partition
            # store-time hash partition on the query timeline: per-bucket
            # slices of THIS fragment's result, the exchange's shuffle write.
            # Partitioned results ship ENCODED (exec/encoded.py): strings
            # dictionary-encode ONCE on the whole input — the hash routes by
            # dictionary VALUES, so placement is unchanged and every bucket
            # slice shares one unified dictionary instead of rebuilding one
            # per record batch — and numerics narrow per slice under ONE
            # global spec, applied AFTER routing (hashing an offset carrier
            # would misroute keys across the two sides of a join). The peer
            # decodes on fetch (cluster/worker.py _fetch_dep); spilled
            # entries write the carrier bytes to disk as-is.
            with tracing.span("exchange.partition", buckets=nb,
                              rows=table.num_rows, salted=salt is not None):
                table = encoded.encode_strings(table)
                plan = encoded.plan_numeric(table)
                slices, base = salted_partition(table, list(keys), nb, salt)
                batches, ranges, meta = [], [], []
                schema = None
                for s in slices:
                    s = encoded.apply_numeric(s, plan)
                    schema = s.schema if schema is None else schema
                    bs = _chunk(s)
                    ranges.append((len(batches), len(bs)))
                    batches.extend(bs)
                    meta.append({"rows": s.num_rows,
                                 "bytes": sum(b.nbytes for b in bs)})
            tracing.counter("exchange.partitions")
            tracing.counter("exchange.partition_rows", table.num_rows)
            # MEASURED resident bytes, shared buffers counted once: the
            # bucket slices view ONE reordered table (and one unified
            # dictionary per string column), so per-batch nbytes sums would
            # over-report 3-4x on dictionary/carrier-heavy results and make
            # the spill budget evict that much early
            ent = _Stored(schema=schema, batches=batches,
                          nbytes=measured_nbytes(batches),
                          nbuckets=len(slices), ranges=ranges, meta=meta,
                          rows=table.num_rows,
                          base_rows=[int(c) for c in base])
            tracing.counter("exchange.partition_bytes", ent.nbytes)
        else:
            batches = _chunk(table)
            ent = _Stored(schema=table.schema, batches=batches,
                          nbytes=measured_nbytes(batches),
                          rows=table.num_rows)
        return self._install(frag_id, ent)

    def stream_put(self, frag_id: str, keys: list[int], nbuckets: int,
                   salt: Optional[tuple] = None,
                   budget_bytes: Optional[int] = None) -> "StreamingPut":
        """Incremental hash-partitioned write: the caller appends row-group
        sized chunks as they arrive (the streaming exchange — the producer
        never materializes its whole result), and `finish()` installs the
        entry. Chunks are hash-routed into per-bucket accumulators on
        append; when resident bytes cross half of `budget_bytes` (the QUERY
        out-of-core budget; defaults to the store budget) every bucket's
        resident batches flush to its open IPC segment file. A result that
        never spilled finishes through the classic encoded `put` path
        (dictionary unification + numeric narrowing intact,
        docs/compressed_execution.md)."""
        return StreamingPut(self, frag_id, keys, nbuckets, salt,
                            budget_bytes=budget_bytes)

    def _install(self, frag_id: str, ent: _Stored) -> _Stored:
        # a `__dep_<fid>:...` slice is released alongside fragment <fid>, so
        # its orphan check keys on the owning fragment id
        base = frag_id
        if base.startswith("__dep_"):
            base = base[len("__dep_"):].split(":", 1)[0]
        with self._lock:
            if frag_id in self._released or base in self._released:
                tracing.counter("exchange.orphan_dropped")
                self._drop_files_of(ent)
                return ent
            self._seq += 1
            ent.seq = self._seq
            self._entries[frag_id] = ent
            self._enforce_budget_locked()
        return ent

    @staticmethod
    def _drop_files_of(ent: _Stored) -> None:
        paths = list(ent.bucket_files and
                     [p for fs in ent.bucket_files for p in fs] or [])
        if ent.spill_path:
            paths.append(ent.spill_path)
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass

    def _segment_path_locked(self, name: str) -> str:
        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(prefix="igloo-fragstore-")
        return os.path.join(self._tmpdir,
                            f"{name}.arrow".replace("/", "_"))

    def _segment_path(self, name: str) -> str:
        with self._lock:
            return self._segment_path_locked(name)

    def _enforce_budget_locked(self) -> None:
        while self.resident_bytes_locked() > self.budget_bytes:
            resident = [(e.seq, fid) for fid, e in self._entries.items()
                        if e.batches is not None]
            if len(resident) == 0:
                return
            _, fid = min(resident)
            self._spill_locked(fid)

    def resident_bytes_locked(self) -> int:
        return sum(e.nbytes for e in self._entries.values()
                   if e.batches is not None)

    def resident_bytes(self) -> int:
        with self._lock:
            return self.resident_bytes_locked()

    def _spill_locked(self, frag_id: str) -> None:
        ent = self._entries[frag_id]
        if ent.bucket_files is not None:
            # streaming entry: the resident TAIL of each bucket moves to a
            # new per-bucket segment (appended after the ones StreamingPut
            # wrote), so bucket addressing survives the spill
            with tracing.span("exchange.spill", bytes=ent.nbytes):
                for b in range(ent.nbuckets):
                    start, count = ent.ranges[b]
                    if count <= 0:
                        continue
                    path = self._segment_path_locked(f"{frag_id}.b{b}.tail")
                    with pa.OSFile(path, "wb") as f, \
                            pa.ipc.new_file(f, ent.schema) as w:
                        for batch in ent.batches[start:start + count]:
                            w.write_batch(batch)
                    ent.bucket_files[b].append(path)
            ent.batches = None
            ent.ranges = [(0, 0)] * ent.nbuckets
            tracing.counter("exchange.spills")
            tracing.counter("exchange.spill_bytes", ent.nbytes)
            return
        path = self._segment_path_locked(frag_id)
        with tracing.span("exchange.spill", bytes=ent.nbytes):
            with pa.OSFile(path, "wb") as f, \
                    pa.ipc.new_file(f, ent.schema) as w:
                for b in ent.batches:
                    w.write_batch(b)
        ent.spill_path = path
        ent.batches = None
        tracing.counter("exchange.spills")
        tracing.counter("exchange.spill_bytes", ent.nbytes)

    def release(self, ids: list[str]) -> None:
        with self._lock:
            for fid in ids:
                self._released[fid] = None
                self._released.move_to_end(fid)
                ent = self._entries.pop(fid, None)
                if ent is not None:
                    self._drop_files_of(ent)
            while len(self._released) > TOMBSTONE_CAP:
                self._released.popitem(last=False)

    # --- reads ---

    def __contains__(self, frag_id: str) -> bool:
        with self._lock:
            return frag_id in self._entries

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    def bucket_meta(self, frag_id: str) -> Optional[list]:
        with self._lock:
            ent = self._entries.get(frag_id)
            return list(ent.meta) if ent is not None and ent.meta else None

    def _entry_range_locked(self, frag_id: str, bucket: Optional[int],
                            nbuckets: Optional[int]):
        ent = self._entries.get(frag_id)
        if ent is None:
            raise KeyError(frag_id)
        if bucket is None:
            return ent, 0, -1  # -1 = every batch
        if ent.nbuckets is None:
            raise ValueError(f"fragment {frag_id} is not hash-partitioned")
        if nbuckets is not None and nbuckets != ent.nbuckets:
            raise ValueError(
                f"fragment {frag_id} partitioned into {ent.nbuckets} "
                f"buckets, request asked for {nbuckets}")
        if not 0 <= bucket < ent.nbuckets:
            raise ValueError(f"bucket {bucket} out of range")
        start, count = ent.ranges[bucket]
        return ent, start, count

    def stream(self, frag_id: str, bucket: Optional[int] = None,
               nbuckets: Optional[int] = None
               ) -> tuple[pa.Schema, Iterator]:
        """(schema, batch iterator) for a fragment result or one bucket slice.
        Resident entries iterate their in-memory batches; spilled entries read
        one batch at a time from the IPC file (plain buffered reads, NOT a
        memory map: mapped pages would count against this process's RSS for
        the whole stream, defeating the budget), so serving never
        re-materializes the whole result."""
        with self._lock:
            ent, start, count = self._entry_range_locked(frag_id, bucket,
                                                         nbuckets)
            batches = list(ent.batches) if ent.batches is not None else None
            spill = ent.spill_path
            files = ([list(fs) for fs in ent.bucket_files]
                     if ent.bucket_files is not None else None)

        def gen():
            if files is not None:
                # streaming entry: a bucket is its spill segments' batches
                # followed by its resident tail; a whole-fragment read walks
                # every bucket (consumers concat, order is irrelevant)
                sel_files = [p for fs in files for p in fs] if bucket is None \
                    else list(files[bucket])
                for path in sel_files:
                    src = pa.OSFile(path, "rb")
                    try:
                        reader = pa.ipc.open_file(src)
                        for i in range(reader.num_record_batches):
                            yield reader.get_batch(i)
                    finally:
                        src.close()
                if batches is not None:
                    sel = batches if count < 0 \
                        else batches[start:start + count]
                    for b in sel:
                        yield b
                return
            if batches is not None:
                sel = batches if count < 0 else batches[start:start + count]
                for b in sel:
                    yield b
                return
            src = pa.OSFile(spill, "rb")
            try:
                reader = pa.ipc.open_file(src)
                n = reader.num_record_batches if count < 0 else count
                s = 0 if count < 0 else start
                for i in range(s, s + n):
                    yield reader.get_batch(i)
            finally:
                src.close()
        return ent.schema, gen()

    def get_table(self, frag_id: str, bucket: Optional[int] = None,
                  nbuckets: Optional[int] = None) -> pa.Table:
        schema, it = self.stream(frag_id, bucket, nbuckets)
        return pa.Table.from_batches(list(it), schema=schema)


class StreamingPut:
    """Incremental hash-partitioned writer (one producer thread; the store's
    lock guards only the shared install/segment-path steps).

    `append` routes each row-group-sized chunk into per-bucket accumulators;
    when routed-but-unflushed bytes cross the flush threshold (half the store
    budget) EVERY bucket's resident batches are appended to that bucket's open
    IPC segment file and dropped. Flushing all buckets — not just the largest
    — is what actually frees memory: the bucket slices of one routed chunk
    are zero-copy views of a single reordered table, so holding any one of
    them holds them all.

    `finish` installs the entry. A result that never flushed is re-submitted
    through the classic encoded `put` (dictionary-unify once, narrow per
    slice); proven-small data pays one extra in-RAM hash pass to keep the
    PR 16 carrier savings. A flushed result installs as a `bucket_files`
    entry: plain lanes, per-bucket segment files plus the resident tail."""

    def __init__(self, store: FragmentStore, frag_id: str, keys: list[int],
                 nbuckets: int, salt: Optional[tuple],
                 budget_bytes: Optional[int] = None):
        self._store = store
        self._frag_id = frag_id
        self._keys = list(keys)
        self._nbuckets = int(nbuckets)
        self._salt = salt
        extra = max(int(salt[1]) - 1, 0) if salt is not None else 0
        self._total = self._nbuckets + extra
        # flush threshold tracks the QUERY's out-of-core budget when given
        # (the worker store's own budget is sized for caching, not spilling)
        base_budget = budget_bytes if budget_bytes else store.budget_bytes
        self._flush_bytes = max(base_budget // 2, 1 << 19)
        self._schema: Optional[pa.Schema] = None
        self._buckets: list[list] = [[] for _ in range(self._total)]
        self._bucket_rows = [0] * self._total
        self._bucket_bytes = [0] * self._total
        self._base = np.zeros(self._nbuckets, dtype=np.int64)
        self._rows = 0
        self._bytes = 0
        self._resident = 0
        self._spilled = False
        # per-bucket (path, OSFile, ipc writer) — opened at first flush of
        # the bucket, closed in finish()/abort(); the IPC FILE footer only
        # lands on close, and nothing reads a segment before install
        self._writers: list = [None] * self._total

    def append(self, table: pa.Table) -> None:
        table = _plain(table)
        if self._schema is None:
            self._schema = table.schema
        elif table.schema != self._schema:
            table = table.cast(self._schema)
        if table.num_rows == 0:
            return
        tracing.counter("exchange.stream_chunks")
        slices, base = salted_partition(table, self._keys, self._nbuckets,
                                        self._salt)
        self._base += base
        self._rows += table.num_rows
        chunk_batches = []
        for b, s in enumerate(slices):
            if s.num_rows == 0:
                continue
            bs = _chunk(s)
            self._buckets[b].extend(bs)
            self._bucket_rows[b] += s.num_rows
            self._bucket_bytes[b] += sum(x.nbytes for x in bs)
            chunk_batches.extend(bs)
        got = measured_nbytes(chunk_batches)
        self._resident += got
        self._bytes += got
        if self._resident > self._flush_bytes:
            self._flush()

    def _writer(self, b: int):
        if self._writers[b] is None:
            path = self._store._segment_path(f"{self._frag_id}.b{b}")
            f = pa.OSFile(path, "wb")
            self._writers[b] = (path, f, pa.ipc.new_file(f, self._schema))
        return self._writers[b][2]

    def _flush(self) -> None:
        with tracing.span("exchange.spill", bytes=self._resident,
                          streaming=True):
            for b in range(self._total):
                bs = self._buckets[b]
                if not bs:
                    continue
                w = self._writer(b)
                for batch in bs:
                    w.write_batch(batch)
                self._buckets[b] = []
        tracing.counter("exchange.spills")
        tracing.counter("exchange.spill_bytes", self._resident)
        self._resident = 0
        self._spilled = True

    def _close_writers(self) -> list[list[str]]:
        files: list[list[str]] = [[] for _ in range(self._total)]
        for b, w in enumerate(self._writers):
            if w is None:
                continue
            path, f, writer = w
            writer.close()
            f.close()
            files[b] = [path]
            self._writers[b] = None
        return files

    def finish(self) -> _Stored:
        if self._schema is None:
            raise ValueError("stream_put finished without any append")
        if not self._spilled:
            # proved under budget: one concat + the classic encoded put
            whole = pa.Table.from_batches(
                [b for bs in self._buckets for b in bs], schema=self._schema)
            self._buckets = [[] for _ in range(self._total)]
            return self._store.put(self._frag_id, whole,
                                   partition=(self._keys, self._nbuckets),
                                   salt=self._salt)
        files = self._close_writers()
        batches, ranges, meta = [], [], []
        for b in range(self._total):
            bs = self._buckets[b]
            ranges.append((len(batches), len(bs)))
            batches.extend(bs)
            meta.append({"rows": self._bucket_rows[b],
                         "bytes": self._bucket_bytes[b]})
        ent = _Stored(schema=self._schema, batches=batches,
                      nbytes=measured_nbytes(batches),
                      nbuckets=self._total, ranges=ranges, meta=meta,
                      rows=self._rows,
                      base_rows=[int(c) for c in self._base],
                      bucket_files=files)
        tracing.counter("exchange.partitions")
        tracing.counter("exchange.partition_rows", self._rows)
        tracing.counter("exchange.partition_bytes", self._bytes)
        return self._store._install(self._frag_id, ent)

    def abort(self) -> None:
        """Drop everything (producer failed mid-stream): close and unlink
        any segment files, release the accumulators."""
        for files in self._close_writers():
            for p in files:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        self._buckets = [[] for _ in range(self._total)]
        self._resident = 0
