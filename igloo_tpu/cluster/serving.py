"""Multi-tenant serving front door: admission control, HBM-aware concurrent
scheduling, and load shedding for the coordinator (docs/serving.md).

The Flight SQL endpoint used to run every query on its own gRPC thread with
no bound at all: under concurrent traffic the cluster either serialized on
the device or planned past HBM and crashed — and PR7's fault-tolerance layer
can only clean up after the crash. This module is the Presto-style front
door (PAPERS.md: "Accelerating Presto with GPUs") that turns overload into
bounded latency and *retryable* rejections instead of failures:

- **bounded admission queue**: one FIFO per priority tier, total depth
  bounded; past the bound a query is SHED with a retryable "server busy"
  Flight error carrying a retry-after hint, which the client-side RpcPolicy
  backoff absorbs (`IGLOO_BUSY` marker, cluster/client.py);
- **weighted fair dequeue** across priority tiers (0 = interactive, 1 =
  normal, 2 = batch by default): each admission picks the non-empty tier
  with the lowest served/weight ratio, so a saturating low-priority flood
  cannot starve interactive queries and vice versa;
- **per-session in-flight caps**: one chatty dashboard cannot occupy the
  whole queue (the session id rides the extended do_get ticket);
- **HBM-aware concurrency**: each query carries a predicted device-memory
  footprint — the AdaptiveStats `peak_hbm_bytes` observation for its plan
  fingerprint when one exists, a conservative bytes-of-inputs estimate on
  first sight — and admission reserves it against a cluster HBM budget, so
  concurrent queries never plan past memory. A query predicted to exceed
  the WHOLE budget is admitted alone and pre-flagged for the degradation
  ladder (the coordinator runs it through the chunked/GRACE budget tiers).

Knobs — `[serving]` config section, each overridable by the matching
IGLOO_SERVING_* env var (env wins, like every [rpc] knob):

- ``IGLOO_SERVING_QUEUE`` / ``queue_depth``: total queued-query bound
  (default 64). **0 is the kill switch**: the admission layer disappears
  and queries serialize one at a time — the pre-serving behavior, for A/B.
- ``IGLOO_SERVING_CONCURRENCY`` / ``max_concurrency``: queries allowed to
  execute concurrently (default 4).
- ``IGLOO_SERVING_SESSION_INFLIGHT`` / ``session_inflight``: per-session
  queued+running cap (default 16).
- ``IGLOO_SERVING_HBM_BUDGET`` / ``hbm_budget_bytes``: cluster HBM budget
  in bytes the footprint gate reserves against (default 0 = gate off —
  CPU/dev hosts report no device memory).
- ``IGLOO_SERVING_WEIGHTS`` / ``weights``: comma-separated per-tier
  dequeue weights, highest priority first (default ``4,2,1``; the list
  length defines how many tiers exist).

Fault-injection points (cluster/faults.py): ``serving.admit`` fires on
every submission (an injected error is counted as a shed — the chaos smoke
drives client-side retry through it) and ``serving.dequeue`` on every
admission grant.
"""
from __future__ import annotations

import os
import threading
import time
from collections import Counter, deque
from typing import Optional

from igloo_tpu.cluster import faults
from igloo_tpu.utils import flight_recorder, tracing

# lock discipline (checked by igloo-lint lock-discipline): submissions run on
# Flight RPC threads and releases on whichever thread finishes the query, so
# all queue/slot/reservation state is guarded by the controller's condition
# (a Condition IS a lock as a context manager)
_GUARDED_BY = {"_cond": ("_queues", "_served", "_running", "_reserved",
                         "_running_demote", "_sessions")}

QUEUE_ENV = "IGLOO_SERVING_QUEUE"
CONCURRENCY_ENV = "IGLOO_SERVING_CONCURRENCY"
SESSION_ENV = "IGLOO_SERVING_SESSION_INFLIGHT"
HBM_BUDGET_ENV = "IGLOO_SERVING_HBM_BUDGET"
WEIGHTS_ENV = "IGLOO_SERVING_WEIGHTS"

DEFAULT_QUEUE_DEPTH = 64
DEFAULT_CONCURRENCY = 4
DEFAULT_SESSION_INFLIGHT = 16
DEFAULT_WEIGHTS = (4, 2, 1)

#: marker the shed error carries so clients can tell "server busy, retry
#: after the hint" from other unavailability (cluster/client.py parses it)
BUSY_MARKER = "IGLOO_BUSY"


class ServerBusy(Exception):
    """Load shed: the admission queue (or a per-session cap) is full. Maps
    to a RETRYABLE FlightUnavailableError carrying a retry-after hint, so
    the client-side RpcPolicy backoff absorbs it instead of failing."""

    def __init__(self, reason: str, retry_after_s: float):
        self.retry_after_s = round(retry_after_s, 3)
        super().__init__(
            f"{BUSY_MARKER} server busy ({reason}); "
            f"retry_after_s={self.retry_after_s}")

    def as_flight_error(self):
        import pyarrow.flight as flight
        return flight.FlightUnavailableError(str(self))


def parse_retry_after(msg: str) -> Optional[float]:
    """The retry-after hint out of a shed error's message, or None."""
    marker = "retry_after_s="
    if BUSY_MARKER not in msg or marker not in msg:
        return None
    try:
        tail = msg.split(marker, 1)[1]
        num = ""
        for ch in tail:
            if ch.isdigit() or ch == ".":
                num += ch
            else:
                break
        return float(num)
    except ValueError:
        return None


def _env_int(name: str, fallback: Optional[int], default: int) -> int:
    v = os.environ.get(name)
    if v is not None and v != "":
        return int(v)
    return fallback if fallback is not None else default


def _env_weights(fallback) -> tuple:
    v = os.environ.get(WEIGHTS_ENV)
    if v:
        ws = tuple(max(1, int(x)) for x in v.split(",") if x.strip())
        if ws:
            return ws
    if fallback:
        return tuple(max(1, int(x)) for x in fallback)
    return DEFAULT_WEIGHTS


class Permit:
    """One admitted (or bypassed) query's hold on the serving controller.
    `release()` is idempotent — the streaming path releases from a finally
    AND a weakref finalizer."""

    __slots__ = ("_controller", "wait_s", "priority", "session", "demote",
                 "reserve_bytes", "_mode", "_released", "_trace_ctx", "_t0")

    def __init__(self, controller, priority: int, session: str,
                 demote: bool = False, reserve_bytes: int = 0,
                 wait_s: float = 0.0, mode: str = "admitted"):
        self._controller = controller
        self.priority = priority
        self.session = session
        self.demote = demote                # run via the degradation ladder
        self.reserve_bytes = reserve_bytes  # HBM bytes reserved while running
        self.wait_s = wait_s
        self._mode = mode                   # admitted | serial | bypass
        self._released = False
        # flight-recorder hold span: the permit is granted on the request
        # thread (trace context capturable) but released by whichever thread
        # finishes the stream — so the hold is recorded AT release, into the
        # trace captured here (docs/observability.md#distributed-tracing)
        self._trace_ctx = flight_recorder.capture() \
            if mode == "admitted" else (None, None, None)
        self._t0 = time.time()

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        trace, _parent, proc = self._trace_ctx
        if trace is not None:
            # concurrency-slot + HBM-reservation hold: how long this query
            # occupied its admission (the dark time between "admitted" and
            # "stream finished" that queue-wait alone never showed).
            # Top-level: the hold outlives the request scope's root span
            # (it releases when the result STREAM drains), so nesting it
            # under the root would break containment
            trace.add_span("serving.hbm_hold", self._t0, time.time(),
                           proc=proc,
                           reserve_bytes=self.reserve_bytes,
                           priority=self.priority)
        if self._mode == "admitted":
            self._controller._release(self)
        elif self._mode == "serial":
            self._controller._serial_lock.release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class _Waiter:
    __slots__ = ("priority", "session", "reserve_bytes", "demote", "admitted",
                 "abandoned")

    def __init__(self, priority: int, session: str, reserve_bytes: int,
                 demote: bool):
        self.priority = priority
        self.session = session
        self.reserve_bytes = reserve_bytes
        self.demote = demote
        self.admitted = False
        self.abandoned = False


class AdmissionController:
    """The coordinator's admission queue + HBM-aware concurrency gate.

    Explicit constructor arguments override config; the matching
    IGLOO_SERVING_* env var overrides both (env wins, [rpc]-style)."""

    def __init__(self, queue_depth: Optional[int] = None,
                 max_concurrency: Optional[int] = None,
                 session_inflight: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 weights=None):
        self.queue_depth = _env_int(QUEUE_ENV, queue_depth,
                                    DEFAULT_QUEUE_DEPTH)
        self.max_concurrency = max(
            1, _env_int(CONCURRENCY_ENV, max_concurrency,
                        DEFAULT_CONCURRENCY))
        self.session_inflight = max(
            1, _env_int(SESSION_ENV, session_inflight,
                        DEFAULT_SESSION_INFLIGHT))
        self.hbm_budget_bytes = max(
            0, _env_int(HBM_BUDGET_ENV, hbm_budget_bytes, 0))
        self.weights = _env_weights(weights)
        self._cond = threading.Condition()
        self._queues: dict[int, deque] = {
            p: deque() for p in range(len(self.weights))}
        self._served = [0] * len(self.weights)
        self._running = 0
        self._reserved = 0          # HBM bytes reserved by running queries
        self._running_demote = 0    # running over-budget (isolated) queries
        self._sessions: Counter = Counter()
        # kill-switch mode: one query at a time, the pre-serving behavior
        self._serial_lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.queue_depth > 0

    # --- submission --------------------------------------------------------

    def submit(self, priority: int = 1, session: str = "",
               predicted_hbm_bytes: int = 0,
               deadline: Optional[float] = None) -> Permit:
        """Block until the query may run; returns its Permit. Sheds with
        ServerBusy when the queue or the session's in-flight cap is full.
        An already-expired deadline bypasses the queue entirely — the
        executor's own deadline accounting must fire, not a shed."""
        try:
            faults.inject("serving.admit")
        except Exception:
            tracing.counter("serving.shed")
            raise
        if not self.enabled:
            # serialized single-query mode (A/B kill switch); a deadline
            # spent while waiting for the one slot surfaces through the
            # executor's own accounting, never as a serving error
            if deadline is not None:
                rem = deadline - time.time()
                if rem <= 0 or not self._serial_lock.acquire(timeout=rem):
                    return Permit(self, priority, session, mode="bypass")
            else:
                self._serial_lock.acquire()
            return Permit(self, priority, session, mode="serial")
        if deadline is not None and time.time() >= deadline:
            return Permit(self, priority, session, mode="bypass")
        priority = min(max(int(priority), 0), len(self.weights) - 1)
        demote = bool(self.hbm_budget_bytes and
                      predicted_hbm_bytes > self.hbm_budget_bytes)
        reserve = min(int(predicted_hbm_bytes), self.hbm_budget_bytes) \
            if self.hbm_budget_bytes else 0
        w = _Waiter(priority, session, reserve, demote)
        t0 = time.perf_counter()
        # timeline: the admission wait is a first-class span — a query slow
        # because it QUEUED (vs executed slowly) is visibly different
        with tracing.span("serving.queue", priority=priority), self._cond:
            if self._sessions[session] >= self.session_inflight:
                tracing.counter("serving.shed")
                tracing.counter("serving.shed_session")
                raise ServerBusy(f"session {session or 'anon'!r} at its "
                                 f"{self.session_inflight}-query in-flight "
                                 "cap", self._retry_after_locked())
            if sum(len(q) for q in self._queues.values()) >= self.queue_depth:
                tracing.counter("serving.shed")
                raise ServerBusy(
                    f"admission queue full ({self.queue_depth})",
                    self._retry_after_locked())
            self._sessions[session] += 1
            self._queues[priority].append(w)
            self._schedule_locked()
            while not w.admitted:
                rem = None if deadline is None else deadline - time.time()
                if rem is not None and rem <= 0:
                    # queue wait ate the budget: hand back a bypass permit so
                    # execution surfaces query.deadline_exceeded through the
                    # normal accounting path instead of a serving error
                    w.abandoned = True
                    self._queues[priority].remove(w)
                    self._sessions[session] -= 1
                    if not self._sessions[session]:
                        del self._sessions[session]
                    self._gauges_locked()
                    return Permit(self, priority, session, mode="bypass",
                                  wait_s=time.perf_counter() - t0)
                self._cond.wait(timeout=rem if rem is not None else 1.0)
        wait = time.perf_counter() - t0
        permit = Permit(self, priority, session, demote=demote,
                        reserve_bytes=reserve, wait_s=wait)
        try:
            faults.inject("serving.dequeue")
        except Exception:
            permit.release()
            tracing.counter("serving.shed")
            raise
        tracing.counter("serving.admitted")
        tracing.histogram("serving.queue_wait_s", wait)
        return permit

    def _release(self, permit: Permit) -> None:
        with self._cond:
            self._running -= 1
            self._reserved -= permit.reserve_bytes
            if permit.demote:
                self._running_demote -= 1
            self._sessions[permit.session] -= 1
            if not self._sessions[permit.session]:
                del self._sessions[permit.session]
            self._schedule_locked()

    # --- scheduling (caller-locked) ----------------------------------------

    def _retry_after_locked(self) -> float:
        """Back-pressure hint: scale with queue pressure, bounded so a
        retrying client polls a draining queue promptly."""
        backlog = sum(len(q) for q in self._queues.values()) + self._running
        return min(0.05 * (1 + backlog), 2.0)

    def _schedule_locked(self) -> None:
        """Admit queued queries while slots + the HBM budget allow; weighted
        fair across tiers, FIFO within one."""
        admitted = False
        while self._running < self.max_concurrency:
            w = self._pick_locked()
            if w is None:
                break
            self._queues[w.priority].popleft()
            self._served[w.priority] += 1
            self._running += 1
            self._reserved += w.reserve_bytes
            if w.demote:
                self._running_demote += 1
            w.admitted = True
            admitted = True
        self._gauges_locked()
        if admitted:
            self._cond.notify_all()

    def _pick_locked(self) -> Optional[_Waiter]:
        """Next admissible waiter: the FIFO head of the tier with the
        lowest served/weight ratio (the weighted-fair rule — ties break
        toward higher priority). Heads only, and ONLY the fairness
        winner's: a winning head that doesn't fit the HBM budget is a
        BARRIER — nothing else admits until running queries drain enough
        for it (running queries always finish or deadline out, so the
        barrier is bounded). Skipping it for other tiers — or for later
        entries in its own tier — would starve a big query forever under
        sustained small-query traffic; when nothing is running, anything
        fits (a single over-budget query runs alone — pre-flagged
        `demote`)."""
        order = sorted((p for p in self._queues if self._queues[p]),
                       key=lambda p: (self._served[p] / self.weights[p], p))
        if not order:
            return None
        w = self._queues[order[0]][0]
        return w if self._fits_locked(w) else None

    def _fits_locked(self, w: _Waiter) -> bool:
        if self._running == 0:
            return True
        if w.demote or self._running_demote:
            # over-budget queries run ALONE: neither beside others (their
            # reservation is the whole budget in spirit) nor with anything
            # admitted beside them — including 0-reserve unsized plans
            return False
        if not self.hbm_budget_bytes:
            return True
        return self._reserved + w.reserve_bytes <= self.hbm_budget_bytes

    def _gauges_locked(self) -> None:
        tracing.gauge("serving.running", self._running)
        tracing.gauge("serving.hbm_reserved_bytes", self._reserved)
        total = 0
        for p, q in self._queues.items():
            total += len(q)
            tracing.gauge(f"serving.queued.p{p}", len(q))
        tracing.gauge("serving.queued", total)

    # --- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """Queue/slot state for the coordinator's serving_status action,
        shaped by the registry (cluster/protocol.py SERVING_STATUS)."""
        from igloo_tpu.cluster import protocol
        with self._cond:
            return protocol.SERVING_STATUS.build(
                enabled=self.enabled,
                queue_depth=self.queue_depth,
                max_concurrency=self.max_concurrency,
                session_inflight=self.session_inflight,
                hbm_budget_bytes=self.hbm_budget_bytes,
                weights=list(self.weights),
                running=self._running,
                hbm_reserved_bytes=self._reserved,
                queued={str(p): len(q) for p, q in self._queues.items()},
                sessions=dict(self._sessions),
            )


# --- footprint prediction -----------------------------------------------------


def predict_hbm_bytes(plan) -> int:
    """Predicted device-memory footprint of a bound plan for the admission
    gate: the AdaptiveStats `peak_hbm_bytes` observation for the plan's
    structural fingerprint when one exists (a previous run of the same
    shape MEASURED its watermark), else a conservative first-sight estimate
    — decoded-lane bytes of every scanned source, doubled for join/sort
    intermediates. Over-estimation costs concurrency; under-estimation is
    what the degradation ladder exists to absorb (docs/serving.md)."""
    from igloo_tpu.exec import hints
    if hints.adaptive_enabled():
        fp = hints.plan_fp(plan)
        if fp is not None:
            rec = hints.adaptive_store().observed(fp)
            if rec and rec.get("peak_hbm_bytes"):
                return int(rec["peak_hbm_bytes"])
    from igloo_tpu.exec.chunked import estimated_lane_bytes
    from igloo_tpu.plan import logical as L
    total = 0
    for n in L.walk_plan(plan):
        if isinstance(n, L.Scan) and n.provider is not None:
            nb = estimated_lane_bytes(n.provider)
            if nb:
                total += nb
    return int(total * 2)
