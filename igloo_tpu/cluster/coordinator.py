"""Coordinator: cluster membership, distributed execution, Flight SQL front door.

Parity map against the reference:
- membership + heartbeat: MyCoordinatorService (crates/coordinator/src/
  service.rs:22-51). The reference records `last_seen` and never acts on it
  (gap G6); here a sweeper thread EVICTS silent workers and the executor
  re-dispatches their fragments (fragments are pure functions of their inputs,
  so re-execution is safe — the elastic recovery SURVEY §5.3 calls for).
- wave scheduler: DistributedExecutor (distributed_executor.rs:36-193) — same
  ready-set/wave structure, but plan serialization is real (serde.py; the
  reference ships empty bytes, G1), results are real Arrow IPC streams (the
  reference fabricates a dummy batch, G1), and a server actually implements
  fragment execution (G2).
- front door: IglooFlightSqlService implements 2 of the proto's 10 Flight
  methods and executes the query TWICE (once in get_flight_info for the
  schema, once in do_get — crates/api/src/lib.rs:81-149). Here
  get_flight_info PLANS only (schema comes from the bound plan), do_get
  executes once, and the served surface is: handshake (token auth),
  list_flights, get_flight_info, get_schema, do_get, do_put (table upload),
  do_exchange (cmd = query stream / path = upload + echo), do_action,
  list_actions — plus PollFlightInfo as the `poll_flight_info` action
  (pyarrow's FlightServerBase exposes no server hook for the real RPC).
"""
from __future__ import annotations

import concurrent.futures as cf
import contextlib
import json
import os
import threading
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Optional

import pyarrow as pa
import pyarrow.flight as flight

from igloo_tpu.cluster import events, faults, protocol, rpc, serde, serving
from igloo_tpu.cluster.fragment import DistributedPlanner, QueryFragment
from igloo_tpu.cluster.rpc import flight_action
from igloo_tpu.engine import QueryEngine
from igloo_tpu.errors import (
    DeadlineExceededError, IglooError, QueryCancelledError,
)
from igloo_tpu.utils import flight_recorder, stats, timeseries, tracing, watch

#: default per-query deadline (seconds) for the distributed path; unset or
#: <= 0 = unbounded. Precedence: per-call override > this env var > [rpc]
#: query_deadline_s config (env beats config, like every other [rpc] knob).
#: A PER-CALL deadline_s of 0 is different: it is an already-spent budget
#: and expires the query immediately (matching rpc.call_options, where a
#: deadline in the past still produces DEADLINE_EXCEEDED, not "no deadline")
QUERY_DEADLINE_ENV = "IGLOO_QUERY_DEADLINE_S"

#: how long recovery waits for SOME worker to (re-)register when every
#: worker is momentarily unreachable — a rolling restart or a flaky blip
#: that evicted the whole fleet should stall the query briefly, not fail it
#: (bounded by the query deadline when one is set)
RECOVER_WAIT_S = 5.0

#: front-door result cache for the distributed path (docs/serving.md):
#: repeated dashboard-shaped queries short-circuit admission entirely. "0"
#: disables it — the A/B the test suite and the adaptive/chaos smokes pin
#: (a cached query skips execution, so assertions about what execution DID
#: would otherwise flip on repetition).
RESULT_CACHE_ENV = "IGLOO_SERVING_RESULT_CACHE"

#: distributed results larger than this are not teed into the result cache
#: while being relayed (the coordinator would otherwise materialize what
#: streaming exists to avoid)
RESULT_CACHE_MAX_BYTES = 64 << 20

#: lock discipline for the coordinator's shared state (lint: lock-discipline
#: enforces these module-wide, any receiver). `_lock` covers BOTH instances
#: of the name: Membership's worker map/evicted set and CoordinatorServer's
#: table-spec registry — each is touched by the sweeper thread, the Flight
#: handler pool, and the dispatch pool. `_totals_lock` guards the metrics
#: publish slot (`last_metrics`) and the cumulative per-worker totals; the
#: event-journal ingest delegates to cluster/events.py, whose ring carries
#: its own module-level `_GUARDED_BY`.
_GUARDED_BY = {
    "_lock": ("_workers", "_evicted_ids", "_table_specs"),
    "_queries_lock": ("_queries",),
    "_totals_lock": ("last_metrics", "worker_totals"),
}


def _is_oom(ex: BaseException) -> bool:
    """An out-of-device-memory failure the degradation ladder can absorb:
    a Python MemoryError, XLA's RESOURCE_EXHAUSTED, or either surfacing in
    a worker-reported fragment failure's message."""
    if isinstance(ex, MemoryError):
        return True
    msg = str(ex)
    return ("RESOURCE_EXHAUSTED" in msg or "MemoryError" in msg
            or "Out of memory" in msg or "out of memory" in msg)


def _released_stream(gen, permit):
    """Wrap a result stream so its serving permit releases when the stream
    finishes, errors, or is abandoned unconsumed (weakref finalizer — an
    unstarted generator's close() never enters its finally block).
    `Permit.release` is idempotent, so double-firing is safe."""
    def g():
        try:
            yield from gen
        finally:
            permit.release()
    out = g()
    weakref.finalize(out, permit.release)
    return out


@dataclass
class WorkerState:
    worker_id: str
    addr: str
    last_seen: float
    tables_pushed: set = field(default_factory=set)
    # topology reported at registration/heartbeat (cluster/serde.py
    # worker_info_*): size of the worker's LOCAL mesh — the chips one
    # fragment runs across — and its execution-slot bound. The planner sizes
    # bucket counts with hosts and weights bucket placement with these
    # (docs/distributed.md "Two-level topology").
    devices: int = 1
    slots: int = 0


class Membership:
    """Live-worker registry with liveness eviction (closes reference gap G6:
    `last_seen` recorded at service.rs:43-49 but nothing ever consumes it)."""

    def __init__(self, timeout_s: float = 15.0):
        self.timeout_s = timeout_s
        self._workers: dict[str, WorkerState] = {}
        # ids evicted at least once: a re-registration from one of these is
        # a RECOVERY (journaled worker_recover, not worker_join)
        self._evicted_ids: set = set()
        self._lock = threading.Lock()

    def register(self, worker_id: str, addr: str, devices: int = 1,
                 slots: int = 0) -> None:
        with self._lock:
            rejoin = worker_id in self._evicted_ids
            self._evicted_ids.discard(worker_id)
            self._workers[worker_id] = WorkerState(
                worker_id, addr, time.time(),
                devices=max(int(devices), 1), slots=int(slots))
        tracing.counter("coordinator.workers_registered")
        if rejoin:
            events.emit("worker_recover", worker=worker_id, addr=addr,
                        devices=int(devices), slots=int(slots))
        else:
            events.emit("worker_join", worker=worker_id, addr=addr,
                        devices=int(devices), slots=int(slots))

    def heartbeat(self, worker_id: str, addr: str = "",
                  devices: Optional[int] = None,
                  slots: Optional[int] = None) -> bool:
        """True if known (reference answers ok=false for unknown workers —
        the worker should re-register). `devices`/`slots` refresh the
        topology so a worker whose visible device count or slot bound
        changed (restart behind the same id, hotplugged slice, retuned
        IGLOO_WORKER_SLOTS) is re-planned against reality, not its
        registration-time snapshot."""
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None:
                return False
            w.last_seen = time.time()
            if addr:
                w.addr = addr
            if devices:
                w.devices = max(int(devices), 1)
            if slots:
                w.slots = int(slots)
            return True

    def topology(self) -> dict:
        """addr -> local mesh device count for every live worker."""
        with self._lock:
            return {w.addr: w.devices for w in self._workers.values()}

    def evict(self, worker_id: str) -> None:
        with self._lock:
            known = self._workers.pop(worker_id, None) is not None
            if known:
                self._evicted_ids.add(worker_id)
        tracing.counter("coordinator.workers_evicted")
        if known:
            events.emit("worker_evict", severity="warn", worker=worker_id,
                        reason="unreachable")

    def sweep(self) -> list[str]:
        """Evict workers silent for > timeout; returns evicted ids."""
        cutoff = time.time() - self.timeout_s
        with self._lock:
            dead = [w.worker_id for w in self._workers.values()
                    if w.last_seen < cutoff]
            for wid in dead:
                self._workers.pop(wid, None)
                self._evicted_ids.add(wid)
        for wid in dead:
            tracing.counter("coordinator.workers_evicted")
            events.emit("worker_evict", severity="warn", worker=wid,
                        reason="heartbeat_timeout")
        return dead

    def live(self) -> list[WorkerState]:
        with self._lock:
            return list(self._workers.values())

    def by_addr(self, addr: str) -> Optional[WorkerState]:
        with self._lock:
            for w in self._workers.values():
                if w.addr == addr:
                    return w
        return None


class CancelToken:
    """Cooperative per-query cancellation flag, checked between fragment
    waves, before each dispatch, and per relayed batch."""

    def __init__(self):
        self._ev = threading.Event()

    def cancel(self) -> None:
        self._ev.set()

    @property
    def cancelled(self) -> bool:
        return self._ev.is_set()


class DistributedExecutor:
    """Wave-based fragment scheduler (distributed_executor.rs:36-193 parity,
    with the wire layer real and worker failure handled by re-dispatch:
    fragments are pure functions of their inputs, so losing a worker only
    costs re-execution of the fragments whose sole result copy it held).

    Failure budget: every query runs under an optional DEADLINE (per-call
    override > constructor default > IGLOO_QUERY_DEADLINE_S > [rpc]
    query_deadline_s) and a
    CancelToken registered under its qid (the `cancel_query` Flight action).
    Hung-worker detection is deadline-driven: a dispatch that exceeds its
    per-call RPC deadline is a dead-worker signal and enters the `_recover`
    re-dispatch path — a worker that accepts TCP but never answers costs one
    bounded timeout, not a wedged query. A cancelled or over-deadline query
    releases its FragmentStore results and stops dispatching instead of
    running to completion."""

    def __init__(self, membership: Membership, max_parallel: int = 16,
                 max_recoveries: int = 8,
                 rpc_policy: Optional[rpc.RpcPolicy] = None,
                 default_deadline_s: Optional[float] = None):
        self.membership = membership
        self.max_parallel = max_parallel
        self.max_recoveries = max_recoveries
        self.rpc_policy = rpc_policy   # None -> rpc.default_policy() per call
        if default_deadline_s is None:
            env = os.environ.get(QUERY_DEADLINE_ENV)
            default_deadline_s = float(env) if env else None
        if default_deadline_s is not None and default_deadline_s <= 0:
            default_deadline_s = None  # "0" = explicitly unbounded
        self.default_deadline_s = default_deadline_s
        # per-fragment metrics of the most recent query: the working version
        # of the reference's never-populated QueryComplete{total_rows,
        # execution_time_ms} (distributed.proto:66-69, SURVEY §5.5)
        self.last_metrics: dict = {}
        # CUMULATIVE per-worker fragment totals (fragments / rows / seconds /
        # bytes since coordinator start): the aggregation the coordinator's
        # `metrics` Flight action exports as labeled Prometheus series
        self.worker_totals: dict = {}
        self._totals_lock = threading.Lock()
        # in-flight queries by qid -> CancelToken (cancel_query targets)
        self._queries: dict[str, CancelToken] = {}
        self._queries_lock = threading.Lock()

    def _policy(self) -> rpc.RpcPolicy:
        return self.rpc_policy or rpc.default_policy()

    def cancel(self, qid: str) -> bool:
        """Flip a running query's cancel token; False if qid is unknown
        (already finished, or never existed)."""
        with self._queries_lock:
            tok = self._queries.get(qid)
        if tok is None:
            return False
        tok.cancel()
        return True

    def active_queries(self) -> list[str]:
        with self._queries_lock:
            return list(self._queries)

    def execute(self, fragments: list[QueryFragment],
                deadline_s: Optional[float] = None,
                qid: Optional[str] = None, sql: str = "",
                adaptive_info: Optional[list] = None,
                extra_metrics: Optional[dict] = None,
                trace: Optional[flight_recorder.Trace] = None,
                budget: Optional[int] = None) -> pa.Table:
        schema, gen = self.execute_stream(fragments, deadline_s=deadline_s,
                                          qid=qid, sql=sql,
                                          adaptive_info=adaptive_info,
                                          extra_metrics=extra_metrics,
                                          trace=trace, budget=budget)
        return pa.Table.from_batches(list(gen), schema=schema)

    def execute_stream(self, fragments: list[QueryFragment],
                       deadline_s: Optional[float] = None,
                       qid: Optional[str] = None, sql: str = "",
                       adaptive_info: Optional[list] = None,
                       extra_metrics: Optional[dict] = None,
                       trace: Optional[flight_recorder.Trace] = None,
                       budget: Optional[int] = None
                       ) -> tuple[pa.Schema, object]:
        """Run the fragment waves, then return (schema, batch generator)
        streaming the root result from its worker — the coordinator never
        holds more than one in-flight batch of a distributed result. The
        generator publishes per-query metrics and releases worker-held
        fragment results when it is exhausted (or closed). Cancellation and
        the deadline are checked between waves, before every dispatch, and
        per relayed batch."""
        frags = {f.id: f for f in fragments}
        root_id = fragments[-1].id
        completed: dict[str, str] = {}  # frag id -> worker addr holding result
        pending = set(frags)
        recoveries = 0
        t_start = time.time()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        # `is not None`, not truthy: a per-call deadline_s of 0 is a spent
        # budget and must expire the query NOW, not run it unbounded
        deadline = t_start + deadline_s if deadline_s is not None else None
        qid = qid or uuid.uuid4().hex[:12]
        token = CancelToken()
        with self._queries_lock:
            self._queries[qid] = token
        if trace is not None:
            # ownership handoff: this query's trace is now published by
            # _finalize (at stream end / error), not by the do_get handler
            trace.deferred = True
            trace.qid = trace.qid or qid
        # per-QUERY metrics dict: concurrent queries each build their own and
        # publish atomically at the end (last_metrics = last finished query).
        # Per-fragment entries attribute wall time to dispatch (RPC + queue)
        # vs execute (worker-reported) vs dep_fetch (peer transfers); the
        # query-level recover_s/fetch_s cover re-dispatch and the root fetch.
        metrics: dict = {"qid": qid, "fragments": [], "recoveries": 0,
                         "recover_s": 0.0, "fetch_s": 0.0, "status": "ok",
                         "deadline_s": deadline_s,
                         "cancelled": False, "deadline_exceeded": False,
                         # every addr a fragment was EVER dispatched to
                         # (set.add is atomic under the GIL; "_"-prefixed
                         # keys never publish): _recover reassigns
                         # frags[fid].worker, so release must remember the
                         # evicted addr too — its handler may still be
                         # running and needs the tombstone
                         "_addrs": set(),
                         # flight-recorder stitching surface: dispatch spans
                         # + worker span trees land here; the root is the
                         # do_get request scope's root span (captured on
                         # THIS thread — the dispatch pool can't read it)
                         "_trace": trace,
                         "_trace_root": flight_recorder.current_root(),
                         # per-worker out-of-core budget of an OVERSIZED
                         # query (docs/out_of_core.md): shipped inside every
                         # dispatch so workers stream-spill / GRACE under it
                         "_budget": budget,
                         "trace_id": trace.trace_id if trace is not None
                         else ""}
        if extra_metrics:
            # serving-path facts (queue_wait_s / priority / demoted) ride
            # beside the execution metrics into last_metrics + query_log
            metrics.update(extra_metrics)
        shuffle_buckets = {f.bucket for f in fragments
                          if f.bucket is not None}
        metrics["shuffle_buckets"] = len(shuffle_buckets)
        # the planner's per-join decision records (strategy / salt /
        # adaptive_source), so sweep JSON and last_metrics show WHY this
        # plan shape was chosen (docs/adaptive.md)
        metrics["adaptive"] = list(adaptive_info or ())
        try:
            with cf.ThreadPoolExecutor(self.max_parallel) as pool:
                while pending:
                    self._check_query(token, deadline, metrics)
                    ready = [frags[fid] for fid in pending
                             if frags[fid].is_ready(set(completed))]
                    if not ready:
                        raise IglooError(
                            "circular dependency in fragment graph")
                    futs = {pool.submit(self._dispatch, f, dict(completed),
                                        metrics, deadline, token): f
                            for f in ready}
                    dead: set[str] = set()
                    lost_deps: set[str] = set()
                    busy: list = []
                    for fut in cf.as_completed(futs):
                        f = futs[fut]
                        try:
                            fut.result()
                        except _WorkerBusy as ex:
                            busy.append((f.id, ex.addr))
                            continue
                        except _WorkerDied as ex:
                            dead.add(ex.addr)
                            continue
                        except _DepLost as ex:
                            lost_deps.add(ex.frag_id)
                            continue
                        completed[f.id] = f.worker
                        pending.discard(f.id)
                    if busy:
                        # saturated-but-ALIVE workers (WORKER_BUSY, all
                        # execution slots occupied): requeue elsewhere
                        # WITHOUT eviction — backpressure is not death, and
                        # the target's slot wait paces the retry loop
                        live_now = self._live_addrs()
                        for i, (fid, addr) in enumerate(busy):
                            others = [a for a in live_now if a != addr]
                            if others:
                                frags[fid].worker = others[i % len(others)]
                            tracing.counter(
                                "coordinator.fragments_requeued_busy")
                            events.emit("fragment_requeue_busy",
                                        qid=qid, worker=addr, frag=fid)
                    for dep_id in lost_deps:
                        # the holder of this dep result is unreachable from a
                        # peer: treat it as dead and re-run the dep
                        dead.add(completed.get(dep_id, ""))
                    if dead:
                        recoveries += 1
                        metrics["recoveries"] = recoveries
                        if recoveries > self.max_recoveries:
                            raise IglooError(
                                "giving up after repeated worker failures")
                        # no budget left: report the deadline, don't burn the
                        # remaining workers on a recovery that cannot finish
                        self._check_query(token, deadline, metrics)
                        t_rec = time.perf_counter()
                        self._recover(dead, frags, completed, pending,
                                      deadline)
                        metrics["recover_s"] += time.perf_counter() - t_rec
                        if trace is not None:
                            trace.add_span(
                                "recover", tracing.epoch(t_rec), time.time(),
                                parent_id=metrics["_trace_root"],
                                proc="coordinator",
                                dead=sorted(dead), recovery=recoveries)
            # open the root stream eagerly: the schema the worker reports is
            # authoritative, and a root holder lost between the last wave and
            # here surfaces now, while the caller can still see the error
            t_fetch = time.perf_counter()
            schema, batch_iter = rpc.flight_stream_batches(
                completed[root_id], root_id, policy=self._policy(),
                deadline=deadline)
        except BaseException as ex:
            self._release(frags, completed, list(frags),
                          metrics["_addrs"])
            self._finalize(qid, metrics, t_start, sql, error=ex, token=token)
            raise

        done = [False]

        def cleanup():
            # idempotent: runs from the generator's finally on the normal
            # path, or from the weakref finalizer when a client abandons the
            # stream before pulling the first batch (a never-started
            # generator's close() does not enter its try/finally)
            if done[0]:
                return
            done[0] = True
            close = getattr(batch_iter, "close", None)
            if close is not None:
                try:
                    close()  # drop the root worker's Flight connection
                except Exception:
                    pass
            self._release(frags, completed, list(frags),
                          metrics["_addrs"])
            # a stream abandoned before its first batch reaches this ONLY
            # through the weakref finalizer — gen()'s except/finally never
            # ran, so finalize here (release-only path: unregisters and
            # publishes the partial trace; the _finalized guard makes this
            # a no-op after any earlier finalize)
            self._finalize(qid, metrics, t_start, sql, token=token)

        def gen():
            total_rows = 0
            try:
                for batch in batch_iter:
                    # over-deadline / cancelled mid-relay: stop streaming,
                    # release worker results (cleanup in finally)
                    self._check_query(token, deadline, metrics)
                    total_rows += batch.num_rows
                    yield batch
                metrics["fetch_s"] = round(time.perf_counter() - t_fetch, 6)
                metrics["total_rows"] = total_rows
                metrics["recoveries"] = recoveries
                if trace is not None:
                    # the root-result relay: open + batch-wise stream from
                    # the root holder (recorded here, where it ends — the
                    # relay spans threads, so a thread-local span cannot).
                    # Top-level, not a child of the "query" root: the relay
                    # OUTLIVES the do_get handler whose scope that root
                    # times, and nesting is containment
                    trace.add_span("fetch", tracing.epoch(t_fetch),
                                   time.time(), proc="coordinator",
                                   rows=total_rows)
                self._finalize(qid, metrics, t_start, sql, completed=True,
                               token=token)
            except BaseException as ex:
                if isinstance(ex, GeneratorExit):
                    # consumer closed the stream early: released, not logged
                    self._finalize(qid, metrics, t_start, sql, token=token)
                else:
                    self._finalize(qid, metrics, t_start, sql, error=ex,
                                   token=token)
                raise
            finally:
                cleanup()
        g = gen()
        weakref.finalize(g, cleanup)
        return schema, g

    # --- internals ---

    def _check_query(self, token: CancelToken, deadline: Optional[float],
                     metrics: dict) -> None:
        """Raise if the query was cancelled or its deadline passed (flags
        recorded in the per-query metrics; counters bump once, at finalize)."""
        if token.cancelled:
            metrics["cancelled"] = True
            raise QueryCancelledError(f"query {metrics['qid']} cancelled")
        if deadline is not None and time.time() >= deadline:
            metrics["deadline_exceeded"] = True
            raise DeadlineExceededError(
                f"query {metrics['qid']} exceeded its "
                f"{metrics['deadline_s']}s deadline")

    def _unregister(self, qid: str, token: CancelToken) -> None:
        """Drop the qid -> CancelToken registration ONLY if it is still this
        query's token: a client that reuses a qid overwrites the slot with
        the NEWER query's token, and the older query's late finalize/cleanup
        must not evict it — that would leave the live query uncancellable
        and invisible to active_queries()."""
        with self._queries_lock:
            if self._queries.get(qid) is token:
                del self._queries[qid]

    def _finalize(self, qid: str, metrics: dict, t_start: float, sql: str,
                  error: Optional[BaseException] = None,
                  completed: bool = False,
                  token: Optional[CancelToken] = None) -> None:
        """Publish a finished query exactly once: last_metrics + cumulative
        worker totals + a system.query_log row (status ok / cancelled /
        deadline_exceeded / error). Called with neither `completed` nor
        `error` (an abandoned stream) it only unregisters the qid — the
        results were released, but nothing finished to report."""
        if token is not None:
            self._unregister(qid, token)
        with self._queries_lock:
            if metrics.get("_finalized"):
                return
            metrics["_finalized"] = True
        # retire the stitched trace exactly once (the _finalized guard),
        # whatever the outcome — a partial trace of a failed or abandoned
        # query is exactly what the timeline is FOR
        flight_recorder.publish(metrics.get("_trace"))
        if error is None and not completed:
            return
        status = "ok"
        if isinstance(error, QueryCancelledError) or metrics["cancelled"]:
            status = "cancelled"
            tracing.counter("query.cancelled")
            events.emit("query_cancelled", severity="warn", qid=qid,
                        trace_id=metrics.get("trace_id", ""))
        elif isinstance(error, DeadlineExceededError) or \
                metrics["deadline_exceeded"]:
            # covers both the wave/relay checks and an rpc-layer
            # DeadlineExceededError raised mid-call
            status = "deadline_exceeded"
            metrics["deadline_exceeded"] = True
            tracing.counter("query.deadline_exceeded")
            events.emit("query_deadline", severity="warn", qid=qid,
                        trace_id=metrics.get("trace_id", ""),
                        deadline_s=metrics.get("deadline_s"))
        elif error is not None:
            status = "error"
        metrics["status"] = status
        # dedupe by fragment id (a fragment re-run after a worker death
        # appends twice; last execution wins)
        by_id: dict = {}
        for info in metrics["fragments"]:
            by_id[info.get("id", len(by_id))] = info
        metrics["fragments"] = list(by_id.values())
        metrics.update(
            recover_s=round(metrics["recover_s"], 6),
            exchange_bytes=sum(i.get("exchange_bytes") or 0
                               for i in metrics["fragments"]),
            execution_time_s=round(time.time() - t_start, 6))
        if status == "ok" and completed:
            # feed the telemetry->planner loop: per-side observed rows /
            # result bytes / skew sketch, under the fingerprint digests the
            # planner tagged the fragments with (docs/adaptive.md)
            self._record_adaptive(metrics["fragments"])
        pub = {k: v for k, v in metrics.items() if not k.startswith("_")}
        # publish under the totals lock: the Flight `last_metrics` handler
        # and the demoted/cached publish paths race this slot otherwise
        with self._totals_lock:
            self.last_metrics = pub
        self._accumulate(pub)
        stats.log_query(sql, elapsed_s=pub["execution_time_s"],
                        tier="distributed", rows=pub.get("total_rows"),
                        status=status, started_at=t_start,
                        queue_wait_s=pub.get("queue_wait_s", 0.0),
                        priority=pub.get("priority", 1),
                        demoted=pub.get("demoted", 0),
                        trace_id=pub.get("trace_id", ""))
        if status == "ok" and completed:
            # watchtower baseline check: judged against this fingerprint's
            # OWN history, then folded in (docs/observability.md#watchtower).
            # After flight_recorder.publish above, so an escalation's pin()
            # finds the trace already ring-resident.
            watch.check_query(
                metrics.get("_plan_fp"), pub["execution_time_s"],
                exchange_bytes=float(pub.get("exchange_bytes") or 0),
                qid=qid, trace_id=pub.get("trace_id", ""), sql=sql,
                tier="distributed", phase=self._dominant_phase(pub))

    @staticmethod
    def _dominant_phase(pub: dict) -> str:
        """Attribute a distributed query's wall time to its widest phase
        (the slow-query record's `dominant_phase` column)."""
        frags = pub.get("fragments") or []
        buckets = {
            "execute": sum(i.get("elapsed_s") or 0.0 for i in frags),
            "dispatch": sum(i.get("dispatch_s") or 0.0 for i in frags),
            "dep_fetch": sum(i.get("dep_fetch_s") or 0.0 for i in frags),
            "fetch": pub.get("fetch_s") or 0.0,
            "recover": pub.get("recover_s") or 0.0,
        }
        name = max(buckets, key=buckets.get)
        return name if buckets[name] > 0 else ""

    def _record_adaptive(self, frag_infos: list) -> None:
        """Fold a finished query's per-fragment reports into the process-wide
        AdaptiveStats store, grouped by the planner's side digests: total
        rows and result bytes per join side, plus the skew sketch (max
        UNSALTED bucket share + hot bucket) from the exchange fragments'
        per-bucket row counts. Best-effort by the stats safety contract."""
        from igloo_tpu.exec import hints
        if not hints.adaptive_enabled():
            return
        try:
            by_key: dict = {}
            for info in frag_infos:
                sk = info.get("stats_key")
                if not sk:
                    continue
                g = by_key.setdefault(sk, {"rows": 0, "bytes": 0,
                                           "bucket_rows": None,
                                           "buckets": None})
                g["rows"] += int(info.get("rows") or 0)
                g["bytes"] += int(info.get("result_bytes") or 0)
                br = info.get("bucket_rows")
                if br:
                    if g["bucket_rows"] is None:
                        g["bucket_rows"] = [0] * len(br)
                        g["buckets"] = info.get("buckets")
                    if len(br) == len(g["bucket_rows"]):
                        g["bucket_rows"] = [a + int(b) for a, b in
                                            zip(g["bucket_rows"], br)]
            if not by_key:
                return
            store = hints.adaptive_store()
            for sk, g in by_key.items():
                fields = {"rows": g["rows"], "bytes": g["bytes"] or None}
                br = g["bucket_rows"]
                if br and sum(br) > 0 and g["buckets"]:
                    hot = max(range(len(br)), key=lambda i: br[i])
                    fields.update(max_share=round(br[hot] / sum(br), 4),
                                  hot_bucket=hot,
                                  nbuckets=int(g["buckets"]))
                store.observe_by_digest(sk, **fields)
            store.flush()
            tracing.counter("adaptive.observed", len(by_key))
        except Exception:
            tracing.counter("adaptive.record_failed")

    def _live_addrs(self) -> list[str]:
        return [w.addr for w in self.membership.live()]

    def _dispatch(self, f: QueryFragment, completed: dict[str, str],
                  metrics: dict, deadline: Optional[float] = None,
                  token: Optional[CancelToken] = None) -> None:
        if token is not None and token.cancelled:
            raise QueryCancelledError("query cancelled")
        # remember the target BEFORE the call: a timed-out dispatch keeps
        # running server-side, and end-of-query release must reach this addr
        # even after _recover reassigns the fragment elsewhere
        metrics["_addrs"].add(f.worker)
        deps = [protocol.DISPATCH_DEP.build(id=d, addr=completed[d])
                for d in f.deps]
        rem = rpc.remaining_s(deadline)
        # ship the remaining budget as a RELATIVE bound (clocks differ
        # across machines): the worker uses it to deadline its own peer
        # dep-fetches so a hung peer can't wedge the fragment either
        timeout_s = round(max(rem, 0.001), 3) if rem is not None else None
        pol = self._policy()
        # flight-recorder: the dispatch span's id ships INSIDE the request
        # as the worker-side parent, so the worker's span tree re-parents
        # under this exact RPC on the stitched timeline
        tr = metrics.get("_trace")
        span_cm = tr.span("dispatch", parent_id=metrics.get("_trace_root"),
                          proc="coordinator", frag=f.id, addr=f.worker) \
            if tr is not None else contextlib.nullcontext()
        try:
            t0 = time.perf_counter()
            with span_cm as span_id:
                # the dispatch payload, typed through the registry; the
                # trace block ships the dispatch span's id as the worker-
                # side parent so the worker's tree stitches under this RPC
                ctx = protocol.TRACE_CTX.build(
                    trace_id=tr.trace_id, parent_id=span_id) \
                    if span_id is not None else None
                req = protocol.DISPATCH.build(id=f.id, plan=f.plan,
                                              deps=deps,
                                              timeout_s=timeout_s,
                                              trace=ctx,
                                              budget=metrics.get("_budget"))
                # retries=0: re-dispatch is the RECOVERY layer's job — an
                # RPC-level retry against the same hung worker would just
                # double the time a dead worker stalls the wave. The
                # per-dispatch bound is the HANG DETECTOR: under a query
                # deadline it is call_timeout_s (clamped to the remaining
                # budget) so rescue fits inside the deadline; without one, a
                # dispatch runs QUERY work and gets the stream budget
                # instead — a slow-but-legitimate fragment must not be
                # misread as a hung worker at the control-action timeout
                info = flight_action(f.worker, "execute_fragment", req,
                                     policy=pol.with_(retries=0),
                                     deadline=deadline,
                                     timeout_s=(pol.call_timeout_s
                                                if deadline is not None
                                                else pol.stream_timeout_s))
            wall = time.perf_counter() - t0
            # typed through the registry: a worker answering with a
            # malformed stats report fails loudly here, naming the field
            info = protocol.FRAGMENT_STATS.parse(info)
            if tr is not None:
                # stitch the worker's span tree into the query trace (and
                # keep the metrics fragments lean — spans are trace data)
                tr.extend(info.pop("spans", None))
            else:
                info.pop("spans", None)
            info["addr"] = f.worker
            if f.kind:
                info["kind"] = f.kind
            if f.bucket is not None:
                info["bucket"] = f.bucket
            if f.stats_key is not None:
                info["stats_key"] = f.stats_key
            # dispatch = RPC wall minus what the worker accounted for
            # (execution + dependency fetches): serialization + network +
            # the worker's action-handler queue
            info["dispatch_s"] = round(max(
                wall - info.get("elapsed_s", 0.0)
                - info.get("dep_fetch_s", 0.0), 0.0), 6)
            metrics["fragments"].append(info)
        except flight.FlightUnauthenticatedError:
            raise  # fatal by classification: never a dead-worker signal
        except DeadlineExceededError:
            raise  # query budget spent before the call could start
        except flight.FlightServerError as ex:
            marker = "DEP_UNAVAILABLE:"
            msg = str(ex)
            if marker in msg:
                dep_id = msg.split(marker, 1)[1].split()[0]
                raise _DepLost(dep_id)
            raise  # execution error on a live worker: surface it
        except Exception as ex:
            if "WORKER_BUSY" in str(ex):
                # all execution slots occupied on a HEALTHY worker: requeue
                # the fragment elsewhere, never evict (docs/serving.md)
                raise _WorkerBusy(f.worker)
            # only RETRYABLE failures are a dead-worker signal:
            # FlightTimedOutError (the hung worker — accepted TCP, never
            # answered), FlightUnavailableError, connection errors. Anything
            # rpc.retryable() calls fatal (internal/cancelled/unknown Flight
            # errors) is a real failure a HEALTHY worker reported —
            # re-dispatching it would evict worker after worker and bury the
            # actual error under "repeated worker failures"
            if rpc.retryable(ex):
                raise _WorkerDied(f.worker)
            raise
        tracing.counter("coordinator.fragments_dispatched")

    def _recover(self, dead_addrs: set[str], frags: dict[str, QueryFragment],
                 completed: dict[str, str], pending: set,
                 deadline: Optional[float] = None) -> None:
        """Evict dead workers, requeue results they held, move their work."""
        import itertools
        for addr in dead_addrs:
            w = self.membership.by_addr(addr)
            if w is not None:
                self.membership.evict(w.worker_id)
        live = self._live_addrs()
        if not live:
            # the whole fleet is momentarily unreachable (rolling restart, a
            # blip that tripped every dispatch at once): evicted-but-alive
            # workers re-register on their next heartbeat — wait for one
            # instead of failing the query instantly
            wait = RECOVER_WAIT_S
            rem = rpc.remaining_s(deadline)
            if rem is not None:
                wait = min(wait, max(rem, 0.0))
            t_end = time.time() + wait
            while not live and time.time() < t_end:
                time.sleep(0.05)
                live = self._live_addrs()
        if not live:
            raise IglooError(
                f"no live workers left (failed: {sorted(dead_addrs)})")
        for fid, holder in list(completed.items()):
            if holder in dead_addrs:
                del completed[fid]
                pending.add(fid)  # pure fragment: safe to re-run
        rr = itertools.cycle(live)
        moved = 0
        for fid in pending:
            if frags[fid].worker not in live:
                frags[fid].worker = next(rr)
                tracing.counter("coordinator.fragments_redispatched")
                moved += 1
        if moved:
            # one journal event per recovery round, not per fragment
            events.emit("fragment_redispatch", severity="warn",
                        fragments=moved, dead=sorted(dead_addrs))

    def _accumulate(self, metrics: dict) -> None:
        """Fold one query's per-fragment stats into the cumulative per-worker
        totals served by the coordinator `metrics` action."""
        with self._totals_lock:
            for info in metrics["fragments"]:
                t = self.worker_totals.setdefault(
                    info.get("worker", info.get("addr", "?")),
                    {"fragments": 0, "rows": 0, "execute_s": 0.0,
                     "dispatch_s": 0.0, "dep_fetch_s": 0.0,
                     "h2d_bytes": 0, "d2h_bytes": 0, "jit_misses": 0,
                     "exchange_bytes": 0})
                t["fragments"] += 1
                t["rows"] += info.get("rows", 0)
                t["execute_s"] += info.get("elapsed_s", 0.0)
                t["dispatch_s"] += info.get("dispatch_s", 0.0)
                t["dep_fetch_s"] += info.get("dep_fetch_s", 0.0)
                t["h2d_bytes"] += info.get("h2d_bytes", 0) or 0
                t["d2h_bytes"] += info.get("d2h_bytes", 0) or 0
                t["jit_misses"] += info.get("jit_misses", 0) or 0
                t["exchange_bytes"] += info.get("exchange_bytes", 0) or 0

    def prometheus_lines(self) -> list:
        """Worker-aggregated fragment stats as labeled Prometheus lines."""
        lines = []
        with self._totals_lock:
            totals = {w: dict(t) for w, t in self.worker_totals.items()}
        for name, key, kind in (
                ("igloo_coordinator_worker_fragments_total", "fragments",
                 "counter"),
                ("igloo_coordinator_worker_fragment_rows_total", "rows", "counter"),
                ("igloo_coordinator_worker_fragment_execute_seconds_total", "execute_s",
                 "counter"),
                ("igloo_coordinator_worker_fragment_dispatch_seconds_total", "dispatch_s",
                 "counter"),
                ("igloo_coordinator_worker_fragment_dep_fetch_seconds_total",
                 "dep_fetch_s", "counter"),
                ("igloo_coordinator_worker_fragment_h2d_bytes_total", "h2d_bytes",
                 "counter"),
                ("igloo_coordinator_worker_fragment_d2h_bytes_total", "d2h_bytes",
                 "counter"),
                ("igloo_coordinator_worker_fragment_jit_misses_total", "jit_misses",
                 "counter"),
                ("igloo_coordinator_worker_exchange_bytes_total",
                 "exchange_bytes", "counter")):
            if totals:
                lines.append(f"# TYPE {name} {kind}")
            for w, t in sorted(totals.items()):
                lines.append(f'{name}{{worker="{w}"}} {t.get(key, 0)}')
        return lines

    def _release(self, frags: dict[str, QueryFragment],
                 completed: dict[str, str], ids: list[str],
                 dispatched=()) -> None:
        # every worker a fragment was ASSIGNED to or EVER dispatched to, not
        # just recorded holders: a wave that errored out mid-collection
        # leaves results on workers whose completions were never processed,
        # and an EVICTED worker (its fragment reassigned by _recover) may
        # still be running the timed-out handler — it needs the release so
        # its store grows a tombstone for the late put
        addrs = set(completed.values()) | \
            {f.worker for f in frags.values()} | set(dispatched)
        for addr in addrs:
            try:
                # short bound, no retries: release is best-effort cleanup and
                # often targets the very worker that just died
                flight_action(addr, "release",
                              protocol.RELEASE.build(ids=ids),
                              policy=self._policy().with_(retries=0),
                              timeout_s=10.0)
            except Exception:
                pass  # worker gone; nothing to release


class _WorkerDied(Exception):
    def __init__(self, addr: str):
        self.addr = addr


class _WorkerBusy(Exception):
    """Dispatch refused with the WORKER_BUSY marker: every execution slot
    on a live worker is occupied. Requeue the fragment, never evict."""

    def __init__(self, addr: str):
        self.addr = addr


class _DepLost(Exception):
    def __init__(self, frag_id: str):
        self.frag_id = frag_id


class CoordinatorServer(flight.FlightServerBase):
    """The cluster's front door + control plane on ONE Flight endpoint."""

    def __init__(self, location: str, worker_timeout_s: float = 15.0,
                 use_jit: bool = True, advertise_host: Optional[str] = None,
                 **kw):
        # trusted-network default; IGLOO_TPU_AUTH_TOKEN installs a shared-
        # token check on every Flight call (see cluster/rpc.py security model)
        mw = rpc.server_middleware()
        if mw is not None:
            kw.setdefault("middleware", mw)
        ah = rpc.server_auth_handler()
        if ah is not None:
            kw.setdefault("auth_handler", ah)
        rpc.warn_if_open_bind(location.split("://")[-1].rsplit(":", 1)[0],
                              "coordinator")
        # pick up IGLOO_FAULTS set after import (in-process test clusters)
        faults.refresh()
        super().__init__(location, **kw)
        if advertise_host is None:
            # endpoint host clients are told to come back to: the bound host
            # (unless wildcard-bound, where loopback is the only safe default)
            host = location.split("://")[-1].rsplit(":", 1)[0]
            advertise_host = host if host and host != "0.0.0.0" else "127.0.0.1"
        self.advertise_host = advertise_host
        self.engine = QueryEngine(use_jit=use_jit)
        self.membership = Membership(worker_timeout_s)
        self.executor = DistributedExecutor(self.membership)
        # multi-tenant front door (docs/serving.md): bounded per-priority
        # admission, weighted fair dequeue, per-session caps, HBM-gated
        # concurrency; IGLOO_SERVING_QUEUE=0 serializes one query at a time
        self.admission = serving.AdmissionController()
        self._table_specs: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sweeper = threading.Thread(target=self._sweep_loop, daemon=True)
        self._sweeper.start()
        # watchtower sampler (utils/timeseries.py): no-op under IGLOO_WATCH=0
        timeseries.start("coordinator")

    # --- table management ---

    def register_table(self, name: str, provider) -> None:
        """Register on the coordinator AND push to every live worker."""
        import pyarrow as _pa
        from igloo_tpu.catalog import MemTable
        if isinstance(provider, _pa.Table):
            provider = MemTable(provider)
        self.engine.register_table(name, provider)
        spec = serde.provider_to_spec(provider)
        if spec is not None:
            with self._lock:
                self._table_specs[name.lower()] = spec
            for w in self.membership.live():
                try:
                    self._push_table(w, name, spec)
                except Exception:
                    # forget any OLDER version this worker holds, so the next
                    # _sync_worker_tables re-pushes instead of serving stale
                    # rows next to fresh ones on other workers
                    w.tables_pushed.discard(name.lower())

    def _push_table(self, w: WorkerState, name: str, spec: dict) -> None:
        flight_action(w.addr, "register_table",
                      protocol.REGISTER_TABLE.build(name=name, spec=spec))
        w.tables_pushed.add(name.lower())

    def _sync_worker_tables(self, w: WorkerState) -> None:
        with self._lock:
            specs = dict(self._table_specs)
        for name, spec in specs.items():
            if name not in w.tables_pushed:
                self._push_table(w, name, spec)

    # --- query execution ---

    def execute_sql(self, sql: str, stream: bool = False,
                    deadline_s: Optional[float] = None,
                    qid: Optional[str] = None, priority: int = 1,
                    session: str = "",
                    trace: Optional[flight_recorder.Trace] = None):
        """-> pa.Table, or — for `stream=True` on the distributed path —
        (pa.Schema, record-batch generator) so do_get can relay the root
        worker's stream batch-wise instead of materializing it here.
        `deadline_s`/`qid` bound + name the DISTRIBUTED execution (deadline,
        cancel_query); the local fallback paths honor the deadline at their
        checkpoints (before planning, between plan and execute) but are not
        cancellable mid-flight. `priority`/`session` feed the admission
        controller (docs/serving.md): past the queue bound or the session's
        in-flight cap the query is SHED with a retryable serving.ServerBusy
        instead of executing."""
        t_start = time.time()
        deadline = t_start + deadline_s if deadline_s is not None else None
        self._check_local_deadline(deadline, sql, t_start, priority,
                                   planned=False)
        try:
            plan = self.engine.plan(sql)
        except IglooError:
            # non-SELECT statements (SHOW/DESCRIBE/CTAS/...) run locally,
            # un-admitted: metadata ops must work even under full overload
            return self.engine.execute(sql)
        # plan+snapshot-keyed result cache: a repeated dashboard-shaped
        # query short-circuits admission (and all execution) entirely
        rkey = self._result_cache_key(plan)
        if rkey is not None:
            hit = self.engine.result_cache.get(rkey)
            if hit is not None:
                return self._serve_cached(hit, sql, stream, t_start,
                                          priority, qid, trace=trace)
        try:
            permit = self.admission.submit(
                priority=priority, session=session,
                predicted_hbm_bytes=serving.predict_hbm_bytes(plan),
                deadline=deadline)
        except serving.ServerBusy:
            stats.log_query(sql, elapsed_s=time.time() - t_start,
                            tier="serving", status="shed",
                            started_at=t_start, priority=priority)
            events.emit("admission_shed", severity="warn", qid=qid or "",
                        priority=priority)
            raise
        try:
            out = self._execute_admitted(plan, sql, stream, deadline,
                                         deadline_s, qid, permit, rkey,
                                         t_start, trace=trace)
        except BaseException:
            permit.release()
            raise
        if stream and isinstance(out, tuple):
            # the permit rides the stream: concurrency and the HBM
            # reservation are held until the relay finishes (worker-held
            # results live exactly that long)
            schema, gen = out
            return schema, _released_stream(gen, permit)
        permit.release()
        return out

    def _execute_admitted(self, plan, sql: str, stream: bool,
                          deadline: Optional[float],
                          deadline_s: Optional[float], qid: Optional[str],
                          permit: "serving.Permit", rkey, t_start: float,
                          trace: Optional[flight_recorder.Trace] = None):
        """The admitted execution body: distributed when possible, local
        fallback otherwise, with the degradation ladder absorbing OOM."""
        if permit.demote:
            # predicted past the WHOLE HBM budget: first try to spread the
            # over-budget join across the fleet (GRACE partitions become
            # exchange buckets, each worker spills and streams its share —
            # docs/out_of_core.md); when the fleet or plan can't take it,
            # fall back to the exact single-node degradation ladder
            out = self._try_oversized_distributed(
                plan, sql, stream, deadline, deadline_s, qid, permit, rkey,
                trace=trace)
            if out is not None:
                return out
            return self._run_demoted(sql, stream, deadline, t_start, permit)
        live = self.membership.live()
        if not live:
            # a coordinator with no workers is still a working single-node
            # engine (the reference coordinator main is exactly that)
            return self._run_local(sql, stream, deadline, t_start, permit)
        synced = []
        for w in live:
            try:
                self._sync_worker_tables(w)
                synced.append(w)
            except Exception:
                # unreachable mid-sweep: evict now instead of failing every
                # query until the sweeper notices
                self.membership.evict(w.worker_id)
        live = synced
        if not live or not self._distributable(plan):
            # only distribute plans whose base tables every worker resolves
            return self._run_local(sql, stream, deadline, t_start, permit)
        # per-worker device counts ride into planning: bucket counts scale
        # with hosts, per-worker shard counts with chips, and heterogeneous
        # clusters get device-weighted bucket placement (two-level
        # parallelism, docs/distributed.md)
        topo = {w.addr: w.devices for w in live}
        planner = DistributedPlanner([w.addr for w in live], topology=topo)
        # watchtower baseline key, captured BEFORE fragmenting: the planner
        # rewrites the tree in place (partial-agg Union merge has no stable
        # key), and the baseline must describe the user's logical plan — the
        # same key the local tier would observe under
        from igloo_tpu.exec import hints
        plan_key = hints.plan_fp(plan)
        frags = planner.plan(plan)
        tracing.counter("coordinator.distributed_queries")
        # reorder decisions from engine.plan's optimize() above ride beside
        # the fragment-tier broadcast/salt records (docs/adaptive.md)
        from igloo_tpu.plan.optimizer import last_adaptive_decisions
        adaptive_info = last_adaptive_decisions() + planner.adaptive_info
        extra = {"queue_wait_s": round(permit.wait_s, 6),
                 "priority": permit.priority, "demoted": 0,
                 # "_"-prefixed: never published; _finalize judges the
                 # finished query under it
                 "_plan_fp": plan_key,
                 # the topology this query was planned against, published in
                 # last_metrics beside the per-fragment mesh_devices reports
                 "topology": {"workers": len(live),
                              "devices": topo,
                              "total_shards": sum(topo.values())}}
        try:
            if stream:
                schema, gen = self.executor.execute_stream(
                    frags, deadline_s=deadline_s, qid=qid, sql=sql,
                    adaptive_info=adaptive_info, extra_metrics=extra,
                    trace=trace)
                return schema, self._caching_stream(schema, gen, rkey)
            table = self.executor.execute(frags, deadline_s=deadline_s,
                                          qid=qid, sql=sql,
                                          adaptive_info=adaptive_info,
                                          extra_metrics=extra, trace=trace)
        except Exception as ex:
            if not _is_oom(ex):
                raise
            # a worker (or the relay) ran out of device memory: demote the
            # query down the local ladder instead of failing it
            return self._run_demoted(sql, stream, deadline, t_start, permit)
        self._result_cache_put(rkey, table)
        return table

    # --- serving helpers (docs/serving.md) ---

    def _check_local_deadline(self, deadline: Optional[float], sql: str,
                              t_start: float, priority: int,
                              planned: bool = True) -> None:
        """`deadline_s` honored on the LOCAL fallback paths too (the
        distributed executor has its own checks): before planning and
        between plan and execute, surfacing `query.deadline_exceeded` and a
        query-log row exactly like the distributed accounting."""
        if deadline is None or time.time() < deadline:
            return
        tracing.counter("query.deadline_exceeded")
        stats.log_query(sql, elapsed_s=time.time() - t_start, tier="local",
                        status="deadline_exceeded", started_at=t_start,
                        priority=priority)
        where = "execution" if planned else "planning"
        raise DeadlineExceededError(
            f"query exceeded its deadline before local {where}")

    def _run_local(self, sql: str, stream: bool, deadline: Optional[float],
                   t_start: float, permit: "serving.Permit"):
        """Local fallback execution under the serving context, with the
        OOM->demote ladder."""
        self._check_local_deadline(deadline, sql, t_start, permit.priority)
        with stats.serving_context(queue_wait_s=permit.wait_s,
                                   priority=permit.priority):
            try:
                out = self.engine.execute(sql)
            except Exception as ex:
                if not _is_oom(ex):
                    raise
                out = self._demote_ladder(sql, deadline, t_start,
                                          permit.priority, level=1)
        return (out.schema, iter(out.to_batches())) if stream else out

    def _try_oversized_distributed(self, plan, sql: str, stream: bool,
                                   deadline: Optional[float],
                                   deadline_s: Optional[float],
                                   qid: Optional[str],
                                   permit: "serving.Permit", rkey,
                                   trace: Optional[
                                       flight_recorder.Trace] = None):
        """Distributed out-of-core attempt for an oversized query: plan the
        over-budget join as per-bucket fragments whose buckets ARE its GRACE
        partitions (cluster/fragment.py `_try_grace_distributed`), spread
        across the live workers, each dispatch carrying the per-worker
        budget so Exchange fragments stream-spill under it. Returns None
        whenever the fleet or the plan can't take it — fewer than two
        synced workers, a non-distributable plan, the planner declining
        (`grace_info` unset), the `IGLOO_GRACE_DISTRIBUTED=0` kill switch,
        or an execution failure — and the caller falls back to the exact
        single-node ladder, byte-identical to the pre-distributed behavior."""
        live = self.membership.live()
        if len(live) < 2:
            return None
        synced = []
        for w in live:
            try:
                self._sync_worker_tables(w)
                synced.append(w)
            except Exception:
                self.membership.evict(w.worker_id)
        live = synced
        if len(live) < 2 or not self._distributable(plan):
            return None
        budget = self._demote_budget()
        topo = {w.addr: w.devices for w in live}
        planner = DistributedPlanner([w.addr for w in live], topology=topo,
                                     budget_bytes=budget)
        # captured before planner.plan rewrites the tree (see
        # _run_distributed): the baseline keys the user's logical plan
        from igloo_tpu.exec import hints
        plan_key = hints.plan_fp(plan)
        try:
            frags = planner.plan(plan)
        except Exception:
            tracing.counter("grace.distributed_planfail")
            return None
        if planner.grace_info is None:
            return None
        tracing.counter("coordinator.distributed_queries")
        from igloo_tpu.plan.optimizer import last_adaptive_decisions
        adaptive_info = last_adaptive_decisions() + planner.adaptive_info
        extra = {"queue_wait_s": round(permit.wait_s, 6),
                 "priority": permit.priority, "demoted": 0,
                 "_plan_fp": plan_key,
                 # per-query out-of-core attribution, published in
                 # last_metrics and the sweep JSON `oversized` block
                 "oversized": dict(planner.grace_info),
                 "topology": {"workers": len(live),
                              "devices": topo,
                              "total_shards": sum(topo.values())}}
        try:
            # materialized (not relay-streamed) even for stream callers:
            # the caller must still be able to fall back to the exact
            # ladder if a worker dies or OOMs mid-query, which is
            # impossible once a stream has been handed out. Oversized
            # results are post-aggregate and small; the BUCKETS never
            # gather here.
            table = self.executor.execute(frags, deadline_s=deadline_s,
                                          qid=qid, sql=sql,
                                          adaptive_info=adaptive_info,
                                          extra_metrics=extra, trace=trace,
                                          budget=budget)
        except (QueryCancelledError, DeadlineExceededError, serving.ServerBusy):
            raise
        except Exception:
            tracing.counter("grace.distributed_fallback")
            return None
        self._result_cache_put(rkey, table)
        return (table.schema, iter(table.to_batches())) if stream else table

    def _run_demoted(self, sql: str, stream: bool,
                     deadline: Optional[float], t_start: float,
                     permit: "serving.Permit"):
        """Entry for queries pre-flagged by the HBM gate: straight onto the
        ladder's first rung."""
        with stats.serving_context(queue_wait_s=permit.wait_s,
                                   priority=permit.priority):
            out = self._demote_ladder(sql, deadline, t_start,
                                      permit.priority, level=1)
        # publish: a demoted query must overwrite last_metrics (clients —
        # and the kill-switch A/B — would otherwise read the PREVIOUS
        # query's oversized/fragment attribution as this one's)
        with self.executor._totals_lock:
            self.executor.last_metrics = {
                "qid": "", "status": "ok", "rows": out.num_rows,
                "fragments": [], "recoveries": 0, "demoted": 1,
                "execution_time_s": round(time.time() - t_start, 6)}
        return (out.schema, iter(out.to_batches())) if stream else out

    def _demote_ladder(self, sql: str, deadline: Optional[float],
                       t_start: float, priority: int, level: int):
        """The graceful-degradation ladder: rung 1 re-runs locally with a
        chunk budget constrained to the serving HBM budget (forcing the
        chunked/GRACE out-of-core tiers); rung 2 forces the numpy host
        tier. Each rung bumps `serving.demoted` + the query-log `demoted`
        column; an OOM on the last rung surfaces."""
        self._check_local_deadline(deadline, sql, t_start, priority)
        tracing.counter("serving.demoted")
        events.emit("query_demoted", severity="warn", rung=level)
        stats.mark_demoted()
        budget = self._demote_budget()
        if level <= 1:
            try:
                with self.engine.demoted(budget_bytes=budget):
                    return self.engine.execute(sql)
            except Exception as ex:
                if not _is_oom(ex):
                    raise
                return self._demote_ladder(sql, deadline, t_start, priority,
                                           level=2)
        with self.engine.demoted(budget_bytes=budget, force_host=True):
            return self.engine.execute(sql)

    def _demote_budget(self) -> int:
        """Chunk budget for demoted execution: the serving HBM budget when
        one is configured (that IS the memory the query must fit), else a
        quarter of the engine's normal budget; floored so partition counts
        stay sane."""
        b = self.admission.hbm_budget_bytes or \
            self.engine.chunk_budget_bytes // 4
        return max(int(b), 1 << 20)

    def _result_cache_key(self, plan):
        if os.environ.get(RESULT_CACHE_ENV, "1") == "0":
            return None
        from igloo_tpu.exec.result_cache import plan_cache_key
        return plan_cache_key(plan)

    def _result_cache_put(self, rkey, table: pa.Table) -> None:
        if rkey is not None and table.nbytes <= RESULT_CACHE_MAX_BYTES:
            self.engine.result_cache.put(rkey, table)

    def _serve_cached(self, hit: pa.Table, sql: str, stream: bool,
                      t_start: float, priority: int, qid: Optional[str],
                      trace: Optional[flight_recorder.Trace] = None):
        """A front-door result-cache hit: no admission, no execution —
        publish attributable metrics (`result_cache_hit` in last_metrics,
        a tier=result_cache query-log row) and serve the cached table."""
        elapsed = time.time() - t_start
        tid = trace.trace_id if trace is not None else ""
        with self.executor._totals_lock:
            self.executor.last_metrics = {
                "qid": qid or "", "result_cache_hit": True, "status": "ok",
                "rows": hit.num_rows, "fragments": [], "recoveries": 0,
                "execution_time_s": round(elapsed, 6), "trace_id": tid}
        stats.log_query(sql, elapsed_s=elapsed, tier="result_cache",
                        rows=hit.num_rows, started_at=t_start,
                        priority=priority, trace_id=tid)
        if stream:
            return hit.schema, iter(hit.to_batches())
        return hit

    def _caching_stream(self, schema: pa.Schema, gen, rkey):
        """Relay a distributed result stream while teeing batches into the
        result cache — giving up silently once the result outgrows the
        cacheable bound (materializing huge results here would defeat the
        streaming design)."""
        if rkey is None:
            return gen

        def teed():
            kept: list = []
            nbytes = 0
            for batch in gen:
                if kept is not None:
                    nbytes += batch.nbytes
                    if nbytes > RESULT_CACHE_MAX_BYTES:
                        kept = None
                    else:
                        kept.append(batch)
                yield batch
            if kept is not None:
                self._result_cache_put(
                    rkey, pa.Table.from_batches(kept, schema=schema))
        return teed()

    def _distributable(self, plan) -> bool:
        from igloo_tpu.plan.logical import Scan, walk_plan
        with self._lock:
            known = set(self._table_specs)
        return all(n.table.lower() in known for n in walk_plan(plan)
                   if isinstance(n, Scan))

    # --- liveness sweep ---

    def _sweep_loop(self) -> None:
        while not self._stop.wait(self.membership.timeout_s / 3):
            self.membership.sweep()

    def shutdown(self):  # pragma: no cover - exercised via tests' finally
        self._stop.set()
        super().shutdown()

    # --- Flight methods (full surface; reference implements 2 of 9) ---

    def do_action(self, context, action):
        faults.inject(f"coordinator.do_action.{action.type}")
        body = action.body.to_pybytes() if action.body is not None else b""
        req = json.loads(body) if body else {}
        if action.type == "cancel_query":
            ok = self.executor.cancel(protocol.CANCEL_QUERY.parse(req)["qid"])
            return [json.dumps({"cancelled": ok}).encode()]
        if action.type == "active_queries":
            return [json.dumps(
                {"queries": self.executor.active_queries()}).encode()]
        if action.type == "register_worker":
            info = serde.worker_info_from_json(req)
            self.membership.register(info["id"], info["addr"],
                                     devices=info["devices"],
                                     slots=info["slots"])
            w = self.membership.by_addr(info["addr"])
            if w is not None:
                try:
                    self._sync_worker_tables(w)
                except Exception:
                    pass
            # propagate the persistent compile-cache setting + entry listing:
            # the worker adopts the setting when it has none of its own and
            # pre-warms by pulling entries it is missing (compile_cache_get),
            # so a fresh worker starts with every program the cluster has
            # ever compiled (docs/compile_cache.md)
            import os
            from igloo_tpu import compile_cache
            return [json.dumps({"compile_cache": {
                "setting": os.environ.get("IGLOO_TPU_COMPILE_CACHE", "1"),
                "entries": compile_cache.entry_names(
                    min_age_s=compile_cache.TRANSFER_MIN_AGE_S),
            }}).encode()]
        if action.type == "compile_cache_get":
            # raw entry bytes by XLA cache filename (NOT JSON — workers use
            # rpc.flight_action_raw); empty body = no such entry
            from igloo_tpu import compile_cache
            data = compile_cache.read_entry(
                protocol.COMPILE_CACHE_GET.parse(req)["name"])
            return [data if data is not None else b""]
        if action.type == "compile_cache_put":
            # worker pushing a freshly compiled entry back to the cluster
            from igloo_tpu import compile_cache
            from igloo_tpu.exec import autotune  # noqa: F401 -- the import
            # registers the tuning-table merge hook, so a pushed
            # autotune.json merges instead of first-writer-wins
            put = protocol.COMPILE_CACHE_PUT.parse(req)
            stored = compile_cache.write_entry(
                put["name"], compile_cache.decode_entry(put["data"]))
            return [json.dumps({"stored": stored}).encode()]
        if action.type == "heartbeat":
            info = serde.worker_info_from_json(req)
            # a legacy payload WITHOUT the topology fields must not reset
            # the recorded devices to the codec's default of 1
            ok = self.membership.heartbeat(
                info["id"], info["addr"],
                devices=info["devices"] if "devices" in req else None,
                slots=info["slots"])
            # journal events riding the beat (cluster/events.py; dedup by
            # eid keeps in-process fleets and heartbeat retries honest)
            events.ingest(info["events"], worker=info["id"])
            return [json.dumps({"ok": ok}).encode()]
        if action.type == "register_table":
            rt = protocol.REGISTER_TABLE.parse(req)
            provider = serde.provider_from_spec(rt["spec"])
            self.register_table(rt["name"], provider)
            return [b"{}"]
        if action.type == "cluster_status":
            return [json.dumps({
                "workers": [{"id": w.worker_id, "addr": w.addr,
                             "last_seen": w.last_seen,
                             "devices": w.devices, "slots": w.slots}
                            for w in self.membership.live()],
                "tables": sorted(self.engine.catalog.names()),
            }).encode()]
        if action.type == "last_metrics":
            with self.executor._totals_lock:
                pub = self.executor.last_metrics
            return [json.dumps(pub).encode()]
        if action.type == "trace":
            # stitched query timeline by trace_id or qid (neither = most
            # recent); Chrome-trace/Perfetto JSON by default, the raw span
            # record with {"format": "raw"} (raw bytes — flight_action_raw)
            tq = protocol.TRACE_REQUEST.parse(req)
            rec = flight_recorder.get_record(tq["trace_id"], tq["qid"])
            if rec is None:
                raise flight.FlightServerError(
                    f"no such trace: {tq['trace_id'] or tq['qid'] or '<last>'}")
            if tq["format"] == "raw":
                return [json.dumps(rec).encode()]
            return [json.dumps(flight_recorder.to_chrome_trace(rec)).encode()]
        if action.type == "serving_status":
            # admission queue / slot / HBM-reservation snapshot
            return [json.dumps(self.admission.snapshot()).encode()]
        if action.type == "metrics":
            # coordinator process registry + worker-aggregated fragment
            # stats, Prometheus text (raw bytes — rpc.flight_action_raw)
            live_w = self.membership.live()
            extra = ["# TYPE igloo_workers_live gauge",
                     f"igloo_workers_live {len(live_w)}",
                     "# TYPE igloo_cluster_devices gauge",
                     f"igloo_cluster_devices {sum(w.devices for w in live_w)}"]
            extra.extend(self.executor.prometheus_lines())
            extra.extend(events.prometheus_lines())
            return [tracing.prometheus_text(extra_lines=extra).encode()]
        if action.type == "ping":
            return [json.dumps({"workers": len(self.membership.live())}).encode()]
        if action.type == "poll_flight_info":
            # body: JSON {"sql": "..."} (do_action parses all bodies as JSON)
            info = self.get_flight_info(
                context, flight.FlightDescriptor.for_command(
                    protocol.POLL_FLIGHT_INFO.parse(req)["sql"]))
            return [json.dumps({"progress": 1.0, "complete": True}).encode(),
                    info.serialize()]
        if action.type == "metrics_history":
            return [json.dumps(protocol.METRICS_HISTORY.build(
                samples=self._aggregate_metrics_history())).encode()]
        if action.type == "events":
            er = protocol.EVENTS_REQUEST.parse(req)
            evs = events.events(min_severity=er["min_severity"] or "info",
                                limit=er["limit"] if er["limit"] else None)
            return [json.dumps(
                protocol.EVENTS_REPLY.build(events=evs)).encode()]
        if action.type == "slow_queries":
            return [json.dumps(protocol.SLOW_QUERIES_REPLY.build(
                slow_queries=watch.slow_queries())).encode()]
        if action.type == "watch_status":
            return [json.dumps(self._watch_status()).encode()]
        raise flight.FlightServerError(f"unknown action {action.type}")

    def _aggregate_metrics_history(self) -> list:
        """The fleet's sampler rings: this process's own plus every live
        worker's (fetched via its `metrics_history` action, relabeled with
        the worker id), merged by timestamp. A worker that cannot answer is
        skipped — a telemetry read must never fail on a flaky fleet. Dedup
        by sample id: an in-process fleet shares one ring, and its samples
        must not triple-count."""
        samples = list(timeseries.samples())
        seen = {s.get("sid") for s in samples}
        for w in self.membership.live():
            try:
                resp = flight_action(w.addr, "metrics_history", {},
                                     timeout_s=10.0)
                for s in protocol.METRICS_HISTORY.parse(resp)["samples"]:
                    if s.get("sid") in seen:
                        continue
                    seen.add(s.get("sid"))
                    s = dict(s)
                    s["source"] = f"worker:{w.worker_id}"
                    samples.append(s)
            except Exception:
                pass
        samples.sort(key=lambda s: s.get("ts", 0.0))
        return samples

    def _watch_status(self) -> dict:
        """The one-call ops snapshot behind `igloo top`: throughput and
        latency quantiles over the recent query log, admission state,
        per-worker topology, in-flight qids, and the journal tail."""
        now = time.time()
        window_s = 60.0
        recent = [q.to_record() for q in stats.query_log()
                  if now - q.started_at <= window_s]
        lats = sorted(r["elapsed_s"] for r in recent)

        def pct(q: float) -> float:
            if not lats:
                return 0.0
            return lats[min(max(int(q * len(lats) + 0.999999) - 1, 0),
                            len(lats) - 1)]

        return protocol.WATCH_STATUS.build(
            qps=round(len(recent) / window_s, 4),
            p50_ms=round(pct(0.5) * 1000.0, 3),
            p99_ms=round(pct(0.99) * 1000.0, 3),
            window_s=window_s,
            serving=self.admission.snapshot(),
            workers=[{"id": w.worker_id, "addr": w.addr,
                      "devices": w.devices, "slots": w.slots,
                      "age_s": round(now - w.last_seen, 1)}
                     for w in self.membership.live()],
            active=self.executor.active_queries(),
            events=events.events(limit=20),
            samples=timeseries.samples()[-12:])

    def list_actions(self, context):
        # straight from the registry: the flight-actions checker holds this
        # surface and do_action's dispatch to the same declaration
        return protocol.action_doc("coordinator")

    def get_flight_info(self, context, descriptor):
        sql = self._descriptor_sql(descriptor)
        # plan once for the schema — the reference executes the whole query
        # here and AGAIN in do_get (crates/api/src/lib.rs:81-149)
        schema = self._result_schema(sql)
        endpoint = flight.FlightEndpoint(sql.encode(), [self._public_location()])
        return flight.FlightInfo(schema, descriptor, [endpoint], -1, -1)

    def get_schema(self, context, descriptor):
        return flight.SchemaResult(self._result_schema(
            self._descriptor_sql(descriptor)))

    def do_get(self, context, ticket):
        faults.inject("coordinator.do_get")
        raw = ticket.ticket.decode()
        try:
            # the registry coerces every extended-ticket field HERE, so a
            # mistyped field ("5" for deadline_s, [5] for priority) is a
            # "bad query ticket" error naming the field, not a TypeError
            # surfacing as an opaque gRPC internal error mid-execute
            t = protocol.parse_query_ticket(raw)
        except protocol.ProtocolError as ex:
            raise flight.FlightServerError(f"bad query ticket: {ex}")
        sql, deadline_s, qid = t["sql"], t["deadline_s"], t["qid"]
        # trace_id is the client-chosen trace identity: lets a caller
        # correlate its own telemetry with the stitched server timeline
        priority, session = t["priority"], t["session"]
        trace_id = t["trace_id"]
        trace = None
        if flight_recorder.enabled():
            trace = flight_recorder.Trace(trace_id=trace_id, qid=qid or "",
                                          sql=sql)
        try:
            # span hygiene: the request scope gives this (reused gRPC)
            # thread a fresh span stack per query and stitches whatever the
            # execution records — planning, admission wait, local fallback
            # spans — under one "query" root
            with flight_recorder.request_scope(trace, "query",
                                               proc="coordinator",
                                               qid=qid or ""):
                out = self.execute_sql(sql, stream=True,
                                       deadline_s=deadline_s,
                                       qid=qid, priority=priority,
                                       session=session, trace=trace)
        except serving.ServerBusy as ex:
            # retryable by the client's RpcPolicy classification; carries
            # the retry-after hint in the message (docs/serving.md). Shed
            # queries never publish a trace — under overload the ring would
            # otherwise churn with empty shed records
            raise ex.as_flight_error()
        except IglooError as ex:
            if trace is not None and not trace.deferred:
                flight_recorder.publish(trace)
            raise flight.FlightServerError(str(ex))
        if trace is not None and not trace.deferred:
            # local / cached / non-SELECT paths: the result is materialized,
            # the query is over — publish now. Distributed streams publish
            # from the executor's finalize instead (trace.deferred).
            flight_recorder.publish(trace)
        if isinstance(out, tuple):
            # distributed: relay the root worker's stream batch-wise, via
            # rpc.flight_stream_response so dictionary-bearing result schemas
            # get their dictionary batches written without costing plain
            # schemas their Flight error statuses
            return rpc.flight_stream_response(
                out[0], faults.wrap_stream("coordinator.do_get", out[1]))
        return flight.RecordBatchStream(out)

    def do_put(self, context, descriptor, reader, writer):
        faults.inject("coordinator.do_put")
        name = self._descriptor_table(descriptor)
        table = reader.read_all()
        self.register_table(name, table)

    def do_exchange(self, context, descriptor, reader, writer):
        """Bidirectional exchange (reference proto flight.proto:127):

        - cmd descriptor: the command is SQL; any uploaded batches are
          ignored and the query's result streams back.
        - path descriptor [table]: uploaded batches register the table (as
          do_put) and the stored table streams back — a round-trip echo a
          stock client can verify; with no uploaded batches the currently
          registered table streams back."""
        faults.inject("coordinator.do_exchange")
        if descriptor.descriptor_type == flight.DescriptorType.CMD:
            sql = descriptor.command.decode()
            try:
                table = self.execute_sql(sql)
            except serving.ServerBusy as ex:
                raise ex.as_flight_error()
            except IglooError as ex:
                raise flight.FlightServerError(str(ex))
            writer.begin(table.schema)
            for batch in table.to_batches():
                writer.write_batch(batch)
            return
        name = self._descriptor_table(descriptor)
        uploaded = None
        try:
            uploaded = reader.read_all()
        except OSError as ex:
            # pyarrow raises ArrowIOError "Client never sent a data message"
            # for a write-less exchange — the one condition where echoing the
            # stored table is the contract. Anything else is a real upload
            # failure and must NOT be masked as a successful-looking echo.
            if "never sent a data message" not in str(ex):
                raise flight.FlightServerError(f"exchange upload failed: {ex}")
        except Exception as ex:
            # mid-stream decode/transport failure: surface it to the client
            raise flight.FlightServerError(f"exchange upload failed: {ex}")
        if uploaded is not None and uploaded.num_rows > 0:
            self.register_table(name, uploaded)
        try:
            table = self.engine.catalog.get(name).read()
        except Exception as ex:
            raise flight.FlightServerError(f"exchange: {ex}")
        writer.begin(table.schema)
        for batch in table.to_batches():
            writer.write_batch(batch)

    # The reference proto also declares PollFlightInfo (flight.proto:92);
    # pyarrow's FlightServerBase has no server hook for it, so the
    # immediate-complete equivalent is served as the "poll_flight_info"
    # action (do_action below): it returns the serialized FlightInfo for a
    # SQL command with progress=1.0 — long-running-query polling semantics
    # collapse to "already complete" because get_flight_info only PLANS.

    def list_flights(self, context, criteria):
        for name in sorted(self.engine.catalog.names()):
            desc = flight.FlightDescriptor.for_path(name)
            sql = f"SELECT * FROM {name}"
            endpoint = flight.FlightEndpoint(sql.encode(),
                                             [self._public_location()])
            yield flight.FlightInfo(self._result_schema(sql), desc,
                                    [endpoint], -1, -1)

    # --- helpers ---

    def _public_location(self) -> str:
        return f"grpc+tcp://{self.advertise_host}:{self.port}"

    @staticmethod
    def _descriptor_sql(descriptor) -> str:
        if descriptor.command:
            return descriptor.command.decode()
        if descriptor.path:
            return f"SELECT * FROM {descriptor.path[0].decode()}"
        raise flight.FlightServerError("descriptor has no SQL command")

    @staticmethod
    def _descriptor_table(descriptor) -> str:
        if descriptor.path:
            return descriptor.path[0].decode()
        if descriptor.command:
            return descriptor.command.decode()
        raise flight.FlightServerError("descriptor has no table name")

    def _result_schema(self, sql: str) -> pa.Schema:
        try:
            plan = self.engine.plan(sql)
        except IglooError as ex:
            raise flight.FlightServerError(str(ex))
        from igloo_tpu.exec.executor import _pa_type_for
        return pa.schema([pa.field(f.name, _pa_type_for(f.dtype), f.nullable)
                          for f in plan.schema])


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="igloo-coordinator")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=50051)
    ap.add_argument("--config", default=None)
    args = ap.parse_args(argv)

    timeout = 15.0
    server = CoordinatorServer(f"grpc+tcp://{args.host}:{args.port}",
                               worker_timeout_s=timeout)
    if args.config:
        from igloo_tpu.config import (
            Config, apply_storage, make_provider, rpc_policy,
        )
        cfg = Config.load(args.config)
        server.membership.timeout_s = cfg.cluster.worker_timeout_s
        # [rpc] config is the base; IGLOO_RPC_* env still wins per-field
        rpc.set_default_policy(rpc.policy_from_env(rpc_policy(cfg)))
        # [storage] likewise (policy + prefetch twins; env wins per-field)
        apply_storage(cfg)
        if cfg.rpc.query_deadline_s is not None and \
                not os.environ.get(QUERY_DEADLINE_ENV):
            # same precedence as every other [rpc] knob: env beats config;
            # a configured 0 means explicitly unbounded
            server.executor.default_deadline_s = \
                cfg.rpc.query_deadline_s or None
        # [serving] section: explicit values flow through the controller's
        # constructor, where IGLOO_SERVING_* env still wins per-field
        sv = cfg.serving
        server.admission = serving.AdmissionController(
            queue_depth=sv.queue_depth,
            max_concurrency=sv.max_concurrency,
            session_inflight=sv.session_inflight,
            hbm_budget_bytes=sv.hbm_budget_bytes,
            weights=sv.weights)
        for t in cfg.tables:
            server.register_table(t.name, make_provider(t))
    print(f"igloo-coordinator serving on grpc+tcp://{args.host}:"
          f"{server.port}", flush=True)
    try:
        server.serve()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
