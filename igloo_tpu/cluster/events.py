"""Cluster event journal (docs/observability.md#watchtower).

One structured, bounded, process-wide journal turning the fleet's
counters into a NARRATIVE: worker join/evict/recover, fragment
re-dispatch and busy-requeue, admission shed, demotion rungs,
deadline/cancel, snapshot retry, corruption quarantine, compile-cache
push/pull, salting/broadcast flips, slow-query escalations. Every event
carries a wall timestamp, a severity, and — where applicable — the
worker id, qid, and trace_id, so an incident is reconstructible from
`system.cluster_events` alone.

Producers call `emit(kind, ...)` with a kind from the event catalog in
docs/observability.md#event-catalog — the event-names lint checker
(igloo_tpu/lint/event_names.py) holds emit sites and catalog to each
other, the same contract the metric-names and span-names checkers
enforce for counters and spans.

Worker events reach the coordinator by riding the heartbeat: the worker
drains its pending queue into the registry-declared `events` field of
WORKER_INFO (cluster/protocol.py) and the coordinator `ingest()`s them
under the sender's worker label. Every event has a process-unique `eid`,
and `ingest` drops eids it has already journaled — an in-process test
fleet (coordinator and workers sharing this module) forwards without
duplicating.

Surfaces: the `system.cluster_events` table, the coordinator `events`
Flight action, Prometheus `igloo_events_total{kind=...}` (via
`prometheus_lines()` on the coordinator's `metrics` action), and JSONL
export to `$IGLOO_TRACE_DIR/events.jsonl`.

`IGLOO_WATCH=0` (utils/timeseries.enabled) makes `emit` a no-op — no
ring writes, no counters, bit-identical to a build without the journal.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional

from igloo_tpu.utils import timeseries, tracing

SEVERITIES = ("info", "warn", "error")

_lock = threading.Lock()
_GUARDED_BY = {
    "_lock": ("_ring", "_pending", "_counts", "_seen", "_seen_order"),
}
_ring: deque = deque(maxlen=timeseries.history())
_pending: deque = deque(maxlen=256)   # worker->coordinator forward queue
_counts: dict = {}                    # kind -> cumulative count (unbounded
                                      # in VALUE, bounded in KEYS by catalog)
_seen: set = set()                    # eids already journaled (dedup)
_seen_order: deque = deque()          # FIFO for bounding _seen
_SEEN_MAX = 4096
_eid_seq = itertools.count(1)


def _next_eid() -> str:
    return f"{os.getpid():x}-{next(_eid_seq)}"


def _severity_rank(sev: str) -> int:
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        return 0


def _export(ev: dict) -> None:
    """Best-effort JSONL append beside the trace export — a full disk
    must never take the cluster down (mirrors flight_recorder)."""
    out_dir = os.environ.get("IGLOO_TRACE_DIR")
    if not out_dir:
        return
    try:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "events.jsonl")
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(ev, default=str) + "\n")
    except OSError:
        tracing.counter("events.export_failed")


def _append_locked(ev: dict) -> None:
    _ring.append(ev)
    _seen.add(ev["eid"])
    _seen_order.append(ev["eid"])
    while len(_seen_order) > _SEEN_MAX:
        _seen.discard(_seen_order.popleft())
    _counts[ev["kind"]] = _counts.get(ev["kind"], 0) + 1


def emit(kind: str, severity: str = "info", worker: str = "",
         qid: str = "", trace_id: str = "", **attrs) -> Optional[dict]:
    """Journal one event. Returns the event dict, or None when the
    watchtower is off. `kind` must be a cataloged event kind
    (docs/observability.md#event-catalog, enforced by lint)."""
    if not timeseries.enabled():
        return None
    ev = {"eid": _next_eid(), "ts": time.time(), "kind": kind,
          "severity": severity if severity in SEVERITIES else "info",
          "worker": worker, "qid": qid, "trace_id": trace_id}
    if attrs:
        ev["attrs"] = attrs
    with _lock:
        _append_locked(ev)
        _pending.append(ev)
    tracing.counter("events.emitted")
    tracing.REGISTRY.bump_version()
    _export(ev)
    return ev


def ingest(evts: list, worker: str = "") -> int:
    """Coordinator side of heartbeat forwarding: journal a batch of
    worker events under the sender's label. Already-seen eids (the
    in-process fleet case, or a heartbeat retry) are dropped. Returns
    how many were new."""
    if not timeseries.enabled() or not evts:
        return 0
    added = 0
    with _lock:
        for ev in evts:
            if not isinstance(ev, dict) or "kind" not in ev:
                continue
            ev = dict(ev)
            ev.setdefault("eid", _next_eid())
            if ev["eid"] in _seen:
                continue
            if worker and not ev.get("worker"):
                ev["worker"] = worker
            _append_locked(ev)
            added += 1
    if added:
        tracing.counter("events.forwarded", added)
        tracing.REGISTRY.bump_version()
    return added


def drain_forward(max_n: int = 64) -> list:
    """Worker side of heartbeat forwarding: pop up to `max_n` pending
    events to ship in WORKER_INFO. Events popped here but lost to a
    failed heartbeat stay journaled locally (the ring is the record;
    forwarding is best-effort)."""
    out: list = []
    with _lock:
        while _pending and len(out) < max_n:
            out.append(_pending.popleft())
    return out


def requeue_forward(evts: list) -> None:
    """Put a drained batch back at the FRONT of the forward queue after a
    failed heartbeat, preserving order (next beat retries them first)."""
    if not evts:
        return
    with _lock:
        for ev in reversed(evts):
            _pending.appendleft(ev)


def events(min_severity: str = "info", limit: Optional[int] = None) -> list:
    """Journal contents, oldest first, at or above `min_severity`."""
    floor = _severity_rank(min_severity)
    with _lock:
        out = [e for e in _ring
               if _severity_rank(e.get("severity", "info")) >= floor]
    if limit is not None and limit >= 0:
        out = out[-limit:]
    return out


def counts() -> dict:
    with _lock:
        return dict(_counts)


def prometheus_lines(prefix: str = "igloo") -> list:
    """Labeled per-kind totals for the coordinator `metrics` action's
    extra_lines — the registry's own counters are unlabeled, so the
    journal carries the {kind=...} dimension itself."""
    with _lock:
        snap = dict(_counts)
    if not snap:
        return []
    m = f"{prefix}_events_total"
    lines = [f"# HELP {m} Cluster journal events by kind "
             "(docs/observability.md#event-catalog).",
             f"# TYPE {m} counter"]
    for kind in sorted(snap):
        lines.append(f'{m}{{kind="{kind}"}} {snap[kind]}')
    return lines


def clear() -> None:
    """Tests only: drop journal state and re-bound the ring from the
    current IGLOO_WATCH_HISTORY."""
    global _ring
    with _lock:
        _ring = deque(maxlen=timeseries.history())
        _pending.clear()
        _counts.clear()
        _seen.clear()
        _seen_order.clear()
