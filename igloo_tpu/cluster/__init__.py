"""Distributed control plane: coordinator, workers, fragments, Flight client.

The reference declares this tier across four crates (coordinator / worker /
api / client) and stubs every wire boundary: plans serialize to empty bytes,
results are fabricated, no server implements the fragment service, and the
shuffle fetch returns empty (SURVEY.md gaps G1/G2). This package is the
working version: real plan serde (serde.py), a fragmenting planner with
partial-aggregate pushdown (fragment.py), a coordinator with liveness
eviction + elastic fragment re-dispatch (coordinator.py), workers that
execute fragments and serve peers (worker.py), all over Arrow Flight.
"""
from igloo_tpu.cluster.client import DistributedClient
from igloo_tpu.cluster.fragment import DistributedPlanner, QueryFragment

__all__ = ["DistributedClient", "DistributedPlanner", "QueryFragment"]
