"""Distributed query flight recorder: cross-process trace stitching and
Perfetto/Chrome-trace export.

utils/tracing.py spans are thread-local and die at the Flight boundary; this
module is what makes them a DISTRIBUTED timeline. Every span carries a
`(trace_id, span_id, parent_id)` identity anchored to wall-clock epoch time
(tracing.epoch), the trace context rides the extended JSON do_get/dispatch
tickets (cluster/coordinator.py, cluster/worker.py), workers return their
span trees beside per-fragment stats, and the coordinator stitches ONE trace
per query out of all of them. A `Trace` is the stitching surface: an
append-only, lock-guarded list of flat span dicts any thread or process can
contribute to.

Consumption paths (docs/observability.md#distributed-tracing):

- `system.query_traces`: one row per span of every ring-resident trace;
- the coordinator's `trace` Flight action: Chrome-trace JSON by trace_id/qid,
  loadable directly in Perfetto (ui.perfetto.dev) or chrome://tracing;
- ``IGLOO_TRACE_DIR``: every finished trace appended as one JSON line to
  ``<dir>/traces.jsonl``;
- ``EXPLAIN ANALYZE`` prints a ``-- trace: <id>`` pointer.

Knobs: ``IGLOO_TRACE=0`` kills the recorder (spans still exist thread-local,
nothing is stitched or retained); ``IGLOO_TRACE_RING`` sizes the ring
(default 32 traces); ``IGLOO_TRACE_DEVICE=1`` turns on the jax.profiler
bridge (tracing.device_annotation). Overhead with the recorder ON is a few
tens of microseconds per query (id generation + one flatten + a ring
append) — under the same <1%-of-a-5ms-query budget the stats layer holds;
scripts/trace_smoke.py measures it.

Cross-host caveat: spans are anchored to each process's own wall clock, so
timelines from different HOSTS carry that clock skew (same-host worker
processes share a clock). Parent/child STRUCTURE is skew-free — it comes
from explicit ids, not timestamps.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Optional

from igloo_tpu.utils import tracing

TRACE_ENV = "IGLOO_TRACE"
TRACE_DIR_ENV = "IGLOO_TRACE_DIR"
RING_ENV = "IGLOO_TRACE_RING"

_tls = threading.local()

# lock discipline (checked by igloo-lint lock-discipline): the ring is
# appended by whichever thread finishes a query and read by system-table
# scans / the trace Flight action; a Trace's span list is appended from
# handler, dispatch-pool, relay, and adopted worker threads at once
_GUARDED_BY = {"_ring_lock": ("_ring", "_pinned"), "_lock": ("_spans",)}

_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=max(int(os.environ.get(RING_ENV, "32") or 32), 1))

# Watchtower retention override (docs/observability.md#watchtower): a trace
# `pin()`ed here survives ring eviction — the slow-query detector pins the
# anomalous query's trace so the evidence is still readable after another
# ring's worth of normal queries has flowed past. Bounded FIFO of LIVE
# Trace objects (straggler spans still land), capped separately from the
# ring so a burst of anomalies cannot grow memory unboundedly.
_PIN_MAX = 32
_pinned: "dict[str, Trace]" = {}


def enabled() -> bool:
    return os.environ.get(TRACE_ENV, "1") != "0"


def _proc_label() -> str:
    return f"pid:{os.getpid()}"


def _tid() -> int:
    # Chrome-trace tids are small ints; the low bits of the thread ident are
    # distinct across one process's live threads, which is all a track needs
    return threading.get_ident() & 0xFFFF


class Trace:
    """One query's cross-process span collection. Thread-safe append-only:
    the coordinator's dispatch pool, the relay generator, adopted worker
    threads, and stitched-in remote span trees all write concurrently."""

    __slots__ = ("trace_id", "qid", "sql", "deferred", "_lock", "_spans")

    def __init__(self, trace_id: Optional[str] = None, qid: str = "",
                 sql: str = ""):
        self.trace_id = str(trace_id) if trace_id else tracing.new_trace_id()
        self.qid = str(qid or "")
        self.sql = sql
        # ownership handoff: the distributed executor publishes at stream
        # end; the do_get handler publishes everything else at handler exit
        self.deferred = False
        self._lock = threading.Lock()
        self._spans: list[dict] = []

    # --- writes -------------------------------------------------------------

    def _append(self, name: str, t0: float, t1: float, span_id: str,
                parent_id: Optional[str], proc: Optional[str],
                tid: Optional[int], attrs: Optional[dict]) -> str:
        d = {"name": name, "id": span_id, "parent": parent_id,
             "proc": proc or _proc_label(),
             "tid": tid if tid is not None else _tid(),
             "t0": t0, "t1": t1}
        if attrs:
            d["args"] = attrs
        with self._lock:
            self._spans.append(d)
        return span_id

    def add_span(self, name: str, t0: float, t1: float,
                 parent_id: Optional[str] = None, proc: Optional[str] = None,
                 tid: Optional[int] = None, **attrs) -> str:
        """Record one completed span by wall-clock epoch bounds — the hook
        for durations measured outside any thread-local scope (the serving
        permit's HBM hold, the coordinator's root-result relay)."""
        return self._append(name, t0, t1, tracing.new_span_id(), parent_id,
                            proc, tid, attrs or None)

    @contextlib.contextmanager
    def span(self, name: str, parent_id: Optional[str] = None,
             proc: Optional[str] = None, **attrs):
        """Explicit cross-thread span: yields its span_id BEFORE the body
        runs so callers can ship it as the parent of remote work (the
        coordinator's dispatch span does exactly that)."""
        sid = tracing.new_span_id()
        t0 = time.time()
        try:
            yield sid
        finally:
            self._append(name, t0, time.time(), sid, parent_id, proc,
                         None, attrs or None)

    def add_tree(self, span: tracing.Span, parent_id: Optional[str] = None,
                 proc: Optional[str] = None,
                 tid: Optional[int] = None) -> None:
        """Flatten one thread-local tracing.Span tree into the trace,
        re-parenting its root under `parent_id`."""
        out: list[dict] = []
        if tid is None:
            tid = _tid()

        def rec(s: tracing.Span, parent: Optional[str]) -> None:
            sid = s.span_id or tracing.new_span_id()
            d = {"name": s.name, "id": sid, "parent": parent,
                 "proc": proc or _proc_label(), "tid": tid,
                 "t0": tracing.epoch(s.start),
                 "t1": tracing.epoch(s.end or time.perf_counter())}
            if s.attrs:
                d["args"] = dict(s.attrs)
            out.append(d)
            for c in s.children:
                rec(c, sid)
        rec(span, parent_id)
        with self._lock:
            self._spans.extend(out)

    def extend(self, span_dicts, proc: Optional[str] = None) -> None:
        """Stitch in span dicts a REMOTE process reported (a worker's
        `spans` list riding its fragment report). Malformed entries are
        dropped, not fatal — telemetry must never fail the query."""
        ok = []
        for d in span_dicts or ():
            if isinstance(d, dict) and "name" in d and "t0" in d:
                if proc and not d.get("proc"):
                    d["proc"] = proc
                ok.append(d)
        if ok:
            with self._lock:
                self._spans.extend(ok)

    # --- reads --------------------------------------------------------------

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def to_record(self) -> dict:
        sp = self.spans()
        return {"trace_id": self.trace_id, "qid": self.qid, "sql": self.sql,
                "t0": min((s["t0"] for s in sp), default=0.0),
                "t1": max((s["t1"] for s in sp), default=0.0),
                "spans": sp}


# --- thread-local activation -------------------------------------------------


def current() -> Optional[Trace]:
    """The trace the current thread's request scope is recording into."""
    return getattr(_tls, "trace", None)


def current_root() -> Optional[str]:
    """The active request scope's root span id (allocated up front so
    cross-thread spans can parent under it while the request runs)."""
    return getattr(_tls, "root_id", None)


class _RequestScope:
    """One server request's span scope: installs a FRESH thread-local span
    stack (span hygiene — a reused gRPC thread must not accumulate spans or
    interleave unrelated queries) and activates `trace` for this thread. On
    exit the scope's span roots flush into the trace under a root span whose
    id was allocated up front (yielded, and readable via `current_root()`).
    `trace=None` still resets the thread-local state — the hygiene applies
    whether or not anything is recorded. Class-based: this sits on the
    per-query hot path."""

    __slots__ = ("trace", "name", "proc", "parent_id", "keep_roots",
                 "attrs", "_tok", "_prev", "_root_id", "_t0")

    def __init__(self, trace: Optional[Trace], name: str,
                 proc: Optional[str], parent_id: Optional[str],
                 keep_roots: bool, attrs: Optional[dict]):
        self.trace = trace
        self.name = name
        self.proc = proc
        self.parent_id = parent_id
        self.keep_roots = keep_roots
        self.attrs = attrs

    def __enter__(self) -> Optional[str]:
        self._tok = tracing.push_scope()
        self._prev = (getattr(_tls, "trace", None),
                      getattr(_tls, "root_id", None),
                      getattr(_tls, "proc", None))
        self._root_id = tracing.new_span_id() \
            if self.trace is not None else None
        _tls.trace = self.trace
        _tls.root_id = self._root_id
        _tls.proc = self.proc
        self._t0 = time.time()
        return self._root_id

    def __exit__(self, *exc):
        roots = tracing.pop_scope(self._tok, keep_roots=self.keep_roots)
        _tls.trace, _tls.root_id, _tls.proc = self._prev
        trace = self.trace
        if trace is not None:
            tid = _tid()
            trace._append(self.name, self._t0, time.time(), self._root_id,
                          self.parent_id, self.proc, tid, self.attrs)
            for s in roots:
                trace.add_tree(s, parent_id=self._root_id, proc=self.proc,
                               tid=tid)
        return False


def request_scope(trace: Optional[Trace], name: str,
                  proc: Optional[str] = None,
                  parent_id: Optional[str] = None,
                  keep_roots: bool = False, **attrs) -> _RequestScope:
    return _RequestScope(trace, name, proc, parent_id, keep_roots,
                         attrs or None)


def capture() -> tuple:
    """Snapshot (trace, parent span id, proc label) for a worker thread
    doing this request's work (the GRACE prefetch thread): its spans then
    land in the same trace, visually overlapping the spawning thread's."""
    return (getattr(_tls, "trace", None),
            tracing.current_span_id() or getattr(_tls, "root_id", None),
            getattr(_tls, "proc", None))


@contextlib.contextmanager
def adopt(ctx: tuple):
    """Run a block on a worker thread with a parent thread's trace adopted:
    fresh span scope (hygiene for pooled threads), spans flushed into the
    parent's trace under the captured parent span."""
    trace, parent, proc = ctx
    tok = tracing.push_scope()
    prev = (getattr(_tls, "trace", None), getattr(_tls, "root_id", None),
            getattr(_tls, "proc", None))
    _tls.trace = trace
    _tls.root_id = parent
    _tls.proc = proc
    try:
        yield
    finally:
        roots = tracing.pop_scope(tok)
        _tls.trace, _tls.root_id, _tls.proc = prev
        if trace is not None:
            for s in roots:
                trace.add_tree(s, parent_id=parent, proc=proc)


# --- the trace ring + exports ------------------------------------------------


def publish(trace: Optional[Trace]) -> Optional[dict]:
    """Retire a finished query's trace: append it to the process ring (the
    system.query_traces backing store, snapshot-tokened by the metrics
    registry) and, when IGLOO_TRACE_DIR is set, write its record to
    `<dir>/traces.jsonl`. The ring holds the LIVE Trace — a straggler span
    recorded after publish (the serving permit's hold span outlives the
    stream that published) still lands in ring-backed reads; the JSONL line
    is the publish-time snapshot. Best-effort by the telemetry contract;
    returns the exported record when IGLOO_TRACE_DIR is set, else None (the
    record is built lazily — this runs once per query)."""
    if trace is None:
        return None
    with _ring_lock:
        _ring.append(trace)
    # counter() bumps the registry version too — that is the system-table
    # snapshot invalidation, no separate bump needed
    tracing.counter("trace.published")
    d = os.environ.get(TRACE_DIR_ENV)
    if not d:
        return None
    rec = trace.to_record()
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "traces.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        tracing.counter("trace.export_failed")
    return rec


def pin(trace_id: Optional[str] = None, qid: Optional[str] = None) -> bool:
    """Force retention of a ring-resident trace beyond ring eviction (the
    watchtower's slow-query escalation, utils/watch.py). Looks the trace up
    by trace_id or qid in the ring (and among already-pinned traces — a
    re-pin refreshes FIFO position); returns False when no such trace is
    resident, True when pinned."""
    if trace_id is None and qid is None:
        return False
    with _ring_lock:
        target = None
        for t in reversed(_ring):
            if ((trace_id is not None and t.trace_id == trace_id)
                    or (qid is not None and t.qid == str(qid))):
                target = t
                break
        if target is None:
            for t in reversed(list(_pinned.values())):
                if ((trace_id is not None and t.trace_id == trace_id)
                        or (qid is not None and t.qid == str(qid))):
                    target = t
                    break
        if target is None:
            return False
        _pinned.pop(target.trace_id, None)
        _pinned[target.trace_id] = target
        while len(_pinned) > _PIN_MAX:
            _pinned.pop(next(iter(_pinned)))
    tracing.counter("trace.pinned")
    return True


def _resident_locked() -> list:
    """Pinned-but-evicted traces first (oldest), then the ring (most recent
    last); a trace both pinned and ring-resident appears once."""
    ring_ids = {t.trace_id for t in _ring}
    out = [t for t in _pinned.values() if t.trace_id not in ring_ids]
    out.extend(_ring)
    return out


def records() -> list:
    """Resident trace records (ring + pinned), most recent last
    (snapshotted at read, so post-publish straggler spans are included)."""
    with _ring_lock:
        traces = _resident_locked()
    return [t.to_record() for t in traces]


def get_record(trace_id: Optional[str] = None,
               qid: Optional[str] = None) -> Optional[dict]:
    """Look a trace up by trace_id or qid; neither = the most recent."""
    with _ring_lock:
        traces = _resident_locked()
    if not traces:
        return None
    if trace_id is None and qid is None:
        return traces[-1].to_record()
    for t in reversed(traces):
        if trace_id is not None and t.trace_id == trace_id:
            return t.to_record()
        if qid is not None and t.qid == str(qid):
            return t.to_record()
    return None


def clear() -> None:
    with _ring_lock:
        _ring.clear()
        _pinned.clear()
    tracing.REGISTRY.bump_version()


# --- Chrome-trace / Perfetto export ------------------------------------------


def to_chrome_trace(rec: dict) -> dict:
    """A trace record as Chrome-trace JSON (the `traceEvents` object form),
    loadable in Perfetto (ui.perfetto.dev) and chrome://tracing. Each span
    becomes one complete ("X") event; each distinct `proc` label becomes a
    pid with a process_name metadata event; timestamps are microseconds
    relative to the trace's first span."""
    base = rec.get("t0") or 0.0
    events: list = []
    pids: dict = {}
    for s in rec.get("spans", ()):
        proc = s.get("proc") or "proc"
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": proc}})
        args = dict(s.get("args") or {})
        args["span"] = s.get("id")
        if s.get("parent"):
            args["parent"] = s["parent"]
        events.append({"name": s.get("name", "?"), "ph": "X", "pid": pid,
                       "tid": int(s.get("tid") or 0),
                       "ts": round((s["t0"] - base) * 1e6, 3),
                       "dur": round(max(s.get("t1", s["t0"]) - s["t0"], 0.0)
                                    * 1e6, 3),
                       "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": rec.get("trace_id", ""),
                          "qid": rec.get("qid", ""),
                          "sql": rec.get("sql", "")}}
