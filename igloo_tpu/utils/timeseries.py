"""Watchtower time-series recorder (docs/observability.md#watchtower).

A lock-guarded ring-buffer sampler over the process-wide MetricsRegistry:
every `IGLOO_WATCH_INTERVAL_S` seconds it snapshots every gauge verbatim
and a selected set of counters as per-second RATES (counters are
monotonic, so the interesting signal is the first derivative — bytes/s
over the exchange, retries/s against the object store, sheds/s at the
admission gate). Memory is bounded by construction: the ring holds at
most `IGLOO_WATCH_HISTORY` samples (default 720 = one hour at the 5 s
default interval), each a small dict of floats.

One sampler per process, started by the coordinator and by each worker
(`start("coordinator"|"worker")`). Workers' rings are aggregated
coordinator-side by the `metrics_history` Flight action with per-worker
source labels; locally the ring backs the `system.metrics_history`
table. `IGLOO_WATCH=0` is the watchtower kill switch: `start()` becomes
a no-op, nothing samples, nothing is recorded — counters and plans are
bit-identical to a build without the watchtower.

Threading: `Sampler` is written to from its own daemon thread and read
from Flight/system-table threads; all ring/previous-snapshot state is
guarded by one lock. Rates are computed against the PREVIOUS sample's
counter snapshot over monotonic elapsed time, so wall-clock steps do
not corrupt them.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Optional

from igloo_tpu.utils import tracing

#: process-unique sample ids: the coordinator's `metrics_history`
#: aggregation dedupes on these, so an in-process test fleet (coordinator
#: and workers sharing this module's one ring) doesn't triple-count
_sid_seq = itertools.count(1)

WATCH_ENV = "IGLOO_WATCH"
INTERVAL_ENV = "IGLOO_WATCH_INTERVAL_S"
HISTORY_ENV = "IGLOO_WATCH_HISTORY"

# Counters sampled as per-second rates. A selection, not the whole
# registry: the fleet-health series worth graphing over an hour —
# data movement (exchange/spill/storage bytes), pressure (sheds,
# retries, faults), and throughput (fragments, distributed queries).
# Names must stay in the docs/observability.md metrics catalog
# (metric-names lint).
RATE_COUNTERS = (
    "rpc.retries",
    "rpc.timeouts",
    "exchange.bytes",
    "exchange.fetch_bytes",
    "exchange.partition_bytes",
    "exchange.spill_bytes",
    "grace.partition_bytes",
    "storage.read_bytes",
    "storage.retry",
    "serving.shed",
    "serving.admitted",
    "coordinator.fragments_dispatched",
    "coordinator.distributed_queries",
    "worker.fragments",
    "compile_cache.hit",
    "compile_cache.miss",
    "faults.injected",
    "events.emitted",
)

# The query-latency summary feeds two derived series: completion rate
# (qps) and the windowed mean latency over the sampling interval.
_LATENCY_HIST = "query.latency_s"


def enabled() -> bool:
    """Watchtower master switch — sampler, baselines, journal all key off
    this ONE knob so `IGLOO_WATCH=0` is a complete kill switch."""
    return os.environ.get("IGLOO_WATCH", "1") != "0"


def interval_s() -> float:
    return float(os.environ.get("IGLOO_WATCH_INTERVAL_S", "5"))


def history() -> int:
    return max(int(os.environ.get("IGLOO_WATCH_HISTORY", "720")), 1)


class Sampler:
    """Bounded ring of registry snapshots; one per process."""

    _GUARDED_BY = {
        "_lock": ("_ring", "_prev_counters", "_prev_hist", "_prev_mono"),
    }

    def __init__(self, source: str = "local",
                 interval: Optional[float] = None,
                 maxlen: Optional[int] = None):
        self.source = source
        self.interval = interval_s() if interval is None else float(interval)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=history() if maxlen is None
                                  else max(int(maxlen), 1))
        self._prev_counters: dict = {}
        self._prev_hist: tuple = (0, 0.0)   # (count, sum) of _LATENCY_HIST
        self._prev_mono: Optional[float] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling --------------------------------------------------------

    def sample_once(self, *, dt: Optional[float] = None) -> dict:
        """Take one sample now and append it to the ring. `dt` overrides
        the measured elapsed seconds (tests want exact rate arithmetic).
        The first sample has no predecessor, so its rates are empty."""
        counters = tracing.REGISTRY.counters()
        gauges = tracing.REGISTRY.gauges()
        hists = tracing.REGISTRY.histograms()
        lat = hists.get(_LATENCY_HIST) or {"count": 0, "sum": 0.0}
        now_mono = time.monotonic()
        sample = {"sid": f"{os.getpid():x}-{next(_sid_seq)}",
                  "ts": time.time(), "source": self.source,
                  "rates": {}, "gauges": {k: float(v)
                                          for k, v in gauges.items()}}
        with self._lock:
            elapsed = dt
            if elapsed is None:
                elapsed = (now_mono - self._prev_mono
                           if self._prev_mono is not None else 0.0)
            if elapsed > 0:
                rates = sample["rates"]
                for name in RATE_COUNTERS:
                    cur = counters.get(name)
                    if cur is None:
                        continue
                    prev = self._prev_counters.get(name, 0)
                    rates[name] = max(cur - prev, 0) / elapsed
                d_count = max(lat["count"] - self._prev_hist[0], 0)
                rates["query.qps"] = d_count / elapsed
                if d_count:
                    d_sum = max(lat["sum"] - self._prev_hist[1], 0.0)
                    sample["gauges"]["query.latency_mean_s"] = d_sum / d_count
            self._prev_counters = {n: counters[n] for n in RATE_COUNTERS
                                   if n in counters}
            self._prev_hist = (lat["count"], lat["sum"])
            self._prev_mono = now_mono
            self._ring.append(sample)
        tracing.counter("watch.samples")
        return sample

    def samples(self) -> list:
        with self._lock:
            return list(self._ring)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"igloo-watch-{self.source}")
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        # sample immediately so a freshly started process has a baseline
        # row, then on the interval until stopped
        while True:
            try:
                self.sample_once()
            except Exception:
                # the watchtower must never take the server down
                pass
            if self._stop_evt.wait(self.interval):
                return


# -- process-wide singleton ---------------------------------------------

_sampler: Optional[Sampler] = None
_sampler_lock = threading.Lock()


def start(source: str = "local") -> Optional[Sampler]:
    """Start the process sampler (idempotent; the FIRST caller's source
    label wins — an in-process coordinator+worker test fleet shares one
    ring). No-op returning None when `IGLOO_WATCH=0`."""
    global _sampler
    if not enabled():
        return None
    with _sampler_lock:
        if _sampler is None:
            _sampler = Sampler(source=source)
            _sampler.start()
        return _sampler


def stop() -> None:
    global _sampler
    with _sampler_lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop()


def get() -> Optional[Sampler]:
    return _sampler


def samples() -> list:
    s = _sampler
    return s.samples() if s is not None else []
