"""Tracing / timing spans + the process metrics registry.

The reference only has `tracing` calls in its cache crate with no subscriber
ever installed (SURVEY.md §5.1); here the layer is real and has three parts:

- spans: nested timers recorded into a thread-local trace that callers (CLI
  --timing, bench harness) can read. `roots()` is bounded (ROOTS_MAX) so
  long-lived processes — the coordinator in particular — don't leak spans.
- MetricsRegistry: process-wide counters AND histograms (query latency,
  compile time, transfer bytes, rows). Counters stay CUMULATIVE; per-query
  numbers come from `counter_delta()`, a thread-isolated snapshot-diff
  context manager, so concurrent queries can never pollute each other's
  deltas. `prometheus_text()` renders the registry for the cluster's
  `metrics` Flight action.
- `profile_trace()` wraps `jax.profiler.trace` for device-level profiles.

Every counter/histogram name used in the codebase is cataloged in
docs/observability.md; igloo-lint's metric-names checker (`python -m
igloo_tpu.lint`) fails the verify flow when the two drift.
"""
from __future__ import annotations

import contextlib
import itertools
import logging
import os
import re
import threading
import time
import uuid
from collections import Counter, deque
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("igloo_tpu")

_tls = threading.local()

# wall-clock anchor for spans: spans time with perf_counter (cheap, monotonic)
# and `epoch()` maps those instants onto unix time so spans from DIFFERENT
# processes line up on one timeline (utils/flight_recorder.py). Computed once
# at import — NTP drift over a process lifetime is noise at span granularity.
_EPOCH_OFFSET = time.time() - time.perf_counter()

# span identity: ids must be unique ACROSS processes (a stitched trace mixes
# coordinator and worker spans), so a per-process random prefix + a cheap
# atomic counter (itertools.count.__next__ is C-level thread-safe) — ~100x
# cheaper than a uuid4 per span; trace ids use the same scheme (one is
# minted per query, on the hot serving path)
_SPAN_PREFIX = uuid.uuid4().hex[:8]
_span_ids = itertools.count(1)
_trace_ids = itertools.count(1)


def new_span_id() -> str:
    return f"{_SPAN_PREFIX}-{next(_span_ids):x}"


def new_trace_id() -> str:
    return f"{_SPAN_PREFIX}{next(_trace_ids):08x}"


def epoch(perf_t: float) -> float:
    """Map a `time.perf_counter()` instant onto unix epoch seconds."""
    return perf_t + _EPOCH_OFFSET

# spans kept per thread: enough for tooling that reads a few recent queries,
# bounded so a server thread answering queries for days cannot grow without
# limit (the coordinator used to leak its whole query history here)
ROOTS_MAX = 64

# lock discipline (checked by igloo-lint lock-discipline): the registry maps
# are hit from every thread; a CounterDelta's backing Counter is shared with
# adopted worker threads (the GRACE prefetch thread), so all `_data` access
# holds the module-wide _delta_lock
_GUARDED_BY = {"_lock": ("_counters", "_hists", "_gauges", "_version"),
               "_delta_lock": ("_data",)}


@dataclass
class HistogramData:
    """Streaming summary of one histogram: count/sum/min/max (no buckets —
    the consumers are per-query deltas and Prometheus summaries, neither of
    which needs quantiles badly enough to pay per-observation bucketing)."""
    count: int = 0
    sum: float = 0.0
    min: float = float("inf")
    max: float = float("-inf")

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def as_dict(self) -> dict:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}


class MetricsRegistry:
    """Thread-safe process metrics: monotonic counters + summary histograms.

    `version()` is a mutation counter — the system.metrics table provider
    uses it as its snapshot token, so the engine's caches invalidate exactly
    when telemetry changed."""

    def __init__(self):
        self._counters: Counter = Counter()
        self._hists: dict[str, HistogramData] = {}
        self._gauges: dict[str, float] = {}
        self._lock = threading.Lock()
        self._version = 0

    def counter(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] += delta
            self._version += 1

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = HistogramData()
            h.observe(value)
            self._version += 1

    def gauge(self, name: str, value: float) -> None:
        """Set a last-value-wins gauge (queue depth, busy slots, reserved
        bytes — instantaneous state, unlike the monotonic counters)."""
        with self._lock:
            self._gauges[name] = float(value)
            self._version += 1

    def gauge_add(self, name: str, delta: float) -> float:
        """Atomically adjust a gauge by `delta`; returns the new value (the
        acquire/release call sites would otherwise read-modify-write race)."""
        with self._lock:
            v = self._gauges.get(name, 0.0) + delta
            self._gauges[name] = v
            self._version += 1
            return v

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def histograms(self) -> dict:
        with self._lock:
            return {k: h.as_dict() for k, h in self._hists.items()}

    def gauges(self) -> dict:
        with self._lock:
            return dict(self._gauges)

    def version(self) -> int:
        with self._lock:
            return self._version

    def bump_version(self) -> None:
        """External telemetry sources (the query log ring) share the
        registry's snapshot token by bumping it on their own mutations."""
        with self._lock:
            self._version += 1

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._gauges.clear()
            self._version += 1


REGISTRY = MetricsRegistry()


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def prometheus_text(prefix: str = "igloo", extra_lines: Optional[list] = None
                    ) -> str:
    """Render the registry in the Prometheus text exposition format —
    conformant enough for a real scraper to ingest without a shim: every
    metric family gets `# HELP` and `# TYPE` lines, counters become
    `<prefix>_<name>_total`, histograms a summary family (its `_count` and
    `_sum` series). Min/max have no standard slot in a summary, so they are
    exposed as their OWN `_min`/`_max` gauge families rather than riding
    untyped under the summary name. `extra_lines` (already formatted,
    HELP/TYPE included where the producer wants them) are appended — the
    coordinator adds its per-worker fragment aggregates and the cluster
    journal's `igloo_events_total{kind=...}` there."""
    lines: list[str] = []
    for name, value in sorted(REGISTRY.counters().items()):
        m = f"{prefix}_{_sanitize(name)}_total"
        lines.append(f"# HELP {m} Cumulative count of {name} "
                     "(docs/observability.md#metrics-catalog).")
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {value}")
    for name, h in sorted(REGISTRY.histograms().items()):
        m = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# HELP {m} Summary of {name} observations "
                     "(docs/observability.md#metrics-catalog).")
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count {h['count']}")
        lines.append(f"{m}_sum {h['sum']}")
        for bound in ("min", "max"):
            b = f"{m}_{bound}"
            lines.append(f"# HELP {b} All-time {bound} of {name}.")
            lines.append(f"# TYPE {b} gauge")
            lines.append(f"{b} {h[bound]}")
    for name, v in sorted(REGISTRY.gauges().items()):
        m = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# HELP {m} Instantaneous value of {name} "
                     "(docs/observability.md#metrics-catalog).")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {v}")
    if extra_lines:
        lines.extend(extra_lines)
    return "\n".join(lines) + "\n"


# --- counters (module-level API, backed by REGISTRY) ------------------------


# guards collector Counters: a collector is thread-local by default, but
# `adopt_collectors` shares it with a worker thread (the GRACE prefetch
# thread), and `c[name] += d` is a non-atomic read-modify-write
_delta_lock = threading.Lock()


def counter(name: str, delta: int = 1) -> None:
    """Bump a process-wide counter (thread-safe). Any `counter_delta()`
    collectors active on the CURRENT thread accumulate the same bump, which
    is what keeps per-query deltas isolated across concurrent queries."""
    REGISTRY.counter(name, delta)
    cols = getattr(_tls, "collectors", None)
    if cols:
        with _delta_lock:
            for c in cols:
                c[name] += delta


def histogram(name: str, value: float) -> None:
    """Record one observation into a process-wide histogram."""
    REGISTRY.observe(name, value)


def gauge(name: str, value: float) -> None:
    """Set a process-wide gauge to an instantaneous value."""
    REGISTRY.gauge(name, value)


def gauge_add(name: str, delta: float) -> float:
    """Atomically adjust a process-wide gauge; returns the new value."""
    return REGISTRY.gauge_add(name, delta)


def counters() -> dict:
    return REGISTRY.counters()


def histograms() -> dict:
    return REGISTRY.histograms()


def gauges() -> dict:
    return REGISTRY.gauges()


def reset_counters() -> None:
    REGISTRY.reset()


class CounterDelta:
    """Live view of the counter bumps made on this thread (plus any adopted
    threads) since the enclosing `counter_delta()` opened. Readable both
    inside and after the `with` block."""

    def __init__(self, data: Counter):
        self._data = data

    def get(self, name: str, default: int = 0) -> int:
        with _delta_lock:
            return self._data.get(name, default)

    def values(self) -> dict:
        with _delta_lock:
            return {k: v for k, v in self._data.items() if v}

    def __getitem__(self, name: str) -> int:
        # same lock as get()/values(): the backing Counter may be mid-update
        # on an adopted worker thread (`c[name] += d` is not atomic)
        with _delta_lock:
            return self._data[name]

    def __contains__(self, name: str) -> bool:
        with _delta_lock:
            return name in self._data


@contextlib.contextmanager
def counter_delta():
    """Per-query counter deltas as a first-class API.

    Yields a CounterDelta that accumulates every `counter()` bump made on the
    current thread while the block is open — NOT a snapshot-diff of the
    process-wide totals, so two threads each inside their own
    `counter_delta()` observe only their own increments. Worker threads an
    operation fans out to (the GRACE prefetch thread) join via
    `adopt_collectors(capture_collectors())`.
    """
    c: Counter = Counter()
    cols = getattr(_tls, "collectors", None)
    if cols is None:
        cols = _tls.collectors = []
    cols.append(c)
    try:
        yield CounterDelta(c)
    finally:
        _remove_by_identity(cols, c)


def _remove_by_identity(cols: list, c) -> None:
    # Counter compares by CONTENT — list.remove would pop a different,
    # equal-content collector (two empty deltas are ==); remove by identity
    for i, x in enumerate(cols):
        if x is c:
            del cols[i]
            return


def capture_collectors() -> tuple:
    """Snapshot of the current thread's active delta collectors, for handing
    to a worker thread that does work on this query's behalf."""
    return tuple(getattr(_tls, "collectors", ()))


@contextlib.contextmanager
def adopt_collectors(cols: tuple):
    """Run a block on a worker thread with a parent thread's collectors
    installed, so its counter bumps land in the parent's deltas too."""
    own = getattr(_tls, "collectors", None)
    if own is None:
        own = _tls.collectors = []
    own.extend(cols)
    try:
        yield
    finally:
        for c in cols:
            _remove_by_identity(own, c)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a jax.profiler trace (TensorBoard format) around a block."""
    import jax
    with jax.profiler.trace(log_dir):
        yield


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    children: list = field(default_factory=list)
    # flight-recorder identity (utils/flight_recorder.py): stable across the
    # wire so a worker's span tree re-parents under the coordinator's
    # dispatch span. `attrs` land in the Perfetto event's args.
    span_id: str = ""
    parent_id: Optional[str] = None
    attrs: Optional[dict] = None

    @property
    def elapsed_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def tree(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.elapsed_s * 1e3:.2f}ms"]
        for c in self.children:
            lines.append(c.tree(indent + 1))
        return "\n".join(lines)


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
        _tls.roots = deque(maxlen=ROOTS_MAX)
    return stack


def roots() -> deque:
    _stack()
    return _tls.roots


def reset(counters_too: bool = False) -> None:
    """Clear the thread-local span trace. Counters are process-wide and
    cumulative; per-query numbers come from `counter_delta()`, which cannot
    be polluted by concurrent queries. Pass counters_too=True only in
    single-threaded tooling that owns the whole process."""
    _tls.stack = []
    _tls.roots = deque(maxlen=ROOTS_MAX)
    if counters_too:
        reset_counters()


def push_scope() -> tuple:
    """Install a FRESH thread-local span stack/roots, returning a token for
    `pop_scope`. The flight recorder opens one per server request so a
    long-lived gRPC thread cannot accumulate spans toward the deque bound or
    interleave spans from unrelated queries (span hygiene)."""
    tok = (getattr(_tls, "stack", None), getattr(_tls, "roots", None))
    _tls.stack = []
    _tls.roots = deque(maxlen=ROOTS_MAX)
    return tok


def pop_scope(tok: tuple, keep_roots: bool = False) -> list:
    """Restore the pre-`push_scope` state; returns the spans the scope
    collected. With `keep_roots` the collected roots are re-appended to the
    restored deque so same-thread consumers (CLI --timing via `last_trace`)
    still see them."""
    collected = list(getattr(_tls, "roots", ()))
    _tls.stack, _tls.roots = tok
    if keep_roots and collected:
        _stack()  # re-init if the restored state was never initialized
        _tls.roots.extend(collected)
    return collected


def current_span_id() -> Optional[str]:
    stack = getattr(_tls, "stack", None)
    return stack[-1].span_id if stack else None


class _SpanCtx:
    """Class-based span context (a @contextmanager generator costs ~2x as
    much, and spans sit on per-operator and per-RPC paths)."""
    __slots__ = ("span",)

    def __init__(self, s: Span):
        self.span = s

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, *exc):
        self.span.end = time.perf_counter()
        _tls.stack.pop()
        return False


def span(name: str, **attrs) -> _SpanCtx:
    s = Span(name, time.perf_counter(), span_id=new_span_id(),
             attrs=attrs or None)
    stack = _stack()
    if stack:
        s.parent_id = stack[-1].span_id
        stack[-1].children.append(s)
    else:
        _tls.roots.append(s)
    stack.append(s)
    return _SpanCtx(s)


# --- device-trace bridge (IGLOO_TRACE_DEVICE=1) ------------------------------


_device_trace: Optional[bool] = None


def device_trace_enabled() -> bool:
    """Opt-in jax.profiler bridge: when IGLOO_TRACE_DEVICE=1 the executor
    brackets compile/execute in named TraceAnnotations so device time lands
    in the same Perfetto UI as the flight-recorder spans. Read once (the
    check sits on the jit dispatch path)."""
    global _device_trace
    if _device_trace is None:
        _device_trace = os.environ.get("IGLOO_TRACE_DEVICE", "0") == "1"
    return _device_trace


@contextlib.contextmanager
def device_annotation(name: str):
    """A named `jax.profiler.TraceAnnotation` around a block (no-op when the
    device bridge is off or the profiler is unavailable)."""
    if not device_trace_enabled():
        yield
        return
    import jax
    try:
        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        yield
        return
    with cm:
        yield


def last_trace(n: int = 2) -> str:
    """Render the `n` most recent root spans of this thread's trace."""
    r = list(roots())
    return "\n".join(s.tree() for s in r[-n:])
