"""Tracing / timing spans + process-wide counters.

The reference only has `tracing` calls in its cache crate with no subscriber ever
installed (SURVEY.md §5.1); here spans are real: nested timers recorded into a
thread-local trace that callers (CLI --explain-timing, coordinator per-fragment
metrics, bench harness) can read. Counters track cross-query events (compile
cache hits/misses, batch cache hits/evictions). `profile_trace()` wraps
`jax.profiler.trace` for device-level profiles.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import Counter
from dataclasses import dataclass, field

log = logging.getLogger("igloo_tpu")

_tls = threading.local()

_counters: Counter = Counter()
_counters_lock = threading.Lock()


def counter(name: str, delta: int = 1) -> None:
    """Bump a process-wide counter (thread-safe)."""
    with _counters_lock:
        _counters[name] += delta


def counters() -> dict:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a jax.profiler trace (TensorBoard format) around a block."""
    import jax
    with jax.profiler.trace(log_dir):
        yield


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    children: list = field(default_factory=list)

    @property
    def elapsed_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def tree(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.elapsed_s * 1e3:.2f}ms"]
        for c in self.children:
            lines.append(c.tree(indent + 1))
        return "\n".join(lines)


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
        _tls.roots = []
    return _tls.stack


def roots() -> list:
    _stack()
    return _tls.roots


def reset(counters_too: bool = False) -> None:
    """Clear the thread-local span trace. Counters are PROCESS-WIDE and
    CUMULATIVE and are NOT cleared by default — per-query deltas must be
    snapshot-diffed (c0 = counters(); ...; diff against c0), or pass
    counters_too=True in single-threaded tooling that owns the whole process
    (clearing them from one thread would corrupt other in-flight queries'
    metrics). Misreading cumulative counters as per-query deltas once cost an
    hour of phantom cache-bug hunting; hence this warning."""
    _tls.stack = []
    _tls.roots = []
    if counters_too:
        reset_counters()


@contextlib.contextmanager
def span(name: str):
    s = Span(name, time.perf_counter())
    stack = _stack()
    (stack[-1].children if stack else _tls.roots).append(s)
    stack.append(s)
    try:
        yield s
    finally:
        s.end = time.perf_counter()
        stack.pop()
        log.debug("span %s took %.3fms", name, s.elapsed_s * 1e3)


def last_trace() -> str:
    r = roots()
    return "\n".join(s.tree() for s in r[-2:])
