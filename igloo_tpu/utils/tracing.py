"""Tracing / timing spans + the process metrics registry.

The reference only has `tracing` calls in its cache crate with no subscriber
ever installed (SURVEY.md §5.1); here the layer is real and has three parts:

- spans: nested timers recorded into a thread-local trace that callers (CLI
  --timing, bench harness) can read. `roots()` is bounded (ROOTS_MAX) so
  long-lived processes — the coordinator in particular — don't leak spans.
- MetricsRegistry: process-wide counters AND histograms (query latency,
  compile time, transfer bytes, rows). Counters stay CUMULATIVE; per-query
  numbers come from `counter_delta()`, a thread-isolated snapshot-diff
  context manager, so concurrent queries can never pollute each other's
  deltas. `prometheus_text()` renders the registry for the cluster's
  `metrics` Flight action.
- `profile_trace()` wraps `jax.profiler.trace` for device-level profiles.

Every counter/histogram name used in the codebase is cataloged in
docs/observability.md; igloo-lint's metric-names checker (`python -m
igloo_tpu.lint`) fails the verify flow when the two drift.
"""
from __future__ import annotations

import contextlib
import logging
import re
import threading
import time
from collections import Counter, deque
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("igloo_tpu")

_tls = threading.local()

# spans kept per thread: enough for tooling that reads a few recent queries,
# bounded so a server thread answering queries for days cannot grow without
# limit (the coordinator used to leak its whole query history here)
ROOTS_MAX = 64

# lock discipline (checked by igloo-lint lock-discipline): the registry maps
# are hit from every thread; a CounterDelta's backing Counter is shared with
# adopted worker threads (the GRACE prefetch thread), so all `_data` access
# holds the module-wide _delta_lock
_GUARDED_BY = {"_lock": ("_counters", "_hists", "_gauges", "_version"),
               "_delta_lock": ("_data",)}


@dataclass
class HistogramData:
    """Streaming summary of one histogram: count/sum/min/max (no buckets —
    the consumers are per-query deltas and Prometheus summaries, neither of
    which needs quantiles badly enough to pay per-observation bucketing)."""
    count: int = 0
    sum: float = 0.0
    min: float = float("inf")
    max: float = float("-inf")

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def as_dict(self) -> dict:
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}


class MetricsRegistry:
    """Thread-safe process metrics: monotonic counters + summary histograms.

    `version()` is a mutation counter — the system.metrics table provider
    uses it as its snapshot token, so the engine's caches invalidate exactly
    when telemetry changed."""

    def __init__(self):
        self._counters: Counter = Counter()
        self._hists: dict[str, HistogramData] = {}
        self._gauges: dict[str, float] = {}
        self._lock = threading.Lock()
        self._version = 0

    def counter(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] += delta
            self._version += 1

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = HistogramData()
            h.observe(value)
            self._version += 1

    def gauge(self, name: str, value: float) -> None:
        """Set a last-value-wins gauge (queue depth, busy slots, reserved
        bytes — instantaneous state, unlike the monotonic counters)."""
        with self._lock:
            self._gauges[name] = float(value)
            self._version += 1

    def gauge_add(self, name: str, delta: float) -> float:
        """Atomically adjust a gauge by `delta`; returns the new value (the
        acquire/release call sites would otherwise read-modify-write race)."""
        with self._lock:
            v = self._gauges.get(name, 0.0) + delta
            self._gauges[name] = v
            self._version += 1
            return v

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def histograms(self) -> dict:
        with self._lock:
            return {k: h.as_dict() for k, h in self._hists.items()}

    def gauges(self) -> dict:
        with self._lock:
            return dict(self._gauges)

    def version(self) -> int:
        with self._lock:
            return self._version

    def bump_version(self) -> None:
        """External telemetry sources (the query log ring) share the
        registry's snapshot token by bumping it on their own mutations."""
        with self._lock:
            self._version += 1

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._gauges.clear()
            self._version += 1


REGISTRY = MetricsRegistry()


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def prometheus_text(prefix: str = "igloo", extra_lines: Optional[list] = None
                    ) -> str:
    """Render the registry in the Prometheus text exposition format.
    Counters become `<prefix>_<name>_total`; histograms a summary-style
    `_count`/`_sum` pair plus `_min`/`_max` gauges. `extra_lines` (already
    formatted) are appended — the coordinator adds its per-worker fragment
    aggregates there."""
    lines: list[str] = []
    for name, value in sorted(REGISTRY.counters().items()):
        m = f"{prefix}_{_sanitize(name)}_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {value}")
    for name, h in sorted(REGISTRY.histograms().items()):
        m = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count {h['count']}")
        lines.append(f"{m}_sum {h['sum']}")
        lines.append(f"{m}_min {h['min']}")
        lines.append(f"{m}_max {h['max']}")
    for name, v in sorted(REGISTRY.gauges().items()):
        m = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {v}")
    if extra_lines:
        lines.extend(extra_lines)
    return "\n".join(lines) + "\n"


# --- counters (module-level API, backed by REGISTRY) ------------------------


# guards collector Counters: a collector is thread-local by default, but
# `adopt_collectors` shares it with a worker thread (the GRACE prefetch
# thread), and `c[name] += d` is a non-atomic read-modify-write
_delta_lock = threading.Lock()


def counter(name: str, delta: int = 1) -> None:
    """Bump a process-wide counter (thread-safe). Any `counter_delta()`
    collectors active on the CURRENT thread accumulate the same bump, which
    is what keeps per-query deltas isolated across concurrent queries."""
    REGISTRY.counter(name, delta)
    cols = getattr(_tls, "collectors", None)
    if cols:
        with _delta_lock:
            for c in cols:
                c[name] += delta


def histogram(name: str, value: float) -> None:
    """Record one observation into a process-wide histogram."""
    REGISTRY.observe(name, value)


def gauge(name: str, value: float) -> None:
    """Set a process-wide gauge to an instantaneous value."""
    REGISTRY.gauge(name, value)


def gauge_add(name: str, delta: float) -> float:
    """Atomically adjust a process-wide gauge; returns the new value."""
    return REGISTRY.gauge_add(name, delta)


def counters() -> dict:
    return REGISTRY.counters()


def histograms() -> dict:
    return REGISTRY.histograms()


def gauges() -> dict:
    return REGISTRY.gauges()


def reset_counters() -> None:
    REGISTRY.reset()


class CounterDelta:
    """Live view of the counter bumps made on this thread (plus any adopted
    threads) since the enclosing `counter_delta()` opened. Readable both
    inside and after the `with` block."""

    def __init__(self, data: Counter):
        self._data = data

    def get(self, name: str, default: int = 0) -> int:
        with _delta_lock:
            return self._data.get(name, default)

    def values(self) -> dict:
        with _delta_lock:
            return {k: v for k, v in self._data.items() if v}

    def __getitem__(self, name: str) -> int:
        # same lock as get()/values(): the backing Counter may be mid-update
        # on an adopted worker thread (`c[name] += d` is not atomic)
        with _delta_lock:
            return self._data[name]

    def __contains__(self, name: str) -> bool:
        with _delta_lock:
            return name in self._data


@contextlib.contextmanager
def counter_delta():
    """Per-query counter deltas as a first-class API.

    Yields a CounterDelta that accumulates every `counter()` bump made on the
    current thread while the block is open — NOT a snapshot-diff of the
    process-wide totals, so two threads each inside their own
    `counter_delta()` observe only their own increments. Worker threads an
    operation fans out to (the GRACE prefetch thread) join via
    `adopt_collectors(capture_collectors())`.
    """
    c: Counter = Counter()
    cols = getattr(_tls, "collectors", None)
    if cols is None:
        cols = _tls.collectors = []
    cols.append(c)
    try:
        yield CounterDelta(c)
    finally:
        _remove_by_identity(cols, c)


def _remove_by_identity(cols: list, c) -> None:
    # Counter compares by CONTENT — list.remove would pop a different,
    # equal-content collector (two empty deltas are ==); remove by identity
    for i, x in enumerate(cols):
        if x is c:
            del cols[i]
            return


def capture_collectors() -> tuple:
    """Snapshot of the current thread's active delta collectors, for handing
    to a worker thread that does work on this query's behalf."""
    return tuple(getattr(_tls, "collectors", ()))


@contextlib.contextmanager
def adopt_collectors(cols: tuple):
    """Run a block on a worker thread with a parent thread's collectors
    installed, so its counter bumps land in the parent's deltas too."""
    own = getattr(_tls, "collectors", None)
    if own is None:
        own = _tls.collectors = []
    own.extend(cols)
    try:
        yield
    finally:
        for c in cols:
            _remove_by_identity(own, c)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a jax.profiler trace (TensorBoard format) around a block."""
    import jax
    with jax.profiler.trace(log_dir):
        yield


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    children: list = field(default_factory=list)

    @property
    def elapsed_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def tree(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.elapsed_s * 1e3:.2f}ms"]
        for c in self.children:
            lines.append(c.tree(indent + 1))
        return "\n".join(lines)


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
        _tls.roots = deque(maxlen=ROOTS_MAX)
    return _tls.stack


def roots() -> deque:
    _stack()
    return _tls.roots


def reset(counters_too: bool = False) -> None:
    """Clear the thread-local span trace. Counters are process-wide and
    cumulative; per-query numbers come from `counter_delta()`, which cannot
    be polluted by concurrent queries. Pass counters_too=True only in
    single-threaded tooling that owns the whole process."""
    _tls.stack = []
    _tls.roots = deque(maxlen=ROOTS_MAX)
    if counters_too:
        reset_counters()


@contextlib.contextmanager
def span(name: str):
    s = Span(name, time.perf_counter())
    stack = _stack()
    (stack[-1].children if stack else _tls.roots).append(s)
    stack.append(s)
    try:
        yield s
    finally:
        s.end = time.perf_counter()
        stack.pop()
        log.debug("span %s took %.3fms", name, s.elapsed_s * 1e3)


def last_trace(n: int = 2) -> str:
    """Render the `n` most recent root spans of this thread's trace."""
    r = list(roots())
    return "\n".join(s.tree() for s in r[-n:])
