"""Tracing / timing spans.

The reference only has `tracing` calls in its cache crate with no subscriber ever
installed (SURVEY.md §5.1); here spans are real: nested timers recorded into a
thread-local trace that callers (CLI --explain-timing, coordinator per-fragment
metrics, bench harness) can read. Integrates with `jax.profiler` when enabled.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field

log = logging.getLogger("igloo_tpu")

_tls = threading.local()


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    children: list = field(default_factory=list)

    @property
    def elapsed_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def tree(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.elapsed_s * 1e3:.2f}ms"]
        for c in self.children:
            lines.append(c.tree(indent + 1))
        return "\n".join(lines)


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
        _tls.roots = []
    return _tls.stack


def roots() -> list:
    _stack()
    return _tls.roots


def reset() -> None:
    _tls.stack = []
    _tls.roots = []


@contextlib.contextmanager
def span(name: str):
    s = Span(name, time.perf_counter())
    stack = _stack()
    (stack[-1].children if stack else _tls.roots).append(s)
    stack.append(s)
    try:
        yield s
    finally:
        s.end = time.perf_counter()
        stack.pop()
        log.debug("span %s took %.3fms", name, s.elapsed_s * 1e3)


def last_trace() -> str:
    r = roots()
    return "\n".join(s.tree() for s in r[-2:])
