"""Watchtower anomaly detector: per-fingerprint baselines and slow-query
escalation (docs/observability.md#watchtower).

At query end the engine (local tiers) and the coordinator (distributed
path) call `check_query()` with the query's `plan_fp` structural
fingerprint and observed cost. The detector compares wall seconds and
exchange bytes against the fingerprint's OWN rolling P99 (BaselineStats,
exec/hints.py — the AdaptiveStats JSON-store idiom): a query beyond
`IGLOO_WATCH_SLOW_FACTOR` x P99 (default 3x), judged WARM-ONLY (at least
`MIN_OBSERVATIONS` prior runs of the same fingerprint), escalates:

- one row in the bounded `system.slow_queries` ring — fingerprint digest,
  observed vs baseline, trace_id, and the dominant phase attributed from
  the QueryStats operator tree;
- the query's trace is PINNED in the flight recorder
  (flight_recorder.pin) so the evidence survives ring eviction;
- a `slow_query` event in the cluster journal (cluster/events.py);
- a JSONL line in `$IGLOO_TRACE_DIR/slow_queries.jsonl`.

Escalation fires at most once per qid (a bounded seen-set — retries and
double-reporting paths cannot duplicate a row). The observation is folded
into the baseline AFTER the comparison, so a query is always judged
against history that does not include itself. `IGLOO_WATCH=0`
(utils/timeseries.enabled) turns `check_query` into a no-op: no store
writes, no counters — bit-identical to a build without the watchtower.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

from igloo_tpu.utils import flight_recorder, timeseries, tracing

SLOW_FACTOR_ENV = "IGLOO_WATCH_SLOW_FACTOR"

#: warm-only gate: a fingerprint needs this many prior observations before
#: its P99 is a baseline worth escalating against
MIN_OBSERVATIONS = 5

_lock = threading.Lock()
_GUARDED_BY = {"_lock": ("_slow", "_escalated")}
_slow: deque = deque(maxlen=timeseries.history())
_escalated: deque = deque(maxlen=1024)   # qids already escalated (FIFO set)


def slow_factor() -> float:
    return float(os.environ.get("IGLOO_WATCH_SLOW_FACTOR", "3"))


def _dominant_phase(qs) -> str:
    """Attribute the anomaly: 'compile' when (re)compilation dominated the
    wall, else the widest operator in the QueryStats tree."""
    if qs is None:
        return ""
    try:
        if qs.compile_s and qs.compile_s >= 0.5 * max(qs.elapsed_s, 1e-9):
            return "compile"
        best, best_wall = "", 0.0
        for op in qs.ops():
            if op.wall_s > best_wall:
                best, best_wall = op.name, op.wall_s
        return best or "execute"
    except Exception:
        return ""


def _export(rec: dict) -> None:
    out_dir = os.environ.get("IGLOO_TRACE_DIR")
    if not out_dir:
        return
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "slow_queries.jsonl"), "a",
                  encoding="utf-8") as fh:
            fh.write(json.dumps(rec, default=str) + "\n")
    except OSError:
        tracing.counter("watch.export_failed")


def check_query(fp, wall_s: float, *, exchange_bytes: float = 0.0,
                hbm_bytes: float = 0.0, qs=None, qid: str = "",
                trace_id: str = "", sql: str = "",
                tier: str = "", phase: str = "") -> Optional[dict]:
    """Judge one finished query against its fingerprint's baseline, then
    fold the observation in. Returns the slow-query record when escalated,
    else None. Cheap by contract: a dict lookup, one sort of a <=64-entry
    window, and a deque append — it sits on every query's exit path."""
    if fp is None or not timeseries.enabled():
        return None
    from igloo_tpu.exec import hints
    store = hints.watch_store()
    base = store.baseline(fp)
    record = None
    if base["count"] >= MIN_OBSERVATIONS:
        factor = slow_factor()
        wall_thr = base["wall_s_p99"] * factor
        bytes_thr = base["exchange_bytes_p99"] * factor
        slow_wall = wall_thr > 0 and wall_s > wall_thr
        slow_bytes = bytes_thr > 0 and exchange_bytes > bytes_thr
        if slow_wall or slow_bytes:
            record = self_rec = {
                "ts": time.time(),
                "qid": str(qid or ""),
                "trace_id": str(trace_id or ""),
                "fingerprint": hints.digest_key(fp),
                "observed_s": float(wall_s),
                "baseline_p99_s": base["wall_s_p99"],
                "observed_bytes": float(exchange_bytes),
                "baseline_p99_bytes": base["exchange_bytes_p99"],
                "factor": (wall_s / base["wall_s_p99"]
                           if base["wall_s_p99"] > 0 else 0.0),
                "dominant_phase": phase or _dominant_phase(qs),
                "tier": tier or (qs.tier if qs is not None else ""),
                "sql": (sql or (qs.sql if qs is not None else ""))[:200],
            }
            with _lock:
                if self_rec["qid"] and self_rec["qid"] in _escalated:
                    record = None   # once per query, ever
                else:
                    if self_rec["qid"]:
                        _escalated.append(self_rec["qid"])
                    _slow.append(self_rec)
            if record is not None:
                tracing.counter("watch.slow_queries")
                tracing.REGISTRY.bump_version()
                if record["trace_id"] or record["qid"]:
                    flight_recorder.pin(trace_id=record["trace_id"] or None,
                                        qid=record["qid"] or None)
                from igloo_tpu.cluster import events
                events.emit("slow_query", severity="warn",
                            qid=record["qid"], trace_id=record["trace_id"],
                            factor=round(record["factor"], 2),
                            dominant_phase=record["dominant_phase"])
                _export(record)
    # fold AFTER judging: the baseline a query is compared against never
    # includes the query itself
    store.observe(fp, wall_s=wall_s,
                  hbm_bytes=hbm_bytes or None,
                  exchange_bytes=exchange_bytes or None)
    return record


def slow_queries() -> list:
    """Escalation records, oldest first (the system.slow_queries rows)."""
    with _lock:
        return list(_slow)


def clear() -> None:
    """Tests only: drop escalation state and re-bound the ring."""
    global _slow
    with _lock:
        _slow = deque(maxlen=timeseries.history())
        _escalated.clear()
