"""Per-query telemetry: the operator stats tree and the query log.

Where utils/tracing.py holds PROCESS-wide metrics, this module holds
PER-QUERY ones: `QueryStats` carries an operator tree mirroring the physical
execution (rows in/out, wall time, compile-vs-execute split, transfer bytes,
cache hits) plus query-level totals and the per-query counter delta. The
engine opens one with `collect()` around `_execute_plan`; every executor tier
(staged / fused / chunked / GRACE / host) records into the thread-local
current stats through the tiny hooks below, each a no-op costing one
thread-local read when no query is being collected.

Two collection levels keep the hot path honest:

- default (engine.execute / the bench sweep): wall times, free row counts
  (host Arrow / numpy shapes), transfer bytes and counter deltas — NO device
  syncs are added, so overhead is a few microseconds per operator;
- detail (EXPLAIN ANALYZE): per-operator ACTUAL row counts, which on the
  device tier cost one `num_live()` sync per blocking operator, and the
  fused whole-plan program is routed to the staged executor so operator
  boundaries exist to observe (docs/observability.md#explain-analyze).

Finished stats land in a process-wide ring (`query_log()`, the backing store
of the `system.query_log` table) and, when IGLOO_QUERY_LOG=path is set, are
appended to that file as JSON lines.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from igloo_tpu.utils import flight_recorder, tracing

_tls = threading.local()

# guards QueryStats/OpStats numeric fields: normally single-threaded, but a
# worker thread under `adopt()` (the GRACE prefetch thread) records into the
# SAME QueryStats/node as the query thread, and `x += n` is a non-atomic
# read-modify-write
_totals_lock = threading.Lock()

# ring of recent finished QueryStats, process-wide (a coordinator process
# logs every query it executed, whichever engine/executor ran it)
QUERY_LOG_SIZE = int(os.environ.get("IGLOO_QUERY_LOG_SIZE", "256"))
_log_lock = threading.Lock()
_query_log: deque = deque(maxlen=QUERY_LOG_SIZE)
_query_seq = 0


@dataclass
class OpStats:
    """One physical operator's recorded execution."""
    name: str
    wall_s: float = 0.0
    compile_s: float = 0.0
    rows_out: Optional[int] = None
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    attrs: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


@dataclass
class QueryStats:
    """Per-query telemetry: totals + the operator tree."""
    sql: str = ""
    started_at: float = 0.0            # unix seconds
    elapsed_s: float = 0.0
    tier: str = "device"               # host|chunked|grace|device|sharded|...
    rows: Optional[int] = None
    compile_s: float = 0.0
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    counters: dict = field(default_factory=dict)  # per-query counter delta
    root: Optional[OpStats] = None
    detail: bool = False
    qid: int = 0
    # terminal outcome: "ok" | "cancelled" | "deadline_exceeded" | "error"
    # (non-ok values come from the distributed tier's deadline/cancel paths)
    status: str = "ok"
    # serving-path fields (coordinator front door, docs/serving.md): how long
    # the query waited in the admission queue, its priority tier, and how
    # many rungs of the degradation ladder it was demoted down (0 = ran at
    # its planned tier)
    queue_wait_s: float = 0.0
    priority: int = 1
    demoted: int = 0
    # flight-recorder trace identity (utils/flight_recorder.py): the key
    # that joins this row with system.query_traces and the Perfetto export
    # ("" when the recorder was off)
    trace_id: str = ""
    # (fingerprint key, observed rows) pairs recorded where a row count was
    # free or already paid for (host tier, detail-mode syncs, first-sight
    # adaptive-input syncs); the engine folds them into the process-wide
    # AdaptiveStats store at query end (exec/hints.py, docs/adaptive.md)
    observations: list = field(default_factory=list)

    # --- programmatic access ------------------------------------------------

    def ops(self):
        """Iterate every operator node (pre-order)."""
        if self.root is not None:
            yield from self.root.walk()

    def find_ops(self, prefix: str) -> list:
        return [o for o in self.ops() if o.name.startswith(prefix)]

    @property
    def execute_s(self) -> float:
        """Wall time minus (first-call) compile time: the steady-state cost."""
        return max(self.elapsed_s - self.compile_s, 0.0)

    def to_record(self) -> dict:
        """Flat dict for the query log (system.query_log row / JSONL line)."""
        return {
            "qid": self.qid,
            "ts": round(self.started_at, 6),
            "sql": self.sql,
            "tier": self.tier,
            "rows": -1 if self.rows is None else int(self.rows),
            "elapsed_s": round(self.elapsed_s, 6),
            "compile_s": round(self.compile_s, 6),
            "execute_s": round(self.execute_s, 6),
            "h2d_bytes": int(self.h2d_bytes),
            "d2h_bytes": int(self.d2h_bytes),
            "operators": sum(1 for _ in self.ops()),
            "grace_partitions": int(
                self.counters.get("grace.partitions", 0)),
            "jit_misses": int(self.counters.get("jit.miss", 0)),
            "cache_hits": int(self.counters.get("cache.hit", 0) +
                              self.counters.get("result_cache.hit", 0)),
            "status": self.status,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "priority": int(self.priority),
            "demoted": int(self.demoted),
            "trace_id": self.trace_id,
        }


# --- collection context -----------------------------------------------------


def current() -> Optional[QueryStats]:
    return getattr(_tls, "qstats", None)


def detail_active() -> bool:
    qs = getattr(_tls, "qstats", None)
    return qs is not None and qs.detail


@contextlib.contextmanager
def collect(sql: str = "", detail: bool = False, log: bool = True):
    """Open a QueryStats collection around a query execution. Nested collects
    are ignored (the outer query owns the tree — scalar subqueries and
    re-runs record into it)."""
    if getattr(_tls, "qstats", None) is not None:
        yield _tls.qstats
        return
    global _query_seq
    with _log_lock:
        _query_seq += 1
        qid = _query_seq
    qs = QueryStats(sql=sql, started_at=time.time(), detail=detail, qid=qid)
    # flight-recorder hookup (utils/flight_recorder.py): an ambient trace (a
    # coordinator request scope around this execution) is joined; otherwise
    # a standalone engine opens — and at the end publishes — its own, with
    # keep_roots so same-thread span consumers (CLI --timing) still work
    trace = flight_recorder.current()
    own_scope = None
    if trace is None and flight_recorder.enabled():
        trace = flight_recorder.Trace(qid=qid, sql=sql)
        own_scope = flight_recorder.request_scope(trace, "query",
                                                  keep_roots=True)
        own_scope.__enter__()
    if trace is not None:
        qs.trace_id = trace.trace_id
    root = OpStats("Query")
    qs.root = root
    _tls.qstats = qs
    _tls.opstack = [root]
    t0 = time.perf_counter()
    try:
        with tracing.counter_delta() as delta:
            yield qs
    finally:
        qs.elapsed_s = time.perf_counter() - t0
        qs.counters = delta.values()
        # an artificial root with a single child is noise — promote the child
        if len(root.children) == 1 and not root.attrs:
            qs.root = root.children[0]
        # serving-path context (admission wait / priority / demotions) set by
        # the coordinator front door around an in-process engine execution
        sv = getattr(_tls, "serving", None)
        if sv is not None:
            qs.queue_wait_s = sv.get("queue_wait_s", 0.0)
            qs.priority = sv.get("priority", 1)
            qs.demoted = sv.get("demoted", 0)
        _tls.qstats = None
        _tls.opstack = None
        if own_scope is not None:
            own_scope.__exit__(None, None, None)
            flight_recorder.publish(trace)
        if log:
            _append_log(qs)


class _NullOp:
    """Fast no-op `op()` result when no collection is active."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_OP = _NullOp()


class _Op:
    __slots__ = ("node",)

    def __init__(self, node: OpStats):
        self.node = node

    def __enter__(self) -> OpStats:
        _tls.opstack.append(self.node)
        self.node.wall_s = time.perf_counter()
        return self.node

    def __exit__(self, *exc):
        self.node.wall_s = time.perf_counter() - self.node.wall_s
        _tls.opstack.pop()
        return False


def op(name: str, **attrs):
    """Record one operator: `with stats.op("Join(...)"): ...`. Children
    recorded inside nest under it. Returns the OpStats (or None inactive)."""
    qs = getattr(_tls, "qstats", None)
    if qs is None or getattr(_tls, "quiet", 0):
        return _NULL_OP
    node = OpStats(name, attrs=dict(attrs) if attrs else {})
    _tls.opstack[-1].children.append(node)
    return _Op(node)


def op_label(plan, limit: int = 72) -> str:
    """Operator display label for the stats tree: the plan node's name,
    truncated (node_name() embeds full expression reprs)."""
    s = plan.node_name()
    return s if len(s) <= limit else s[: limit - 3] + "..."


def plan_op(plan):
    """`op()` for a plan node — the label (a string build over expression
    reprs) is only computed when a query is actually being recorded, so
    paths with no collection open (cluster fragments) pay one tls read."""
    qs = getattr(_tls, "qstats", None)
    if qs is None or getattr(_tls, "quiet", 0):
        return _NULL_OP
    node = OpStats(op_label(plan))
    _tls.opstack[-1].children.append(node)
    return _Op(node)


@contextlib.contextmanager
def quiet():
    """Suppress op-node creation (totals still accumulate): the GRACE loop
    uses this past the first few partitions so a 1024-partition query does
    not materialize 1024 subtrees — their numbers land in the rollup."""
    _tls.quiet = getattr(_tls, "quiet", 0) + 1
    try:
        yield
    finally:
        _tls.quiet -= 1


def current_op() -> Optional[OpStats]:
    stack = getattr(_tls, "opstack", None)
    return stack[-1] if stack else None


def set_rows(n: int) -> None:
    node = current_op()
    if node is not None:
        node.rows_out = int(n)


def annotate(**attrs) -> None:
    node = current_op()
    if node is not None:
        node.attrs.update(attrs)


def bump_attr(key: str, delta: int = 1) -> None:
    """Increment an integer attr on the current op (per-op hit/miss tallies)."""
    node = current_op()
    if node is not None:
        with _totals_lock:
            node.attrs[key] = node.attrs.get(key, 0) + delta


def observe_card(key, rows: int) -> None:
    """Record one observed subtree cardinality for the adaptive feedback
    loop. Callers only invoke this where the count is already in hand (free
    host/Arrow shapes, a sync another feature paid for) — the hook itself
    must never add device syncs."""
    qs = getattr(_tls, "qstats", None)
    if qs is None:
        return
    with _totals_lock:
        qs.observations.append((key, int(rows)))


def record_compile(seconds: float) -> None:
    qs = getattr(_tls, "qstats", None)
    if qs is None:
        return
    node = current_op()
    with _totals_lock:
        qs.compile_s += seconds
        if node is not None:
            node.compile_s += seconds


def add_transfer(h2d: int = 0, d2h: int = 0) -> None:
    qs = getattr(_tls, "qstats", None)
    if qs is None:
        return
    node = current_op()
    with _totals_lock:
        qs.h2d_bytes += h2d
        qs.d2h_bytes += d2h
        if node is not None:
            node.h2d_bytes += h2d
            node.d2h_bytes += d2h


def host_nbytes(obj) -> int:
    """Total bytes of a nested structure of host arrays (the shape
    `jax.device_get` returns: lists/tuples/dicts of ndarrays + scalars)."""
    if obj is None:
        return 0
    if isinstance(obj, (list, tuple)):
        return sum(host_nbytes(o) for o in obj)
    if isinstance(obj, dict):
        return sum(host_nbytes(o) for o in obj.values())
    nb = getattr(obj, "nbytes", None)
    return int(nb) if nb is not None else 0


def record_fetch(host_objs) -> int:
    """Book one device->host fetch (process counter + current query);
    returns the byte total."""
    n = host_nbytes(host_objs)
    if n:
        tracing.counter("xfer.d2h_bytes", n)
        add_transfer(d2h=n)
    return n


def record_upload(nbytes: int) -> None:
    """Book one host->device upload (process counter + current query)."""
    if nbytes:
        tracing.counter("xfer.h2d_bytes", nbytes)
        add_transfer(h2d=nbytes)


def device_peak_hbm_bytes() -> int:
    """Peak device-memory watermark across local devices (0 when the backend
    reports no memory stats — CPU). Process-cumulative, so per-query use of
    it is an UPPER bound; the admission gate wants conservative."""
    try:
        import jax
        peaks = []
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            ms = ms() if callable(ms) else None
            if ms:
                peaks.append(ms.get("peak_bytes_in_use",
                                    ms.get("bytes_in_use", 0)))
        return int(max(peaks)) if peaks else 0
    except Exception:
        return 0


# --- serving context ---------------------------------------------------------


@contextlib.contextmanager
def serving_context(queue_wait_s: float = 0.0, priority: int = 1):
    """Attribute serving-path facts (admission wait, priority tier, ladder
    demotions via `mark_demoted`) to every query-log record the wrapped
    in-process execution produces on this thread — the coordinator's LOCAL
    fallback/demotion paths run through `engine.execute`, whose `collect()`
    has no other way to learn them."""
    prev = getattr(_tls, "serving", None)
    _tls.serving = {"queue_wait_s": float(queue_wait_s),
                    "priority": int(priority), "demoted": 0}
    try:
        yield _tls.serving
    finally:
        _tls.serving = prev


def mark_demoted() -> None:
    """Count one degradation-ladder demotion for the current serving context
    (no-op outside one)."""
    sv = getattr(_tls, "serving", None)
    if sv is not None:
        sv["demoted"] = sv.get("demoted", 0) + 1


# --- cross-thread propagation ----------------------------------------------


def capture() -> tuple:
    """Snapshot (qstats, opstack top, collectors, trace context) for a
    worker thread doing this query's work (GRACE prefetch): its transfers/
    counters land in the right query's totals and its spans in the right
    query's trace (where they visibly overlap the spawning thread's)."""
    return (getattr(_tls, "qstats", None), current_op(),
            tracing.capture_collectors(), flight_recorder.capture())


@contextlib.contextmanager
def adopt(ctx: tuple):
    qs, node, cols, tctx = ctx
    if qs is None:
        # no stats collection, but the parent thread may still hold
        # counter_delta collectors (bench sweep) — adopt those regardless
        with flight_recorder.adopt(tctx), tracing.adopt_collectors(cols):
            yield
        return
    _tls.qstats = qs
    _tls.opstack = [node if node is not None else qs.root]
    _tls.quiet = 1  # worker threads contribute totals, not tree nodes
    try:
        with flight_recorder.adopt(tctx), tracing.adopt_collectors(cols):
            yield
    finally:
        _tls.qstats = None
        _tls.opstack = None
        _tls.quiet = 0


# --- query log --------------------------------------------------------------


def _append_log(qs: QueryStats) -> None:
    # process-wide query histograms (system.metrics / Prometheus summaries)
    tracing.histogram("query.latency_s", qs.elapsed_s)
    if qs.compile_s:
        tracing.histogram("query.compile_s", qs.compile_s)
    if qs.rows is not None:
        tracing.histogram("query.rows", qs.rows)
    if qs.h2d_bytes:
        tracing.histogram("query.h2d_bytes", qs.h2d_bytes)
    if qs.d2h_bytes:
        tracing.histogram("query.d2h_bytes", qs.d2h_bytes)
    with _log_lock:
        _query_log.append(qs)
    tracing.REGISTRY.bump_version()  # system tables snapshot on this
    path = os.environ.get("IGLOO_QUERY_LOG")
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(qs.to_record(), default=str) + "\n")
        except OSError:  # export is best-effort; never fail the query
            tracing.counter("stats.query_log_write_failed")


def log_query(sql: str, elapsed_s: float, tier: str = "distributed",
              rows: Optional[int] = None, status: str = "ok",
              started_at: Optional[float] = None,
              queue_wait_s: float = 0.0, priority: int = 1,
              demoted: int = 0, trace_id: str = "") -> QueryStats:
    """Append a query-log record for a query NOT executed through
    `collect()` — the coordinator's distributed path logs every query here,
    including cancelled / deadline-exceeded / shed ones that never finished
    (their `status` column is how an operator audits what the cluster
    dropped)."""
    global _query_seq
    with _log_lock:
        _query_seq += 1
        qid = _query_seq
    qs = QueryStats(sql=sql, elapsed_s=elapsed_s, tier=tier, rows=rows,
                    status=status, qid=qid,
                    started_at=started_at if started_at is not None
                    else time.time() - elapsed_s,
                    queue_wait_s=queue_wait_s, priority=priority,
                    demoted=demoted, trace_id=trace_id)
    _append_log(qs)
    return qs


def query_log() -> list:
    """Most-recent-last list of finished QueryStats."""
    with _log_lock:
        return list(_query_log)


def clear_query_log() -> None:
    with _log_lock:
        _query_log.clear()
    tracing.REGISTRY.bump_version()


# --- rendering --------------------------------------------------------------


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"  # pragma: no cover - loop always returns


def _fmt_op(o: OpStats) -> str:
    parts = [f"{o.name}:"]
    if o.rows_out is not None:
        parts.append(f"rows={o.rows_out}")
    parts.append(f"wall={o.wall_s * 1e3:.2f}ms")
    if o.compile_s:
        parts.append(f"compile={o.compile_s * 1e3:.1f}ms "
                     f"exec={(o.wall_s - o.compile_s) * 1e3:.2f}ms")
    if o.h2d_bytes:
        parts.append(f"h2d={_fmt_bytes(o.h2d_bytes)}")
    if o.d2h_bytes:
        parts.append(f"d2h={_fmt_bytes(o.d2h_bytes)}")
    for k, v in o.attrs.items():
        parts.append(f"{k}={v}")
    return " ".join(parts)


def render_tree(qs: QueryStats) -> str:
    """EXPLAIN ANALYZE / --timing rendering of the operator tree."""
    head = (f"tier={qs.tier} elapsed={qs.elapsed_s:.4f}s "
            f"compile={qs.compile_s:.4f}s execute={qs.execute_s:.4f}s")
    if qs.rows is not None:
        head += f" rows={qs.rows}"
    if qs.h2d_bytes or qs.d2h_bytes:
        head += (f" h2d={_fmt_bytes(qs.h2d_bytes)}"
                 f" d2h={_fmt_bytes(qs.d2h_bytes)}")
    lines = [head]

    def rec(o: OpStats, indent: int):
        lines.append("  " * indent + _fmt_op(o))
        for c in o.children:
            rec(c, indent + 1)

    if qs.root is not None:
        rec(qs.root, 0)
    return "\n".join(lines)
