"""Recursive-descent SQL parser.

Own frontend replacing the reference's sqlparser-rs shim (crates/engine/src/parser.rs:7-12)
and DataFusion's SQL planner on the working path (crates/engine/src/lib.rs:54-57).
Parses the dialect needed for TPC-H and the reference's demo queries: SELECT blocks
with CTEs, joins, subqueries (scalar / IN / EXISTS), set operations, aggregates,
CASE/CAST/EXTRACT/INTERVAL/BETWEEN/LIKE, plus a few utility statements
(EXPLAIN, SHOW TABLES, DESCRIBE, CREATE TABLE AS, DROP TABLE).

Mirrors the reference's single-statement semantics: `parse_sql` returns the LAST
statement when several are separated by ';' (crates/engine/src/parser.rs:10-11).
"""
from __future__ import annotations

import datetime as _dt
from typing import Optional

from igloo_tpu import types as T
from igloo_tpu.errors import SqlParseError
from igloo_tpu.plan import expr as E
from igloo_tpu.sql import ast as A
from igloo_tpu.sql.lexer import Tok, Token, line_col, tokenize

_EPOCH = _dt.date(1970, 1, 1).toordinal()


_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "INTERSECT", "EXCEPT", "ON", "USING", "JOIN", "INNER", "LEFT", "RIGHT",
    "FULL", "CROSS", "OUTER", "AS", "AND", "OR", "NOT", "WHEN", "THEN", "ELSE",
    "END", "BY", "ASC", "DESC", "NULLS", "FIRST", "LAST", "SELECT", "DISTINCT",
    "ALL", "WITH", "CASE", "BETWEEN", "IN", "IS", "LIKE", "ILIKE", "EXISTS",
    "NULL", "TRUE", "FALSE", "CAST", "INTERVAL", "EXTRACT", "VALUES", "SEMI",
    "ANTI", "NATURAL",
}

_TYPE_NAMES = {
    "INT": T.INT32, "INTEGER": T.INT32, "SMALLINT": T.INT32, "TINYINT": T.INT32,
    "BIGINT": T.INT64, "LONG": T.INT64,
    "FLOAT": T.FLOAT32, "REAL": T.FLOAT32,
    "DOUBLE": T.FLOAT64, "DECIMAL": T.FLOAT64, "NUMERIC": T.FLOAT64,
    "VARCHAR": T.STRING, "CHAR": T.STRING, "TEXT": T.STRING, "STRING": T.STRING,
    "DATE": T.DATE32, "TIMESTAMP": T.TIMESTAMP, "DATETIME": T.TIMESTAMP,
    "BOOLEAN": T.BOOL, "BOOL": T.BOOL,
}


def parse_sql(sql: str) -> object:
    """Parse `sql`; if multiple ';'-separated statements, return the last (parity with
    reference parser.rs:10-11)."""
    stmts = parse_statements(sql)
    if not stmts:
        raise SqlParseError("empty SQL input")
    return stmts[-1]


def parse_statements(sql: str) -> list[object]:
    p = Parser(tokenize(sql), sql)
    out = []
    while not p.at(Tok.EOF):
        if p.try_op(";"):
            continue
        out.append(p.parse_statement())
    return out


class Parser:
    def __init__(self, toks: list[Token], sql: str):
        self.toks = toks
        self.sql = sql
        self.i = 0

    # --- token helpers ---

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def at(self, kind: Tok) -> bool:
        return self.peek().kind == kind

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == Tok.IDENT and t.upper() in kws

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != Tok.EOF:
            self.i += 1
        return t

    def try_kw(self, *kws: str) -> Optional[str]:
        if self.at_kw(*kws):
            return self.next().upper()
        return None

    def expect_kw(self, kw: str):
        if not self.try_kw(kw):
            self.err(f"expected {kw}")

    def try_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == Tok.OP and t.text == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.try_op(op):
            self.err(f"expected '{op}'")

    def err(self, msg: str):
        t = self.peek()
        line, col = line_col(self.sql, t.pos)
        got = t.text if t.kind != Tok.EOF else "<end of input>"
        raise SqlParseError(f"{msg}, got {got!r} at line {line}, column {col}")

    def ident(self, what: str = "identifier") -> str:
        t = self.peek()
        if t.kind == Tok.QIDENT:
            self.next()
            return t.text
        if t.kind == Tok.IDENT:
            if t.upper() in _RESERVED_STOP:
                self.err(f"expected {what}")
            self.next()
            return t.text.lower()
        self.err(f"expected {what}")

    # --- statements ---

    def parse_statement(self) -> object:
        if self.at_kw("EXPLAIN"):
            self.next()
            analyze = self.try_kw("ANALYZE") is not None
            return A.ExplainStmt(query=self.parse_query(), analyze=analyze)
        if self.at_kw("SHOW"):
            self.next()
            self.expect_kw("TABLES")
            return A.ShowTablesStmt()
        if self.at_kw("DESCRIBE", "DESC"):
            self.next()
            return A.DescribeStmt(table=self.ident("table name"))
        if self.at_kw("CREATE"):
            self.next()
            self.expect_kw("TABLE")
            name = self.ident("table name")
            self.expect_kw("AS")
            return A.CreateTableAsStmt(name=name, query=self.parse_query())
        if self.at_kw("DROP"):
            self.next()
            self.expect_kw("TABLE")
            if_exists = False
            if self.try_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return A.DropTableStmt(name=self.ident("table name"), if_exists=if_exists)
        return self.parse_query()

    # --- queries ---

    def parse_query(self) -> A.SelectStmt:
        ctes: list[tuple[str, A.SelectStmt]] = []
        if self.try_kw("WITH"):
            while True:
                name = self.ident("CTE name")
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, q))
                if not self.try_op(","):
                    break
        stmt = self.parse_set_expr()
        # trailing ORDER BY / LIMIT apply to the whole set expression; if the inner
        # statement already carries its own (e.g. "(SELECT ... LIMIT 5) LIMIT 3"),
        # wrap it as a derived table so both layers apply in order
        order_by, limit, offset = self.parse_order_limit()
        if (order_by or limit is not None or offset is not None) and (
            stmt.order_by or stmt.limit is not None or stmt.offset is not None
        ):
            inner = stmt
            dt = A.DerivedTable(query=inner)
            dt.alias = "_q"
            stmt = A.SelectStmt(projections=[E.Star()], from_=dt)
        if order_by:
            stmt.order_by = order_by
        if limit is not None:
            stmt.limit = limit
        if offset is not None:
            stmt.offset = offset
        stmt.ctes = ctes + stmt.ctes
        return stmt

    def _int_tok(self, what: str) -> int:
        t = self.next()
        if t.kind != Tok.NUMBER or not t.text.lstrip("+-").isdigit():
            self.i -= 1
            self.err(f"expected integer {what}")
        return int(t.text)

    def parse_order_limit(self):
        order_by: list[A.OrderItem] = []
        limit = offset = None
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                ex = self.parse_expr()
                asc = True
                if self.try_kw("ASC"):
                    asc = True
                elif self.try_kw("DESC"):
                    asc = False
                nulls_first = None
                if self.try_kw("NULLS"):
                    if self.try_kw("FIRST"):
                        nulls_first = True
                    else:
                        self.expect_kw("LAST")
                        nulls_first = False
                order_by.append(A.OrderItem(ex, asc, nulls_first))
                if not self.try_op(","):
                    break
        if self.try_kw("LIMIT"):
            limit = self._int_tok("LIMIT count")
        if self.try_kw("OFFSET"):
            offset = self._int_tok("OFFSET count")
            self.try_kw("ROWS", "ROW")
        return order_by, limit, offset

    def parse_set_expr(self) -> A.SelectStmt:
        # standard SQL: INTERSECT binds tighter than UNION/EXCEPT
        left = self.parse_intersect_expr()
        while True:
            if self.try_kw("UNION"):
                all_ = self.try_kw("ALL") is not None
                self.try_kw("DISTINCT")
                right = self.parse_intersect_expr()
                op = A.SetOp.UNION_ALL if all_ else A.SetOp.UNION
                left = A.SelectStmt(set_op=op, left=left, right=right)
            elif self.try_kw("EXCEPT"):
                self.try_kw("DISTINCT")
                right = self.parse_intersect_expr()
                left = A.SelectStmt(set_op=A.SetOp.EXCEPT, left=left, right=right)
            else:
                return left

    def parse_intersect_expr(self) -> A.SelectStmt:
        left = self.parse_select_core()
        while self.try_kw("INTERSECT"):
            self.try_kw("DISTINCT")
            right = self.parse_select_core()
            left = A.SelectStmt(set_op=A.SetOp.INTERSECT, left=left, right=right)
        return left

    def parse_select_core(self) -> A.SelectStmt:
        if self.try_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        if self.at_kw("VALUES"):
            self.next()
            rows = self.parse_values_rows()
            vt = A.ValuesTable(rows=rows)
            vt.alias = "values"
            cols = [E.Column(f"column{i + 1}") for i in range(len(rows[0]) if rows else 0)]
            return A.SelectStmt(projections=cols, from_=vt)
        self.expect_kw("SELECT")
        distinct = False
        if self.try_kw("DISTINCT"):
            distinct = True
        else:
            self.try_kw("ALL")
        projections = [self.parse_select_item()]
        while self.try_op(","):
            projections.append(self.parse_select_item())
        from_ = None
        if self.try_kw("FROM"):
            from_ = self.parse_from()
        where = None
        if self.try_kw("WHERE"):
            where = self.parse_expr()
        group_by: list[E.Expr] = []
        if self.try_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_expr())
            while self.try_op(","):
                group_by.append(self.parse_expr())
        having = None
        if self.try_kw("HAVING"):
            having = self.parse_expr()
        return A.SelectStmt(projections=projections, distinct=distinct, from_=from_,
                            where=where, group_by=group_by, having=having)

    def parse_values_rows(self) -> list[list[E.Expr]]:
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_expr()]
            while self.try_op(","):
                row.append(self.parse_expr())
            self.expect_op(")")
            rows.append(row)
            if not self.try_op(","):
                return rows

    def parse_select_item(self) -> E.Expr:
        if self.try_op("*"):
            return E.Star()
        # qualified star: ident.*
        if self.peek().kind in (Tok.IDENT, Tok.QIDENT) and \
           self.peek(1).kind == Tok.OP and self.peek(1).text == "." and \
           self.peek(2).kind == Tok.OP and self.peek(2).text == "*" and \
           (self.peek().kind == Tok.QIDENT or self.peek().upper() not in _RESERVED_STOP):
            q = self.ident()
            self.next()  # .
            self.next()  # *
            return E.Star(qualifier=q)
        ex = self.parse_expr()
        if self.try_kw("AS"):
            alias = self.ident_or_kw("alias")
            return E.Alias(operand=ex, alias=alias)
        # bare alias (identifier not a clause keyword)
        t = self.peek()
        if t.kind == Tok.QIDENT or (t.kind == Tok.IDENT and t.upper() not in _RESERVED_STOP):
            return E.Alias(operand=ex, alias=self.ident())
        return ex

    def ident_or_kw(self, what: str) -> str:
        """After AS, even keywords may serve as aliases (e.g. AS count)."""
        t = self.peek()
        if t.kind == Tok.QIDENT:
            self.next()
            return t.text
        if t.kind == Tok.IDENT:
            self.next()
            return t.text.lower()
        self.err(f"expected {what}")

    # --- FROM / joins ---

    def parse_from(self) -> A.TableRef:
        left = self.parse_join_tree()
        while self.try_op(","):
            right = self.parse_join_tree()
            left = A.Join(left=left, right=right, join_type=A.JoinType.CROSS)
        return left

    def parse_join_tree(self) -> A.TableRef:
        left = self.parse_table_factor()
        while True:
            natural = False
            if self.at_kw("NATURAL"):
                self.next()
                natural = True
            jt = None
            if self.try_kw("JOIN"):
                jt = A.JoinType.INNER
            elif self.try_kw("INNER"):
                self.expect_kw("JOIN")
                jt = A.JoinType.INNER
            elif self.try_kw("LEFT"):
                self.try_kw("OUTER")
                if self.try_kw("SEMI"):
                    jt = A.JoinType.SEMI
                elif self.try_kw("ANTI"):
                    jt = A.JoinType.ANTI
                else:
                    jt = A.JoinType.LEFT
                self.expect_kw("JOIN")
            elif self.try_kw("RIGHT"):
                self.try_kw("OUTER")
                self.expect_kw("JOIN")
                jt = A.JoinType.RIGHT
            elif self.try_kw("FULL"):
                self.try_kw("OUTER")
                self.expect_kw("JOIN")
                jt = A.JoinType.FULL
            elif self.try_kw("CROSS"):
                self.expect_kw("JOIN")
                jt = A.JoinType.CROSS
            else:
                if natural:
                    self.err("expected JOIN after NATURAL")
                return left
            right = self.parse_table_factor()
            on = None
            using = None
            if jt is not A.JoinType.CROSS and not natural:
                if self.try_kw("ON"):
                    on = self.parse_expr()
                elif self.try_kw("USING"):
                    self.expect_op("(")
                    using = [self.ident("column")]
                    while self.try_op(","):
                        using.append(self.ident("column"))
                    self.expect_op(")")
                else:
                    self.err("expected ON or USING")
            if natural:
                using = []  # binder resolves shared columns
            left = A.Join(left=left, right=right, join_type=jt, on=on, using=using)

    def parse_table_factor(self) -> A.TableRef:
        if self.try_op("("):
            if self.at_kw("SELECT", "WITH", "VALUES"):
                q = self.parse_query()
                self.expect_op(")")
                ref: A.TableRef = A.DerivedTable(query=q)
            elif self.peek().kind == Tok.OP and self.peek().text == "(":
                # ambiguous: "((SELECT ...))" vs "((a JOIN b ...))" — try query first,
                # backtrack to a parenthesized join on failure
                save = self.i
                try:
                    q = self.parse_query()
                    self.expect_op(")")
                    ref = A.DerivedTable(query=q)
                except SqlParseError:
                    self.i = save
                    ref = self.parse_from()
                    self.expect_op(")")
            else:
                ref = self.parse_from()
                self.expect_op(")")
        elif self.at_kw("VALUES"):
            self.next()
            ref = A.ValuesTable(rows=self.parse_values_rows())
        else:
            name = self.ident("table name")
            while self.try_op("."):
                name += "." + self.ident("table name part")
            ref = A.NamedTable(name=name)
        if self.try_kw("AS"):
            ref.alias = self.ident_or_kw("alias")
        else:
            t = self.peek()
            if t.kind == Tok.QIDENT or (t.kind == Tok.IDENT and t.upper() not in _RESERVED_STOP):
                ref.alias = self.ident()
        return ref

    # --- expressions (precedence climbing) ---

    def parse_expr(self) -> E.Expr:
        return self.parse_or()

    def parse_or(self) -> E.Expr:
        left = self.parse_and()
        while self.try_kw("OR"):
            left = E.Binary(op=E.BinOp.OR, left=left, right=self.parse_and())
        return left

    def parse_and(self) -> E.Expr:
        left = self.parse_not()
        while self.try_kw("AND"):
            left = E.Binary(op=E.BinOp.AND, left=left, right=self.parse_not())
        return left

    def parse_not(self) -> E.Expr:
        if self.try_kw("NOT"):
            return E.Not(operand=self.parse_not())
        return self.parse_comparison()

    _CMP = {"=": E.BinOp.EQ, "<>": E.BinOp.NEQ, "!=": E.BinOp.NEQ, "<": E.BinOp.LT,
            "<=": E.BinOp.LTE, ">": E.BinOp.GT, ">=": E.BinOp.GTE}

    def parse_comparison(self) -> E.Expr:
        left = self.parse_additive()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text in self._CMP:
                self.next()
                # comparison with subquery: = (SELECT ...) treated as scalar subquery
                right = self.parse_additive()
                left = E.Binary(op=self._CMP[t.text], left=left, right=right)
                continue
            negated = False
            save = self.i
            if self.try_kw("NOT"):
                negated = True
            if self.try_kw("BETWEEN"):
                low = self.parse_additive()
                self.expect_kw("AND")
                high = self.parse_additive()
                rng = E.Binary(op=E.BinOp.AND,
                               left=E.Binary(op=E.BinOp.GTE, left=left, right=low),
                               right=E.Binary(op=E.BinOp.LTE, left=left, right=high))
                left = E.Not(operand=rng) if negated else rng
                continue
            if self.try_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = E.InSubquery(operand=left, query=q, negated=negated)
                else:
                    items = [self.parse_expr()]
                    while self.try_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = E.InList(operand=left, items=items, negated=negated)
                continue
            if self.at_kw("LIKE", "ILIKE"):
                ci = self.next().upper() == "ILIKE"
                pat = self.parse_additive()
                if not isinstance(pat, E.Literal) or not isinstance(pat.value, str):
                    self.err("LIKE pattern must be a string literal")
                left = E.Like(operand=left, pattern=pat.value, negated=negated,
                              case_insensitive=ci)
                continue
            if negated:
                self.i = save  # NOT belonged to something else
                return left
            if self.try_kw("IS"):
                neg = self.try_kw("NOT") is not None
                if self.try_kw("NULL"):
                    left = E.IsNull(operand=left, negated=neg)
                else:
                    if self.try_kw("TRUE"):
                        bv = True
                    elif self.try_kw("FALSE"):
                        bv = False
                    else:
                        self.err("expected NULL/TRUE/FALSE after IS")
                    # IS [NOT] TRUE/FALSE: never NULL -> NOT(IsNull(x)) AND x = bv
                    cmpe = E.Binary(op=E.BinOp.EQ, left=left,
                                    right=E.Literal(value=bv, literal_type=T.BOOL))
                    isn = E.IsNull(operand=left)
                    t_ = E.Binary(op=E.BinOp.AND, left=E.Not(operand=isn), right=cmpe)
                    left = E.Not(operand=t_) if neg else t_
                continue
            return left

    def parse_additive(self) -> E.Expr:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text == "+":
                self.next()
                left = E.Binary(op=E.BinOp.ADD, left=left, right=self.parse_multiplicative())
            elif t.kind == Tok.OP and t.text == "-":
                self.next()
                left = E.Binary(op=E.BinOp.SUB, left=left, right=self.parse_multiplicative())
            elif t.kind == Tok.OP and t.text == "||":
                self.next()
                right = self.parse_multiplicative()
                left = E.Func(name="concat", args=[left, right])
            else:
                return left

    def parse_multiplicative(self) -> E.Expr:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text == "*":
                self.next()
                left = E.Binary(op=E.BinOp.MUL, left=left, right=self.parse_unary())
            elif t.kind == Tok.OP and t.text == "/":
                self.next()
                left = E.Binary(op=E.BinOp.DIV, left=left, right=self.parse_unary())
            elif t.kind == Tok.OP and t.text == "%":
                self.next()
                left = E.Binary(op=E.BinOp.MOD, left=left, right=self.parse_unary())
            else:
                return left

    def parse_unary(self) -> E.Expr:
        if self.try_op("-"):
            operand = self.parse_unary()
            if isinstance(operand, E.Literal) and isinstance(operand.value, (int, float)) \
               and not isinstance(operand.value, bool):
                operand.value = -operand.value
                return operand
            return E.Negate(operand=operand)
        if self.try_op("+"):
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self) -> E.Expr:
        ex = self.parse_primary()
        while True:
            t = self.peek()
            if t.kind == Tok.OP and t.text == "::":
                self.next()
                ex = E.Cast(operand=ex, to=self.parse_type_name())
            else:
                return ex

    def parse_type_name(self) -> T.DataType:
        name = self.ident_or_kw("type name").upper()
        if name == "DOUBLE" and self.try_kw("PRECISION"):
            pass
        if name not in _TYPE_NAMES:
            self.err(f"unknown type {name}")
        # optional (p[,s]) as in DECIMAL(15,2), VARCHAR(25)
        if self.try_op("("):
            t = self.next()
            if t.kind != Tok.NUMBER:
                self.err("expected type parameter")
            if self.try_op(","):
                t = self.next()
                if t.kind != Tok.NUMBER:
                    self.err("expected type parameter")
            self.expect_op(")")
        return _TYPE_NAMES[name]

    def parse_primary(self) -> E.Expr:
        t = self.peek()
        if t.kind == Tok.NUMBER:
            self.next()
            txt = t.text
            if "." in txt or "e" in txt or "E" in txt:
                return E.Literal(value=float(txt), literal_type=T.FLOAT64)
            v = int(txt)
            lt = T.INT32 if -(2 ** 31) <= v < 2 ** 31 else T.INT64
            return E.Literal(value=v, literal_type=lt)
        if t.kind == Tok.STRING:
            self.next()
            return E.Literal(value=t.text, literal_type=T.STRING)
        if t.kind == Tok.OP and t.text == "(":
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.parse_query()
                self.expect_op(")")
                return E.ScalarSubquery(query=q)
            ex = self.parse_expr()
            self.expect_op(")")
            return ex
        if t.kind == Tok.OP and t.text == "*":
            self.next()
            return E.Star()
        if t.kind == Tok.QIDENT:
            return self.parse_name_or_call()
        if t.kind != Tok.IDENT:
            self.err("expected expression")
        kw = t.upper()
        if kw == "NULL":
            self.next()
            return E.Literal(value=None, literal_type=T.NULL)
        if kw == "TRUE":
            self.next()
            return E.Literal(value=True, literal_type=T.BOOL)
        if kw == "FALSE":
            self.next()
            return E.Literal(value=False, literal_type=T.BOOL)
        if kw == "CASE":
            return self.parse_case()
        if kw == "CAST":
            self.next()
            self.expect_op("(")
            ex = self.parse_expr()
            self.expect_kw("AS")
            to = self.parse_type_name()
            self.expect_op(")")
            return E.Cast(operand=ex, to=to)
        if kw == "EXISTS":
            self.next()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return E.Exists(query=q)
        if kw == "EXTRACT":
            self.next()
            self.expect_op("(")
            part = self.ident_or_kw("date part").lower()
            self.expect_kw("FROM")
            ex = self.parse_expr()
            self.expect_op(")")
            if part not in ("year", "month", "day"):
                self.err(f"unsupported EXTRACT part {part}")
            return E.Func(name=f"extract_{part}", args=[ex])
        if kw == "INTERVAL":
            self.next()
            tv = self.next()
            if tv.kind not in (Tok.STRING, Tok.NUMBER):
                self.err("expected INTERVAL value")
            # unit either inside the string ('3 month') or as a following keyword
            text = tv.text.strip()
            parts = text.split()
            try:
                if len(parts) == 2:
                    qty, unit = int(parts[0]), parts[1].lower()
                elif len(parts) == 1:
                    qty = int(text)
                    unit = self.ident_or_kw("interval unit").lower()
                else:
                    raise ValueError(text)
            except ValueError:
                self.err(f"bad INTERVAL value {tv.text!r}")
            unit = unit.rstrip("s")
            if unit == "day":
                return E.Interval(days=qty)
            if unit == "week":
                return E.Interval(days=qty * 7)
            if unit == "month":
                return E.Interval(months=qty)
            if unit == "year":
                return E.Interval(months=qty * 12)
            self.err(f"unsupported INTERVAL unit {unit}")
        if kw == "DATE" and self.peek(1).kind == Tok.STRING:
            self.next()
            s = self.next().text
            try:
                d = _dt.date.fromisoformat(s)
            except ValueError:
                self.err(f"bad DATE literal {s!r}")
            return E.Literal(value=d.toordinal() - _EPOCH, literal_type=T.DATE32)
        if kw == "TIMESTAMP" and self.peek(1).kind == Tok.STRING:
            self.next()
            s = self.next().text
            try:
                ts = _dt.datetime.fromisoformat(s)
            except ValueError:
                self.err(f"bad TIMESTAMP literal {s!r}")
            if ts.tzinfo is not None:  # normalize aware timestamps to UTC
                ts = ts.astimezone(_dt.timezone.utc).replace(tzinfo=None)
            # exact integer microseconds (float total_seconds() loses 1us ~1% of
            # the time past 2005)
            us = (ts - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)
            return E.Literal(value=us, literal_type=T.TIMESTAMP)
        if kw in ("LEFT", "RIGHT") and self.peek(1).kind == Tok.OP and self.peek(1).text == "(":
            # left(s, n) / right(s, n) string functions (names double as join keywords)
            self.next()
            self.next()
            return self.parse_call(kw.lower())
        if kw in _RESERVED_STOP:
            self.err("expected expression")
        return self.parse_name_or_call()

    def parse_name_or_call(self) -> E.Expr:
        name = self.ident("identifier")
        # function call?
        if self.peek().kind == Tok.OP and self.peek().text == "(":
            self.next()
            return self.parse_call(name)
        # qualified column a.b(.c)
        full = name
        while self.peek().kind == Tok.OP and self.peek().text == "." and \
                self.peek(1).kind in (Tok.IDENT, Tok.QIDENT):
            self.next()
            full += "." + self.ident("column name part")
        return E.Column(name=full)

    _AGG_NAMES = {"sum": E.AggFunc.SUM, "count": E.AggFunc.COUNT, "min": E.AggFunc.MIN,
                  "max": E.AggFunc.MAX, "avg": E.AggFunc.AVG, "mean": E.AggFunc.AVG}

    _WINDOW_ONLY = {"row_number", "rank", "dense_rank", "lag", "lead"}

    def parse_call(self, name: str) -> E.Expr:
        lname = name.lower()
        if self.try_op(")"):
            return self._maybe_over(lname, [], E.Func(name=lname, args=[]))
        distinct = self.try_kw("DISTINCT") is not None
        if self.try_op("*"):
            self.expect_op(")")
            if lname == "count":
                return self._maybe_over(
                    lname, [], E.Aggregate(func=E.AggFunc.COUNT_STAR))
            self.err(f"{name}(*) is only valid for count")
        args = [self.parse_expr()]
        while self.try_op(","):
            args.append(self.parse_expr())
        self.expect_op(")")
        if lname in self._AGG_NAMES:
            if len(args) != 1:
                raise SqlParseError(f"{name} takes exactly one argument")
            return self._maybe_over(lname, args, E.Aggregate(
                func=self._AGG_NAMES[lname], arg=args[0], distinct=distinct))
        if distinct:
            self.err("DISTINCT only valid in aggregate functions")
        return self._maybe_over(lname, args, E.Func(name=lname, args=args))

    def _maybe_over(self, lname: str, args: list, plain: E.Expr) -> E.Expr:
        """Attach an OVER (...) window spec, or return the plain call."""
        if not self.try_kw("OVER"):
            if lname in self._WINDOW_ONLY:
                self.err(f"{lname}() requires an OVER (...) clause")
            return plain
        if isinstance(plain, E.Aggregate) and plain.distinct:
            self.err("DISTINCT aggregates cannot be windowed")
        self.expect_op("(")
        partition: list[E.Expr] = []
        order: list[E.Expr] = []
        asc: list[bool] = []
        nf: list = []
        if self.try_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.parse_expr())
            while self.try_op(","):
                partition.append(self.parse_expr())
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                order.append(self.parse_expr())
                a = True
                if self.try_kw("ASC"):
                    a = True
                elif self.try_kw("DESC"):
                    a = False
                n = None
                if self.try_kw("NULLS"):
                    if self.try_kw("FIRST"):
                        n = True
                    else:
                        self.expect_kw("LAST")
                        n = False
                asc.append(a)
                nf.append(n if n is not None else not a)
                if not self.try_op(","):
                    break
        self.expect_op(")")
        if isinstance(plain, E.Aggregate):
            return E.Window(func="agg", agg=plain, partition_by=partition,
                            order_by=order, ascending=asc, nulls_first=nf)
        if lname not in self._WINDOW_ONLY:
            self.err(f"{lname}() cannot take an OVER clause")
        if lname in ("row_number", "rank", "dense_rank"):
            if args:
                self.err(f"{lname}() takes no arguments")
            if not order:
                self.err(f"{lname}() requires ORDER BY in its OVER clause")
        else:  # lag / lead
            if not (1 <= len(args) <= 2):
                self.err(f"{lname}() takes 1 or 2 arguments")
            if not order:
                self.err(f"{lname}() requires ORDER BY in its OVER clause")
        return E.Window(func=lname, args=args, partition_by=partition,
                        order_by=order, ascending=asc, nulls_first=nf)

    def parse_case(self) -> E.Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens: list[tuple[E.Expr, E.Expr]] = []
        while self.try_kw("WHEN"):
            cond = self.parse_expr()
            if operand is not None:  # simple CASE: desugar to operand = cond
                cond = E.Binary(op=E.BinOp.EQ, left=operand, right=cond)
            self.expect_kw("THEN")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.try_kw("ELSE"):
            else_ = self.parse_expr()
        self.expect_kw("END")
        return E.Case(whens=whens, else_=else_)
