"""SQL frontend: lexer, parser, AST (replaces reference crates/engine/src/parser.rs
and the DataFusion SQL planner front half)."""
from igloo_tpu.sql.parser import SqlParseError, parse_sql, parse_statements  # noqa: F401
from igloo_tpu.sql import ast  # noqa: F401
