"""SQL lexer.

Hand-rolled tokenizer for the engine's SQL dialect (GenericDialect-equivalent of the
reference's sqlparser setup, crates/engine/src/parser.rs:7-9). Produces a flat token
list consumed by the recursive-descent parser.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

from igloo_tpu.errors import SqlParseError


def line_col(sql: str, pos: int) -> tuple[int, int]:
    line = sql.count("\n", 0, pos) + 1
    col = pos - (sql.rfind("\n", 0, pos) + 1) + 1
    return line, col


class Tok(enum.Enum):
    IDENT = "ident"
    QIDENT = "qident"       # "quoted identifier"
    NUMBER = "number"
    STRING = "string"       # 'literal'
    OP = "op"               # punctuation / operators
    EOF = "eof"


@dataclass(frozen=True)
class Token:
    kind: Tok
    text: str
    pos: int  # character offset, for error messages

    def upper(self) -> str:
        return self.text.upper()


class SqlLexError(SqlParseError):
    def __init__(self, msg: str, sql: str, pos: int):
        line, col = line_col(sql, pos)
        super().__init__(f"{msg} at line {line}, column {col}")


_TWO_CHAR_OPS = {"<>", "!=", "<=", ">=", "||", "::"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>[]")


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        # comments
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlLexError("unterminated block comment", sql, i)
            i = j + 2
            continue
        # string literal (single quotes, '' escape)
        if c == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlLexError("unterminated string literal", sql, i)
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(Tok.STRING, "".join(buf), i))
            i = j + 1
            continue
        # quoted identifier ("" / `` doubling escapes the quote char)
        if c in ('"', "`"):
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlLexError("unterminated quoted identifier", sql, i)
                if sql[j] == c:
                    if j + 1 < n and sql[j + 1] == c:
                        buf.append(c)
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(Tok.QIDENT, "".join(buf), i))
            i = j + 1
            continue
        # number: digits, optional fraction/exponent; also ".5"
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    # disambiguate "1." followed by identifier (qualified name) —
                    # only treat as fraction if next char is a digit
                    if j + 1 < n and sql[j + 1].isdigit():
                        seen_dot = True
                        j += 1
                    else:
                        break
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                    sql[j + 1].isdigit() or (sql[j + 1] in "+-" and j + 2 < n and sql[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            toks.append(Token(Tok.NUMBER, sql[i:j], i))
            i = j
            continue
        # identifier / keyword
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_" or sql[j] == "$"):
                j += 1
            toks.append(Token(Tok.IDENT, sql[i:j], i))
            i = j
            continue
        # operators
        if sql[i:i + 2] in _TWO_CHAR_OPS:
            toks.append(Token(Tok.OP, sql[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            toks.append(Token(Tok.OP, c, i))
            i += 1
            continue
        raise SqlLexError(f"unexpected character {c!r}", sql, i)
    toks.append(Token(Tok.EOF, "", n))
    return toks
