"""SQL abstract syntax tree.

The reference delegates SQL to sqlparser-rs (crates/engine/src/parser.rs:7-12 returns
the last `Statement`) and to DataFusion's frontend for the working path
(crates/engine/src/lib.rs:54-57). We own the frontend: the parser produces this AST,
the binder (plan/binder.py) turns it into a typed logical plan.

Expression nodes live in plan/expr.py and are shared between AST and logical plan —
the parser emits unbound Expr trees directly.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from igloo_tpu.plan import expr as E


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"
    CROSS = "cross"
    SEMI = "semi"    # produced by IN/EXISTS rewrites, not by the grammar
    ANTI = "anti"


class SetOp(enum.Enum):
    UNION = "union"
    UNION_ALL = "union_all"
    INTERSECT = "intersect"
    EXCEPT = "except"


@dataclass
class TableRef:
    """Base of FROM items."""
    alias: Optional[str] = field(default=None, init=False)


@dataclass
class NamedTable(TableRef):
    name: str = ""

    def __repr__(self):
        return f"table({self.name}{' as ' + self.alias if self.alias else ''})"


@dataclass
class DerivedTable(TableRef):
    """(SELECT ...) AS alias in FROM."""
    query: "SelectStmt" = None  # type: ignore[assignment]

    def __repr__(self):
        return f"derived({self.alias})"


@dataclass
class Join(TableRef):
    left: TableRef = None   # type: ignore[assignment]
    right: TableRef = None  # type: ignore[assignment]
    join_type: JoinType = JoinType.INNER
    on: Optional[E.Expr] = None          # ON condition
    using: Optional[list[str]] = None    # USING (cols)

    def __repr__(self):
        return f"join({self.join_type.value}, {self.left!r}, {self.right!r})"


@dataclass
class ValuesTable(TableRef):
    """VALUES (...), (...) as an inline table."""
    rows: list[list[E.Expr]] = field(default_factory=list)


@dataclass
class OrderItem:
    expr: E.Expr
    asc: bool = True
    nulls_first: Optional[bool] = None  # None = SQL default (nulls last if asc)


@dataclass
class SelectStmt:
    """One SELECT query block (possibly with CTEs and set operations).

    When `set_op` is set, this node is a set operation over `left`/`right` and the
    select fields are unused.
    """
    # set operation form
    set_op: Optional[SetOp] = None
    left: Optional["SelectStmt"] = None
    right: Optional["SelectStmt"] = None
    # plain select form
    projections: list[E.Expr] = field(default_factory=list)
    distinct: bool = False
    from_: Optional[TableRef] = None
    where: Optional[E.Expr] = None
    group_by: list[E.Expr] = field(default_factory=list)
    having: Optional[E.Expr] = None
    # applies to either form
    ctes: list[tuple[str, "SelectStmt"]] = field(default_factory=list)
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclass
class ExplainStmt:
    query: SelectStmt = None  # type: ignore[assignment]
    analyze: bool = False


@dataclass
class ShowTablesStmt:
    pass


@dataclass
class DescribeStmt:
    table: str = ""


@dataclass
class CreateTableAsStmt:
    name: str = ""
    query: SelectStmt = None  # type: ignore[assignment]


@dataclass
class DropTableStmt:
    name: str = ""
    if_exists: bool = False


Statement = object  # SelectStmt | ExplainStmt | ShowTablesStmt | DescribeStmt | ...
