"""Python session API — the first-class pyigloo replacement.

The reference's Python bindings are an empty stub (pyigloo/src/lib.rs, gap in
SURVEY.md §2 #30). Since this framework is Python-hosted, the session IS the
native API: `igloo_tpu.connect()` -> Session with register_* + sql(), returning
pyarrow Tables (and pandas via .to_pandas()).
"""
from __future__ import annotations

from typing import Optional

import pyarrow as pa

from igloo_tpu.config import Config, make_provider
from igloo_tpu.engine import QueryEngine, QueryResult


class Session:
    def __init__(self, config: Optional[Config | str] = None,
                 use_jit: bool = True):
        if isinstance(config, str):
            config = Config.load(config)
        self.config = config
        self.engine = QueryEngine(use_jit=use_jit if config is None
                                  else config.use_jit)
        if config is not None:
            for t in config.tables:
                self.engine.register_table(t.name, make_provider(t))

    # --- registration ---

    def register_table(self, name: str, table) -> "Session":
        """Register a pyarrow Table, pandas DataFrame, or TableProvider."""
        if hasattr(table, "to_arrow"):  # pandas-like via pyarrow
            table = pa.Table.from_pandas(table)
        elif not isinstance(table, pa.Table) and hasattr(table, "columns") \
                and hasattr(table, "index"):
            table = pa.Table.from_pandas(table)
        self.engine.register_table(name, table)
        return self

    def register_parquet(self, name: str, path: str) -> "Session":
        from igloo_tpu.connectors.parquet import ParquetTable
        self.engine.register_table(name, ParquetTable(path))
        return self

    def register_csv(self, name: str, path: str, **opts) -> "Session":
        from igloo_tpu.connectors.csv import CsvTable
        self.engine.register_table(name, CsvTable(path, **opts))
        return self

    def register_iceberg(self, name: str, path: str) -> "Session":
        from igloo_tpu.connectors.iceberg import IcebergTable
        self.engine.register_table(name, IcebergTable(path))
        return self

    def deregister(self, name: str) -> "Session":
        self.engine.deregister_table(name)
        return self

    # --- queries ---

    def sql(self, query: str) -> pa.Table:
        return self.engine.execute(query)

    def query(self, query: str) -> QueryResult:
        return self.engine.query(query)

    def explain(self, query: str) -> str:
        t = self.engine.execute(f"EXPLAIN {query}")
        return "\n".join(t.column("plan").to_pylist())

    def tables(self) -> list[str]:
        return self.engine.catalog.names()
