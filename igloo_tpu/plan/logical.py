"""Logical plan IR.

The reference uses DataFusion's `LogicalPlan` and lowers a 4-node subset to its custom
operators (crates/engine/src/physical_planner.rs:23-140: TableScan/Projection/Filter/
Join). We own the logical plan — it is the unit the optimizer rewrites, the
distributed planner fragments, and the executor lowers to fused jit computations.

Every node carries its output `Schema`; expressions inside nodes are *bound*
(Column.index resolved against the node's input schema, dtypes inferred).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from igloo_tpu.types import Schema
from igloo_tpu.plan import expr as E
from igloo_tpu.sql.ast import JoinType

if TYPE_CHECKING:  # pragma: no cover
    from igloo_tpu.catalog import TableProvider


@dataclass
class LogicalPlan:
    schema: Schema = field(default=None, init=False)  # type: ignore[assignment]

    def children(self) -> list["LogicalPlan"]:
        return []

    def node_name(self) -> str:
        return type(self).__name__


@dataclass
class Scan(LogicalPlan):
    """Table scan. `projection` (column names) is filled by projection pruning;
    `pushed_filters` by predicate pushdown (connector may evaluate them early —
    unlike the reference, which ignores the provider and hardcodes a path,
    physical_planner.rs:37-39 / gap G5, the provider here is authoritative)."""
    table: str = ""
    provider: object = None  # TableProvider
    projection: Optional[list[str]] = None
    pushed_filters: list[E.Expr] = field(default_factory=list)
    # restrict the scan to these provider partition indices (distributed /
    # chunked execution); None = whole table
    partition: Optional[tuple[int, ...]] = None
    # fingerprint of the provider's partition index captured at planning time;
    # verified before partitioned reads (a re-globbed index of the same length
    # must not silently remap partition ids)
    partition_token: Optional[str] = None
    # fragment-tier bucket scan: read only hash bucket `bucket` of `buckets`
    # from a dependency fragment's Exchange-partitioned result (the worker's
    # dep fetch resolves these into bucketed do_get tickets); None = whole
    # result. Only meaningful on `__frag_*` scans.
    bucket: Optional[int] = None
    buckets: Optional[int] = None

    def node_name(self):
        cols = f" cols={self.projection}" if self.projection is not None else ""
        part = f" part={list(self.partition)}" if self.partition is not None else ""
        bk = f" bucket={self.bucket}/{self.buckets}" if self.bucket is not None else ""
        return f"Scan({self.table}{cols}{part}{bk})"


@dataclass
class Filter(LogicalPlan):
    input: LogicalPlan = None  # type: ignore[assignment]
    predicate: E.Expr = None   # type: ignore[assignment]

    def children(self):
        return [self.input]

    def node_name(self):
        return f"Filter({self.predicate!r})"


@dataclass
class Project(LogicalPlan):
    input: LogicalPlan = None          # type: ignore[assignment]
    exprs: list[E.Expr] = field(default_factory=list)
    names: list[str] = field(default_factory=list)

    def children(self):
        return [self.input]

    def node_name(self):
        return f"Project({', '.join(self.names)})"


@dataclass
class Aggregate(LogicalPlan):
    """Group-by + aggregate. Output schema = group columns then aggregate columns."""
    input: LogicalPlan = None  # type: ignore[assignment]
    group_exprs: list[E.Expr] = field(default_factory=list)
    group_names: list[str] = field(default_factory=list)
    aggs: list[E.Aggregate] = field(default_factory=list)
    agg_names: list[str] = field(default_factory=list)

    def children(self):
        return [self.input]

    def node_name(self):
        return f"Aggregate(by=[{', '.join(self.group_names)}], aggs=[{', '.join(self.agg_names)}])"


@dataclass
class Join(LogicalPlan):
    """Equi-join with optional residual filter (bound against concat(left, right)
    schema). CROSS join = empty key lists. Completes the reference's partial
    HashJoinExec (G4: right/full outer unmatched rows are emitted here)."""
    left: LogicalPlan = None   # type: ignore[assignment]
    right: LogicalPlan = None  # type: ignore[assignment]
    join_type: JoinType = JoinType.INNER
    left_keys: list[E.Expr] = field(default_factory=list)
    right_keys: list[E.Expr] = field(default_factory=list)
    residual: Optional[E.Expr] = None  # non-equi part of ON

    def children(self):
        return [self.left, self.right]

    def node_name(self):
        return f"Join({self.join_type.value}, on={len(self.left_keys)} keys{', residual' if self.residual else ''})"


@dataclass
class Window(LogicalPlan):
    """Window-function evaluation: output = input columns + one column per
    entry in `funcs` (bound E.Window exprs sharing this node's single
    PARTITION BY / ORDER BY spec; the binder stacks one node per distinct
    spec). Row ORDER of the output batch is unspecified (like every
    non-Sort node); only the VALUES are window-ordered."""
    input: LogicalPlan = None  # type: ignore[assignment]
    partition_exprs: list[E.Expr] = field(default_factory=list)
    order_exprs: list[E.Expr] = field(default_factory=list)
    ascending: list[bool] = field(default_factory=list)
    nulls_first: list[bool] = field(default_factory=list)
    funcs: list[E.Expr] = field(default_factory=list)   # bound E.Window nodes
    names: list[str] = field(default_factory=list)

    def children(self):
        return [self.input]

    def node_name(self):
        return (f"Window({', '.join(self.names)} part="
                f"{len(self.partition_exprs)} order={len(self.order_exprs)})")


@dataclass
class Sort(LogicalPlan):
    input: LogicalPlan = None  # type: ignore[assignment]
    keys: list[E.Expr] = field(default_factory=list)  # bound against input schema
    ascending: list[bool] = field(default_factory=list)
    nulls_first: list[bool] = field(default_factory=list)

    def children(self):
        return [self.input]

    def node_name(self):
        return f"Sort({len(self.keys)} keys)"


@dataclass
class Limit(LogicalPlan):
    input: LogicalPlan = None  # type: ignore[assignment]
    limit: Optional[int] = None
    offset: int = 0

    def children(self):
        return [self.input]

    def node_name(self):
        return f"Limit({self.limit}, offset={self.offset})"


@dataclass
class Distinct(LogicalPlan):
    input: LogicalPlan = None  # type: ignore[assignment]

    def children(self):
        return [self.input]


@dataclass
class Union(LogicalPlan):
    """UNION ALL (bag union). Set-union is Distinct(Union)."""
    inputs: list[LogicalPlan] = field(default_factory=list)

    def children(self):
        return list(self.inputs)


@dataclass
class SetOpJoin(LogicalPlan):
    """INTERSECT / EXCEPT as distinct + semi/anti join on all columns."""
    left: LogicalPlan = None   # type: ignore[assignment]
    right: LogicalPlan = None  # type: ignore[assignment]
    anti: bool = False         # False=INTERSECT, True=EXCEPT

    def children(self):
        return [self.left, self.right]


@dataclass
class Values(LogicalPlan):
    """Inline literal rows (VALUES ... / SELECT-without-FROM one-row source)."""
    rows: list[list[object]] = field(default_factory=list)  # python values


@dataclass
class Exchange(LogicalPlan):
    """Hash-partition marker at a FRAGMENT root (distributed planner only —
    the reference's never-built FragmentType::Shuffle, fragment.rs:12): the
    worker executes `input`, then hash-partitions the result by the key
    columns (indices into the input schema) into `buckets` bucket slices
    served via bucketed do_get tickets. Never reaches a local executor.

    Hot-key salting (docs/adaptive.md): when the adaptive skew sketch flags
    bucket `salt_bucket` as pathologically hot, the exchange grows `salt - 1`
    extra buckets. The PROBE side spreads its hot-bucket rows round-robin
    across {salt_bucket} + the extra buckets; the BUILD side keeps its
    hot-bucket rows in place AND replicates them into every extra bucket, so
    each salted join fragment still sees every build row that could match."""
    input: LogicalPlan = None  # type: ignore[assignment]
    keys: list[int] = field(default_factory=list)
    buckets: int = 1
    salt_bucket: Optional[int] = None
    salt: int = 1                      # salted bucket count S (1 = no salting)
    salt_role: Optional[str] = None    # "probe" | "build"

    def children(self):
        return [self.input]

    def node_name(self):
        s = (f", salt={self.salt}@{self.salt_bucket}/{self.salt_role}"
             if self.salt_role else "")
        return f"Exchange(keys={self.keys}, buckets={self.buckets}{s})"


def copy_plan(plan: LogicalPlan) -> LogicalPlan:
    """Structural copy of a plan tree: nodes and expressions are fresh objects
    (safe for in-place optimizer rewrites), table providers are shared. Needed
    when one subtree is referenced twice (CTE used in two FROM positions)."""
    import copy as _copy
    n = _copy.copy(plan)
    if isinstance(n, Scan):
        n.pushed_filters = [_copy.deepcopy(e) for e in n.pushed_filters]
        n.projection = list(n.projection) if n.projection is not None else None
    elif isinstance(n, Filter):
        n.input = copy_plan(n.input)
        n.predicate = _copy.deepcopy(n.predicate)
    elif isinstance(n, Project):
        n.input = copy_plan(n.input)
        n.exprs = [_copy.deepcopy(e) for e in n.exprs]
        n.names = list(n.names)
    elif isinstance(n, Aggregate):
        n.input = copy_plan(n.input)
        n.group_exprs = [_copy.deepcopy(e) for e in n.group_exprs]
        n.group_names = list(n.group_names)
        n.aggs = [_copy.deepcopy(a) for a in n.aggs]
        n.agg_names = list(n.agg_names)
    elif isinstance(n, Join):
        n.left = copy_plan(n.left)
        n.right = copy_plan(n.right)
        n.left_keys = [_copy.deepcopy(e) for e in n.left_keys]
        n.right_keys = [_copy.deepcopy(e) for e in n.right_keys]
        n.residual = _copy.deepcopy(n.residual) if n.residual is not None else None
    elif isinstance(n, Window):
        n.input = copy_plan(n.input)
        n.partition_exprs = [_copy.deepcopy(e) for e in n.partition_exprs]
        n.order_exprs = [_copy.deepcopy(e) for e in n.order_exprs]
        n.ascending = list(n.ascending)
        n.nulls_first = list(n.nulls_first)
        n.funcs = [_copy.deepcopy(e) for e in n.funcs]
        n.names = list(n.names)
    elif isinstance(n, Sort):
        n.input = copy_plan(n.input)
        n.keys = [_copy.deepcopy(e) for e in n.keys]
        n.ascending = list(n.ascending)
        n.nulls_first = list(n.nulls_first)
    elif isinstance(n, (Limit, Distinct)):
        n.input = copy_plan(n.input)
    elif isinstance(n, Exchange):
        n.input = copy_plan(n.input)
        n.keys = list(n.keys)
    elif isinstance(n, Union):
        n.inputs = [copy_plan(c) for c in n.inputs]
    elif isinstance(n, SetOpJoin):
        n.left = copy_plan(n.left)
        n.right = copy_plan(n.right)
    elif isinstance(n, Values):
        n.rows = [list(r) for r in n.rows]
    return n


def plan_tree_str(plan: LogicalPlan, indent: int = 0) -> str:
    lines = ["  " * indent + plan.node_name()]
    for c in plan.children():
        lines.append(plan_tree_str(c, indent + 1))
    return "\n".join(lines)


def walk_plan(plan: LogicalPlan):
    yield plan
    for c in plan.children():
        yield from walk_plan(c)
