"""Binder: AST -> typed, bound logical plan.

Plays the role DataFusion's SQL planner plays for the reference's working path
(crates/engine/src/lib.rs:54-57 delegates parse→logical-plan→optimize wholesale).
Responsibilities:

- name resolution (qualified/unqualified columns, aliases, CTEs, scopes)
- type inference (every bound Expr gets a dtype)
- aggregate extraction (SELECT/HAVING/ORDER BY aggregates hoisted into an
  Aggregate node, projections rewritten against its output)
- subquery rewrites: IN/EXISTS -> semi/anti joins (with correlated-equality
  decorrelation); uncorrelated scalar subqueries -> eager-eval placeholders;
  correlated scalar aggregate subqueries -> group-by + join decorrelation
- interval folding for date arithmetic
"""
from __future__ import annotations

import copy
import datetime as _dt
from dataclasses import dataclass, field
from typing import Optional

from igloo_tpu import types as T
from igloo_tpu.catalog import Catalog
from igloo_tpu.errors import NotSupportedError, PlanError
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql import ast as A

_EPOCH_ORD = _dt.date(1970, 1, 1).toordinal()


# --- scopes ----------------------------------------------------------------------

@dataclass
class ScopeEntry:
    qualifier: Optional[str]
    name: str
    dtype: T.DataType
    index: int


@dataclass
class Scope:
    entries: list[ScopeEntry] = field(default_factory=list)
    parent: Optional["Scope"] = None

    @staticmethod
    def from_schema(schema, qualifier: Optional[str] = None, parent=None) -> "Scope":
        return Scope([ScopeEntry(qualifier, f.name, f.dtype, i)
                      for i, f in enumerate(schema)], parent)

    def concat(self, other: "Scope") -> "Scope":
        n = len(self.entries)
        merged = list(self.entries) + [
            ScopeEntry(e.qualifier, e.name, e.dtype, e.index + n) for e in other.entries
        ]
        return Scope(merged, self.parent)

    def resolve(self, name: str) -> tuple[Optional[ScopeEntry], int]:
        """Returns (entry, outer_level). outer_level 0 = this scope."""
        parts = name.split(".")
        if len(parts) >= 2:
            qual, col = parts[-2].lower(), parts[-1]
        else:
            qual, col = None, parts[0]
        matches = [e for e in self.entries
                   if e.name.lower() == col.lower()
                   and (qual is None or (e.qualifier or "").lower() == qual)]
        if len(matches) > 1 and qual is None:
            # unqualified ambiguity is an error only if they come from different
            # qualifiers (duplicate output names within one table: last wins)
            quals = {e.qualifier for e in matches}
            if len(quals) > 1:
                raise PlanError(f"ambiguous column reference: {name}")
            return matches[-1], 0
        if matches:
            return matches[0], 0
        if self.parent is not None:
            e, lvl = self.parent.resolve(name)
            return e, lvl + 1
        return None, 0


@dataclass
class OuterRef(E.Expr):
    """Placeholder for a correlated reference to an outer-query column; replaced
    during decorrelation (never reaches the executor). `level` counts scopes
    outward (1 = immediate parent); only level-1 correlation can be rewritten
    into a join against the parent plan."""
    name: str = ""
    entry: ScopeEntry = None  # type: ignore[assignment]
    level: int = 1

    def __repr__(self):
        return f"outer({self.name}@{self.level})"


# --- aggregate typing ------------------------------------------------------------

def agg_result_type(func: E.AggFunc, arg_dtype: Optional[T.DataType]) -> T.DataType:
    if func in (E.AggFunc.COUNT, E.AggFunc.COUNT_STAR):
        return T.INT64
    if func is E.AggFunc.AVG:
        return T.FLOAT64
    if func is E.AggFunc.SUM:
        if arg_dtype is None or not arg_dtype.is_numeric:
            raise PlanError(f"sum() requires a numeric argument, got {arg_dtype}")
        return T.INT64 if arg_dtype.is_integer else T.FLOAT64
    # MIN/MAX keep the argument type
    return arg_dtype  # type: ignore[return-value]


_FUNC_TYPES = {
    "abs": None, "sign": None,  # None => same as arg
    "floor": T.FLOAT64, "ceil": T.FLOAT64, "sqrt": T.FLOAT64, "exp": T.FLOAT64,
    "ln": T.FLOAT64, "log": T.FLOAT64, "log10": T.FLOAT64, "round": T.FLOAT64,
    "power": T.FLOAT64, "pow": T.FLOAT64,
    "sin": T.FLOAT64, "cos": T.FLOAT64, "tan": T.FLOAT64,
    "extract_year": T.INT32, "extract_month": T.INT32, "extract_day": T.INT32,
    "year": T.INT32, "month": T.INT32, "day": T.INT32,
    "length": T.INT32, "char_length": T.INT32, "character_length": T.INT32,
    "upper": T.STRING, "lower": T.STRING, "capitalize": T.STRING, "trim": T.STRING,
    "substr": T.STRING, "substring": T.STRING, "concat": T.STRING,
    "left": T.STRING, "right": T.STRING,
}


class Binder:
    def __init__(self, catalog: Catalog, udfs: Optional[dict] = None):
        self.catalog = catalog
        self.udfs = udfs or {}
        self._cte_env: dict[str, L.LogicalPlan] = {}
        self._anon = 0
        # correlated scalar subqueries are only decorrelatable where the
        # caller can rewrite the surrounding plan (WHERE conjuncts)
        self._allow_corr_scalar = False

    # --- entry point ---

    def bind(self, stmt: A.SelectStmt) -> L.LogicalPlan:
        return self.bind_query(stmt, outer=None)

    def bind_query(self, stmt: A.SelectStmt, outer: Optional[Scope]) -> L.LogicalPlan:
        saved = dict(self._cte_env)
        try:
            for name, q in stmt.ctes:
                self._cte_env[name.lower()] = self.bind_query(q, outer)
            if stmt.set_op is not None:
                plan = self._bind_set_op(stmt, outer)
            else:
                plan = self._bind_select(stmt, outer)
            return plan
        finally:
            self._cte_env = saved

    # --- set operations ---

    def _bind_set_op(self, stmt: A.SelectStmt, outer) -> L.LogicalPlan:
        left = self.bind_query(stmt.left, outer)
        right = self.bind_query(stmt.right, outer)
        if len(left.schema) != len(right.schema):
            raise PlanError(
                f"set operation inputs have different column counts: "
                f"{len(left.schema)} vs {len(right.schema)}")
        # unify column types; keep left's names
        casts_l, casts_r, fields = [], [], []
        for i, (fl, fr) in enumerate(zip(left.schema, right.schema)):
            ct = T.common_type(fl.dtype, fr.dtype)
            fields.append(T.Field(fl.name, ct, fl.nullable or fr.nullable))
            casts_l.append(None if fl.dtype == ct else ct)
            casts_r.append(None if fr.dtype == ct else ct)
        left = self._maybe_cast_all(left, casts_l)
        right = self._maybe_cast_all(right, casts_r)
        out_schema = T.Schema(fields)
        if stmt.set_op is A.SetOp.UNION_ALL:
            node: L.LogicalPlan = L.Union(inputs=[left, right])
            node.schema = out_schema
        elif stmt.set_op is A.SetOp.UNION:
            u = L.Union(inputs=[left, right])
            u.schema = out_schema
            node = L.Distinct(input=u)
            node.schema = out_schema
        else:
            node = L.SetOpJoin(left=left, right=right,
                               anti=(stmt.set_op is A.SetOp.EXCEPT))
            node.schema = out_schema
        node = self._apply_order_limit(node, stmt,
                                       Scope.from_schema(out_schema), None)
        return node

    def _maybe_cast_all(self, plan: L.LogicalPlan, casts: list) -> L.LogicalPlan:
        if all(c is None for c in casts):
            return plan
        exprs, names = [], []
        for i, f in enumerate(plan.schema):
            col = E.Column(f.name, index=i)
            col.dtype = f.dtype
            if casts[i] is not None:
                cast = E.Cast(operand=col, to=casts[i])
                cast.dtype = casts[i]
                exprs.append(cast)
            else:
                exprs.append(col)
            names.append(f.name)
        pr = L.Project(input=plan, exprs=exprs, names=names)
        pr.schema = T.Schema([T.Field(n, e.dtype, True) for n, e in zip(names, exprs)])
        return pr

    # --- SELECT core ---

    def _bind_select(self, stmt: A.SelectStmt, outer) -> L.LogicalPlan:
        # FROM
        if stmt.from_ is not None:
            plan, scope = self._bind_from(stmt.from_, outer)
        else:
            plan = L.Values(rows=[[]])
            plan.schema = T.Schema([])
            scope = Scope([], outer)
        scope.parent = outer

        # WHERE (may rewrite plan for IN/EXISTS subqueries)
        if stmt.where is not None:
            plan, preds = self._bind_where(stmt.where, plan, scope)
            for p in preds:
                plan = self._filter(plan, p)

        # expand stars & pre-process projections
        projections = self._expand_stars(stmt.projections, scope)

        # GROUP BY ordinals / aliases
        group_items = []
        for g in stmt.group_by:
            g = self._resolve_positional(g, projections)
            g = self._resolve_select_alias(g, projections)
            group_items.append(g)

        # bind projections (as written, against input scope)
        bound_proj: list[E.Expr] = []
        names: list[str] = []
        for p in projections:
            if isinstance(p, E.Alias):
                b = self.bind_expr(p.operand, scope, plan)
                names.append(p.alias)
            else:
                b = self.bind_expr(p, scope, plan)
                names.append(p.name_hint())
            bound_proj.append(b)

        bound_groups = [self.bind_expr(g, scope, plan) for g in group_items]
        bound_having = None
        if stmt.having is not None:
            h = self._resolve_select_alias(stmt.having, projections)
            bound_having = self.bind_expr(h, scope, plan)

        # ORDER BY: try output names first (post-projection), else bind to input
        has_aggs = any(self._contains_agg(b) for b in bound_proj) or \
            (bound_having is not None and self._contains_agg(bound_having)) or \
            bool(bound_groups)

        agg_rewrite = None
        pre_agg_scope = scope
        if has_aggs:
            plan, bound_proj, bound_having, scope, agg_rewrite = \
                self._build_aggregate(plan, scope, bound_groups, bound_proj,
                                      bound_having, group_items, names)

        if bound_having is not None:
            if bound_having.dtype != T.BOOL:
                raise PlanError("HAVING predicate must be boolean")
            plan = self._filter(plan, bound_having)

        # window functions: hoist E.Window subexpressions into Window plan
        # nodes (one per distinct OVER spec) below the projection
        if any(self._contains_window(b) for b in bound_proj):
            if has_aggs:
                raise PlanError("window functions combined with GROUP BY / "
                                "aggregates are not supported yet")
            plan, bound_proj = self._build_windows(plan, bound_proj)

        # projection node
        proj_node = L.Project(input=plan, exprs=bound_proj, names=list(names))
        proj_node.schema = T.Schema([
            T.Field(n, b.dtype, True) for n, b in zip(names, bound_proj)])
        plan = proj_node
        out_scope = Scope.from_schema(plan.schema)

        if stmt.distinct:
            d = L.Distinct(input=plan)
            d.schema = plan.schema
            plan = d

        plan = self._apply_order_limit(plan, stmt, out_scope,
                                       None if stmt.distinct else proj_node,
                                       hidden_scope=(pre_agg_scope if agg_rewrite
                                                     else scope),
                                       agg_rewrite=agg_rewrite)
        return plan

    # --- ORDER BY / LIMIT ---

    def _apply_order_limit(self, plan, stmt: A.SelectStmt, out_scope: Scope,
                           proj_node: Optional[L.Project],
                           hidden_scope: Optional[Scope] = None,
                           agg_rewrite=None) -> L.LogicalPlan:
        if stmt.order_by:
            keys, asc, nf = [], [], []
            hidden: list[E.Expr] = []
            for item in stmt.order_by:
                ex = self._resolve_positional(item.expr, None, out_schema=plan.schema)
                try:
                    b = self.bind_expr(ex, out_scope, plan)
                except PlanError:
                    b = None
                if b is not None and any(isinstance(n, E.Aggregate)
                                         for n in E.walk(b)):
                    # ORDER BY over an aggregate expression: the output scope
                    # "bind" produced a raw Aggregate node, which only the
                    # hidden-column path (through the aggregate rewrite) can
                    # turn into an executable sort key
                    b = None
                if b is None:
                    if proj_node is None:
                        raise PlanError(
                            f"ORDER BY expression {ex!r} not in output columns")
                    # hidden sort column: bind against the projection's input
                    # scope (qualified FROM scope pre-aggregation, aggregate
                    # output scope post-aggregation), append to the projection
                    in_scope = hidden_scope if hidden_scope is not None \
                        else Scope.from_schema(proj_node.input.schema)
                    hb = self.bind_expr(ex, in_scope, proj_node.input)
                    if agg_rewrite is not None:
                        # aggregated query: ORDER BY expressions go through the
                        # same rewrite HAVING uses — aggregates / group exprs
                        # map to the Aggregate node's output columns (which may
                        # grow for ORDER-BY-only aggregates); plain non-grouped
                        # columns are an error. Re-sync pass-through schemas
                        # above the (possibly extended) Aggregate node.
                        hb = agg_rewrite(hb)
                        chain = []
                        n = proj_node.input
                        while isinstance(n, (L.Filter, L.Distinct)):
                            chain.append(n)
                            n = n.input
                        for f in reversed(chain):
                            f.schema = f.input.schema
                    hname = f"__sort_{len(hidden)}"
                    hidden.append(hb)
                    proj_node.exprs.append(hb)
                    proj_node.names.append(hname)
                    proj_node.schema = T.Schema(
                        list(proj_node.schema.fields) + [T.Field(hname, hb.dtype, True)])
                    plan.schema = proj_node.schema if plan is proj_node else plan.schema
                    b = E.Column(hname, index=len(proj_node.exprs) - 1)
                    b.dtype = hb.dtype
                keys.append(b)
                asc.append(item.asc)
                nf.append(item.nulls_first if item.nulls_first is not None
                          else not item.asc)  # SQL default: NULLS LAST when ASC
            s = L.Sort(input=plan, keys=keys, ascending=asc, nulls_first=nf)
            s.schema = plan.schema
            plan = s
            if hidden and proj_node is not None:
                # drop hidden columns with a final narrow projection
                keep = len(proj_node.schema) - len(hidden)
                exprs, names2 = [], []
                for i, f in enumerate(plan.schema.fields[:keep]):
                    c = E.Column(f.name, index=i)
                    c.dtype = f.dtype
                    exprs.append(c)
                    names2.append(f.name)
                pr = L.Project(input=plan, exprs=exprs, names=names2)
                pr.schema = T.Schema(list(plan.schema.fields[:keep]))
                plan = pr
        if stmt.limit is not None or stmt.offset is not None:
            lim = L.Limit(input=plan, limit=stmt.limit, offset=stmt.offset or 0)
            lim.schema = plan.schema
            plan = lim
        return plan

    # --- window functions ---

    @staticmethod
    def _contains_window(e: E.Expr) -> bool:
        return any(isinstance(n, E.Window) for n in E.walk(e))

    def _build_windows(self, plan, bound_proj):
        """Hoist bound E.Window subexpressions into stacked L.Window nodes
        (one per distinct OVER spec; each preserves its input columns and
        appends one column per function), rewriting the projections to
        reference the appended columns."""
        specs: dict = {}
        order_specs: list = []
        for b in bound_proj:
            for n in E.walk(b):
                if not isinstance(n, E.Window):
                    continue
                skey = (tuple(repr(p) for p in n.partition_by),
                        tuple(repr(o) for o in n.order_by),
                        tuple(n.ascending), tuple(n.nulls_first))
                if skey not in specs:
                    specs[skey] = (n, [], [])
                    order_specs.append(skey)
                _, wins, reprs = specs[skey]
                r = repr(n)
                if r not in reprs:
                    wins.append(n)
                    reprs.append(r)
        col_of: dict[str, E.Column] = {}
        for skey in order_specs:
            proto, wins, reprs = specs[skey]
            base = len(plan.schema)
            names = [f"__win_{base + i}" for i in range(len(wins))]
            node = L.Window(input=plan, partition_exprs=proto.partition_by,
                            order_exprs=proto.order_by,
                            ascending=list(proto.ascending),
                            nulls_first=list(proto.nulls_first),
                            funcs=wins, names=names)
            node.schema = T.Schema(
                list(plan.schema.fields) +
                [T.Field(nm, w.dtype, True) for nm, w in zip(names, wins)])
            plan = node
            for i, r in enumerate(reprs):
                c = E.Column(names[i], index=base + i)
                c.dtype = wins[i].dtype
                col_of[r] = c

        def sub(n):
            if isinstance(n, E.Window):
                return col_of[repr(n)]
            return n
        return plan, [E.transform(b, sub) for b in bound_proj]

    def _resolve_positional(self, ex: E.Expr, projections, out_schema=None) -> E.Expr:
        if isinstance(ex, E.Literal) and isinstance(ex.value, int) \
                and not isinstance(ex.value, bool):
            k = ex.value
            if projections is not None:
                if not (1 <= k <= len(projections)):
                    raise PlanError(f"position {k} is out of range")
                p = projections[k - 1]
                return p.operand if isinstance(p, E.Alias) else p
            if out_schema is not None:
                if not (1 <= k <= len(out_schema)):
                    raise PlanError(f"ORDER BY position {k} is out of range")
                return E.Column(out_schema.fields[k - 1].name)
        return ex

    def _resolve_select_alias(self, ex: E.Expr, projections) -> E.Expr:
        """GROUP BY / HAVING may reference SELECT aliases."""
        aliases = {p.alias.lower(): p.operand for p in projections
                   if isinstance(p, E.Alias)}

        def sub(n):
            if isinstance(n, E.Column) and n.name.lower() in aliases:
                return copy.deepcopy(aliases[n.name.lower()])
            return n
        return E.transform(copy.deepcopy(ex), sub)

    def _expand_stars(self, projections: list[E.Expr], scope: Scope) -> list[E.Expr]:
        out = []
        for p in projections:
            if isinstance(p, E.Star):
                for e in scope.entries:
                    if p.qualifier is None or \
                            (e.qualifier or "").lower() == p.qualifier.lower():
                        c = E.Column(e.name if e.qualifier is None
                                     else f"{e.qualifier}.{e.name}")
                        out.append(c)
                if p.qualifier is not None and not any(
                        (e.qualifier or "").lower() == p.qualifier.lower()
                        for e in scope.entries):
                    raise PlanError(f"unknown table alias in {p.qualifier}.*")
            else:
                out.append(p)
        if not out:
            raise PlanError("SELECT list is empty after * expansion")
        return out

    # --- FROM / joins ---

    def _bind_from(self, ref: A.TableRef, outer) -> tuple[L.LogicalPlan, Scope]:
        if isinstance(ref, A.NamedTable):
            name = ref.name
            key = name.split(".")[-1].lower()
            if key in self._cte_env:
                # fresh copy per reference: the optimizer rewrites plans in
                # place, so two FROM positions must not share one subtree
                plan = L.copy_plan(self._cte_env[key])
                alias = ref.alias or key
                return plan, Scope.from_schema(plan.schema, alias)
            provider = self.catalog.get(name)
            plan = L.Scan(table=name.split(".")[-1].lower(), provider=provider)
            plan.schema = provider.schema()
            alias = ref.alias or name.split(".")[-1].lower()
            return plan, Scope.from_schema(plan.schema, alias)
        if isinstance(ref, A.DerivedTable):
            plan = self.bind_query(ref.query, outer)
            alias = ref.alias or self._anon_name("subquery")
            return plan, Scope.from_schema(plan.schema, alias)
        if isinstance(ref, A.ValuesTable):
            return self._bind_values(ref)
        if isinstance(ref, A.Join):
            return self._bind_join(ref, outer)
        raise PlanError(f"unsupported FROM item {type(ref).__name__}")

    def _anon_name(self, prefix: str) -> str:
        self._anon += 1
        return f"__{prefix}_{self._anon}"

    def _bind_values(self, ref: A.ValuesTable) -> tuple[L.LogicalPlan, Scope]:
        if not ref.rows:
            raise PlanError("VALUES requires at least one row")
        width = len(ref.rows[0])
        rows = []
        col_types: list[T.DataType] = [T.NULL] * width
        for r in ref.rows:
            if len(r) != width:
                raise PlanError("VALUES rows have unequal lengths")
            vals = []
            for j, cell in enumerate(r):
                cell = self._fold_intervals(cell)
                if not isinstance(cell, E.Literal):
                    raise NotSupportedError("VALUES cells must be literals")
                vals.append(cell.value)
                if cell.value is not None:
                    col_types[j] = T.common_type(col_types[j],
                                                 cell.literal_type or T.FLOAT64)
            rows.append(vals)
        fields = [T.Field(f"column{j + 1}",
                          col_types[j] if col_types[j] != T.NULL else T.INT32, True)
                  for j in range(width)]
        plan = L.Values(rows=rows)
        plan.schema = T.Schema(fields)
        alias = ref.alias or self._anon_name("values")
        return plan, Scope.from_schema(plan.schema, alias)

    def _bind_join(self, ref: A.Join, outer) -> tuple[L.LogicalPlan, Scope]:
        lplan, lscope = self._bind_from(ref.left, outer)
        rplan, rscope = self._bind_from(ref.right, outer)
        combined = lscope.concat(rscope)
        combined.parent = outer
        jt = ref.join_type

        using = ref.using
        natural = using is not None and len(using) == 0
        if natural:
            lnames = {e.name.lower() for e in lscope.entries}
            using = [e.name for e in rscope.entries if e.name.lower() in lnames]
            if not using:
                # no shared columns: INNER degenerates to CROSS; outer NATURAL
                # joins keep their type (empty keys = all pairs match, with
                # null-extension when a side is empty)
                if jt is A.JoinType.INNER:
                    jt = A.JoinType.CROSS
                using = None

        left_keys: list[E.Expr] = []
        right_keys: list[E.Expr] = []
        residual = None

        def bind_in_left(name):
            ent, lvl = lscope.resolve(name)
            if ent is None or lvl:
                raise PlanError(f"USING column {name} not found on left side")
            c = E.Column(name, index=ent.index)
            c.dtype = ent.dtype
            return c

        def bind_in_right(name):
            ent, lvl = rscope.resolve(name)
            if ent is None or lvl:
                raise PlanError(f"USING column {name} not found on right side")
            c = E.Column(name, index=ent.index)
            c.dtype = ent.dtype
            return c

        if using:
            for name in using:
                lk, rk = coerce_key_pair(bind_in_left(name), bind_in_right(name))
                left_keys.append(lk)
                right_keys.append(rk)
        elif ref.on is not None:
            n_left = len(lscope.entries)
            conjuncts = _split_conjuncts(self.bind_expr(ref.on, combined, None))
            residual_parts = []
            for c in conjuncts:
                lk_rk = _extract_equi_key(c, n_left)
                if lk_rk is not None:
                    lk, rk = coerce_key_pair(*lk_rk)
                    left_keys.append(lk)
                    right_keys.append(rk)
                else:
                    residual_parts.append(c)
            residual = _and_all(residual_parts)
        elif jt is not A.JoinType.CROSS and not natural:
            raise PlanError("JOIN requires ON or USING")

        node = L.Join(left=lplan, right=rplan, join_type=jt,
                      left_keys=left_keys, right_keys=right_keys, residual=residual)
        # output schema: left + right (semi/anti: left only)
        if jt in (A.JoinType.SEMI, A.JoinType.ANTI):
            node.schema = lplan.schema
            out_scope = lscope
        elif using:
            # USING outputs the shared column once (from the left)
            drop = {n.lower() for n in using}
            rfields = [f for f in rplan.schema if f.name.lower() not in drop]
            node.schema = T.Schema(_dedup_fields(list(lplan.schema) + rfields))
            # scope: left entries + right minus using
            rentries = [e for e in rscope.entries if e.name.lower() not in drop]
            out_scope = Scope(list(lscope.entries) + [
                ScopeEntry(e.qualifier, e.name, e.dtype,
                           len(lplan.schema) + i) for i, e in enumerate(rentries)])
            node = self._project_using(node, lplan, rplan, drop, jt)
        else:
            node.schema = T.Schema(_dedup_fields(
                list(lplan.schema) + list(rplan.schema)))
            out_scope = combined
        out_scope.parent = outer
        return node, out_scope

    def _project_using(self, join: L.Join, lplan, rplan, drop: set,
                       jt: A.JoinType) -> L.LogicalPlan:
        """Narrow a USING join's raw (left++right) output to a single copy of
        each shared key column. For RIGHT/FULL joins the key must be
        COALESCE(left, right): unmatched right rows carry the right value."""
        coalesce_key = jt in (A.JoinType.RIGHT, A.JoinType.FULL)
        n_left = len(lplan.schema)
        exprs, names = [], []
        full = list(lplan.schema) + list(rplan.schema)
        for i, f in enumerate(full):
            if i >= n_left and f.name.lower() in drop:
                continue
            c = E.Column(f.name, index=i)
            c.dtype = f.dtype
            ex: E.Expr = c
            if i < n_left and f.name.lower() in drop and coalesce_key:
                rj = next(j for j, rf in enumerate(rplan.schema)
                          if rf.name.lower() == f.name.lower())
                rc = E.Column(f.name, index=n_left + rj)
                rc.dtype = rplan.schema.fields[rj].dtype
                fn = E.Func(name="coalesce", args=[c, rc])
                fn.dtype = T.common_type(c.dtype, rc.dtype)
                ex = fn
            exprs.append(ex)
            names.append(f.name)
        raw_schema = T.Schema(_dedup_fields(full))
        join.schema = raw_schema
        pr = L.Project(input=join, exprs=exprs, names=names)
        pr.schema = T.Schema([T.Field(n, e.dtype, True) for n, e in zip(names, exprs)])
        return pr

    # --- WHERE with subquery rewrites ---

    def _bind_where(self, where: E.Expr, plan: L.LogicalPlan,
                    scope: Scope) -> tuple[L.LogicalPlan, list[E.Expr]]:
        conjuncts = _split_conjuncts_ast(where)
        preds: list[E.Expr] = []
        for c in conjuncts:
            neg = False
            inner = c
            while isinstance(inner, E.Not):
                neg = not neg
                inner = inner.operand
            if isinstance(inner, E.InSubquery):
                plan = self._rewrite_in_subquery(
                    inner, plan, scope, anti=(neg != inner.negated))
            elif isinstance(inner, E.Exists):
                plan = self._rewrite_exists(
                    inner, plan, scope, anti=(neg != inner.negated))
            else:
                saved_flag = self._allow_corr_scalar
                self._allow_corr_scalar = True
                try:
                    p = self.bind_expr(c, scope, plan)
                finally:
                    self._allow_corr_scalar = saved_flag
                if _contains_corr_scalar(p):
                    plan = self._apply_corr_scalar(plan, p)
                else:
                    preds.append(p)
        for p in preds:
            if p.dtype != T.BOOL:
                raise PlanError(f"WHERE predicate must be boolean, got {p.dtype}")
        return plan, preds

    def _rewrite_in_subquery(self, node: E.InSubquery, plan, scope, anti: bool):
        sub = self.bind_query(node.query, scope)
        if len(sub.schema) != 1:
            raise PlanError("IN subquery must return exactly one column")
        probe = self.bind_expr(node.operand, scope, plan)
        sub, corr_l, corr_r, _ = self._decorrelate(sub, plan.schema)
        key_r = E.Column(sub.schema.fields[0].name, index=0)
        key_r.dtype = sub.schema.fields[0].dtype
        probe, key_r = coerce_key_pair(probe, key_r)
        corr_l, corr_r = _coerce_key_lists(corr_l, corr_r)
        if not anti:
            j = L.Join(left=plan, right=sub, join_type=A.JoinType.SEMI,
                       left_keys=[probe] + corr_l, right_keys=[key_r] + corr_r)
            j.schema = plan.schema
            return j
        if not corr_l:
            # UNCORRELATED NOT IN: a keyed anti join + two scalar guards.
            # The residual form below matches every |left| x |sub| pair (its
            # join has no keys), whose candidate expansion is |L|x|S| slots —
            # at TPC-H SF1 q16 that is an ~3e8-lane program the TPU compiler
            # cannot hold. Keyed anti gives "no equal y" directly; SQL
            # three-valued NOT IN then needs exactly two data-dependent
            # corrections, both one-row scalars evaluated once:
            #   S contains a NULL  -> NOT IN is never TRUE -> keep nothing
            #   probe IS NULL      -> dropped unless S is empty
            j = L.Join(left=plan, right=sub, join_type=A.JoinType.ANTI,
                       left_keys=[probe], right_keys=[key_r])
            j.schema = plan.schema
            c_null = self._count_scalar(sub, null_key_only=True)
            c_all = self._count_scalar(sub, null_key_only=False)
            zero = E.Literal(value=0, literal_type=T.INT64)
            zero.dtype = T.INT64
            no_nulls = E.Binary(op=E.BinOp.EQ, left=c_null, right=zero)
            no_nulls.dtype = T.BOOL
            x_not_null = E.IsNull(operand=copy.deepcopy(probe), negated=True)
            x_not_null.dtype = T.BOOL
            zero2 = E.Literal(value=0, literal_type=T.INT64)
            zero2.dtype = T.INT64
            s_empty = E.Binary(op=E.BinOp.EQ, left=c_all, right=zero2)
            s_empty.dtype = T.BOOL
            x_ok = E.Binary(op=E.BinOp.OR, left=x_not_null, right=s_empty)
            x_ok.dtype = T.BOOL
            keep = E.Binary(op=E.BinOp.AND, left=no_nulls, right=x_ok)
            keep.dtype = T.BOOL
            return self._filter(j, keep)
        # correlated NOT IN: anti join on the CORRELATION keys only, with the
        # IN condition as a residual that is satisfied when the pair is "not
        # definitely unequal": probe = y OR y IS NULL OR probe IS NULL. This
        # encodes SQL three-valued NOT IN exactly, per correlation group:
        #   empty group            -> no candidate -> row kept
        #   group contains NULL y  -> residual true -> row dropped
        #   probe NULL, group != {} -> residual true -> row dropped
        n_left = len(plan.schema)
        key_r_comb = E.Column(sub.schema.fields[0].name, index=n_left)
        key_r_comb.dtype = key_r.dtype
        eq = E.Binary(op=E.BinOp.EQ, left=copy.deepcopy(probe), right=key_r_comb)
        eq.dtype = T.BOOL
        y_null = E.IsNull(operand=copy.deepcopy(key_r_comb))
        y_null.dtype = T.BOOL
        x_null = E.IsNull(operand=copy.deepcopy(probe))
        x_null.dtype = T.BOOL
        residual = _or_all([eq, y_null, x_null])
        j = L.Join(left=plan, right=sub, join_type=A.JoinType.ANTI,
                   left_keys=corr_l, right_keys=corr_r, residual=residual)
        j.schema = plan.schema
        return j

    def _count_scalar(self, sub: L.LogicalPlan,
                      null_key_only: bool) -> E.ScalarSubquery:
        """Bound scalar subquery `(SELECT count(*) FROM sub [WHERE key IS
        NULL])` over a copy of a one-column subquery plan."""
        s = L.copy_plan(sub)
        if null_key_only:
            c = E.Column(s.schema.fields[0].name, index=0)
            c.dtype = s.schema.fields[0].dtype
            cond = E.IsNull(operand=c)
            cond.dtype = T.BOOL
            s = self._filter(s, cond)
        a = E.Aggregate(func=E.AggFunc.COUNT_STAR)
        a.dtype = T.INT64
        node = L.Aggregate(input=s, group_exprs=[], group_names=[],
                           aggs=[a], agg_names=["__c"])
        node.schema = T.Schema([T.Field("__c", T.INT64, True)])
        q = E.ScalarSubquery(query=node)
        q.dtype = T.INT64
        return q

    def _rewrite_exists(self, node: E.Exists, plan, scope, anti: bool):
        sub = self.bind_query(node.query, scope)
        sub, corr_l, corr_r, residual = self._decorrelate(
            sub, plan.schema, allow_residual=True)
        corr_l, corr_r = _coerce_key_lists(corr_l, corr_r)
        if not corr_l:
            if residual is not None:
                # pure non-equi correlation: the __one projection below would
                # invalidate the residual's inner column indices
                raise NotSupportedError(
                    "EXISTS correlated only through non-equality predicates "
                    "is not supported yet")
            # uncorrelated EXISTS: degenerate — keep all or no rows; model as
            # cross-semi on constant key
            one = E.Literal(value=1, literal_type=T.INT32)
            one.dtype = T.INT32
            corr_l, corr_r = [one], [copy.deepcopy(one)]
            # project subquery to the constant too
            ce = E.Literal(value=1, literal_type=T.INT32)
            ce.dtype = T.INT32
            pr = L.Project(input=sub, exprs=[ce], names=["__one"])
            pr.schema = T.Schema([T.Field("__one", T.INT32, False)])
            sub = pr
            corr_r = [E.Column("__one", index=0)]
            corr_r[0].dtype = T.INT32
        j = L.Join(left=plan, right=sub,
                   join_type=A.JoinType.ANTI if anti else A.JoinType.SEMI,
                   left_keys=corr_l, right_keys=corr_r, residual=residual)
        j.schema = plan.schema
        return j

    def _apply_corr_scalar(self, plan: L.LogicalPlan,
                           pred: E.Expr) -> L.LogicalPlan:
        """WHERE conjunct containing correlated scalar aggregate subqueries
        (q2/q17/q20 shape: `x CMP (SELECT agg(...) FROM t WHERE t.k = o.k)`).
        Each subquery becomes a group-by-correlation-keys aggregate LEFT-joined
        to the plan; the conjunct is filtered on top and the original columns
        are projected back (no-match rows carry NULL -> comparison fails, the
        SQL semantics of a scalar subquery over an empty set)."""
        orig_schema = plan.schema
        while True:
            node = next((n for n in E.walk(pred)
                         if isinstance(n, E.ScalarSubquery)
                         and _plan_has_outer(n.query)), None)
            if node is None:
                break
            plan, col = self._join_corr_scalar(plan, node.query)
            # transform shallow-copies nodes, so match on the shared bound
            # plan object rather than node identity
            target = node.query
            pred = E.transform(
                pred, lambda n: col if isinstance(n, E.ScalarSubquery)
                and n.query is target else n)
        f = L.Filter(input=plan, predicate=pred)
        f.schema = plan.schema
        exprs = []
        for i, fld in enumerate(orig_schema):
            c = E.Column(fld.name, index=i)
            c.dtype = fld.dtype
            exprs.append(c)
        pr = L.Project(input=f, exprs=exprs, names=list(orig_schema.names))
        pr.schema = T.Schema(list(orig_schema.fields))
        return pr

    def _join_corr_scalar(self, plan: L.LogicalPlan, sub: L.LogicalPlan):
        """-> (LEFT-joined plan, bound column for the subquery's value)."""
        proj: Optional[L.Project] = None
        node = sub
        if isinstance(node, L.Project):
            proj, node = node, node.input
        if not isinstance(node, L.Aggregate) or node.group_exprs:
            raise NotSupportedError(
                "correlated scalar subquery must be a single ungrouped "
                "aggregate")
        inp, outer_keys, inner_cols, residual = self._decorrelate(
            node.input, plan.schema)
        if residual is not None or not outer_keys:
            raise NotSupportedError(
                "correlated scalar subquery needs equality correlation")
        k = len(inner_cols)
        agg = L.Aggregate(input=inp, group_exprs=inner_cols,
                          group_names=[f"__ck{i}" for i in range(k)],
                          aggs=list(node.aggs),
                          agg_names=list(node.agg_names))
        agg.schema = T.Schema(
            [T.Field(f"__ck{i}", c.dtype, True)
             for i, c in enumerate(inner_cols)] + list(node.schema.fields))
        # value projection on top of the grouped aggregate: the original
        # projection's column refs shift by k (group keys now lead). A second
        # "empty-set" variant substitutes each aggregate with its value over
        # zero rows (COUNT -> 0, others -> NULL): an ungrouped scalar subquery
        # always returns one row, so no-match outer rows must see THAT value,
        # not plain NULL (e.g. `(SELECT count(*) ...) = 0` keeps them).
        count_idx = {i for i, a in enumerate(node.aggs)
                     if a.func in (E.AggFunc.COUNT, E.AggFunc.COUNT_STAR)}

        def shift(n):
            if isinstance(n, E.Column):
                c = E.Column(n.name, index=n.index + k)
                c.dtype = n.dtype
                return c
            return n

        def on_empty(n):
            if isinstance(n, E.Column):
                if n.index in count_idx:
                    zero = E.Literal(value=0, literal_type=T.INT64)
                    zero.dtype = n.dtype or T.INT64
                    return zero
                nul = E.Literal(value=None, literal_type=n.dtype)
                nul.dtype = n.dtype
                return nul
            return n
        if proj is not None:
            vexpr = E.transform(copy.deepcopy(proj.exprs[0]), shift)
            empty_expr = E.transform(copy.deepcopy(proj.exprs[0]), on_empty)
        else:
            c0 = E.Column(node.schema.fields[0].name, index=0)
            c0.dtype = node.schema.fields[0].dtype
            vexpr = shift(c0)
            empty_expr = on_empty(c0)
        present = E.Literal(value=True, literal_type=T.BOOL)
        present.dtype = T.BOOL
        exprs = [vexpr, present]
        names = ["__sv", "__pv"]
        for i, c in enumerate(inner_cols):
            kc = E.Column(f"__ck{i}", index=i)
            kc.dtype = c.dtype
            exprs.append(kc)
            names.append(f"__ck{i}")
        pr = L.Project(input=agg, exprs=exprs, names=names)
        pr.schema = T.Schema([T.Field(n, e.dtype, True)
                              for n, e in zip(names, exprs)])
        right_keys = []
        for i, c in enumerate(inner_cols):
            rc = E.Column(f"__ck{i}", index=2 + i)
            rc.dtype = c.dtype
            right_keys.append(rc)
        outer_keys, right_keys = _coerce_key_lists(outer_keys, right_keys)
        j = L.Join(left=plan, right=pr, join_type=A.JoinType.LEFT,
                   left_keys=outer_keys, right_keys=right_keys)
        j.schema = T.Schema(list(plan.schema.fields) + list(pr.schema.fields))
        n_left = len(plan.schema)
        sv = E.Column("__sv", index=n_left)
        sv.dtype = vexpr.dtype
        pv = E.Column("__pv", index=n_left + 1)
        pv.dtype = T.BOOL
        miss = E.IsNull(operand=pv)
        miss.dtype = T.BOOL
        val = E.Case(whens=[(miss, empty_expr)], else_=sv)
        val.dtype = vexpr.dtype
        return j, val

    def _decorrelate(self, sub: L.LogicalPlan, outer_schema,
                     allow_residual: bool = False):
        """Pull correlated equality predicates (OuterRef = inner_col) out of the
        subquery plan, returning (rewritten_sub, outer_keys, inner_key_cols,
        residual). Inner key columns are appended to the subquery output; each
        stripped predicate remembers the schema its inner side was bound
        against so the keys are attached at a projection with a MATCHING input
        schema. With `allow_residual`, NON-equality correlated conjuncts (e.g.
        q21's l2.l_suppkey <> l1.l_suppkey) are also stripped and returned as
        one predicate re-based against concat(outer, inner) — the caller
        attaches it as the join residual."""
        corr: list[tuple[ScopeEntry, E.Expr, T.Schema]] = []
        residuals: list[tuple[E.Expr, T.Schema]] = []

        def strip(plan: L.LogicalPlan) -> L.LogicalPlan:
            if isinstance(plan, L.Filter):
                kept = []
                for c in _split_conjuncts(plan.predicate):
                    pair = _extract_corr_eq(c)
                    if pair is not None:
                        corr.append((pair[0], pair[1], plan.input.schema))
                    else:
                        if any(isinstance(n, OuterRef) for n in E.walk(c)):
                            if allow_residual and all(
                                    n.level == 1 for n in E.walk(c)
                                    if isinstance(n, OuterRef)):
                                residuals.append((c, plan.input.schema))
                                continue
                            raise NotSupportedError(
                                f"unsupported correlated predicate: {c!r}")
                        kept.append(c)
                inner = strip(plan.input)
                p = _and_all(kept)
                if p is None:
                    return inner
                f = L.Filter(input=inner, predicate=p)
                f.schema = inner.schema
                return f
            for i, ch in enumerate(plan.children()):
                new = strip(ch)
                if new is not ch:
                    _replace_child(plan, i, new)
            return plan

        sub = strip(sub)
        has_outer = any(isinstance(n, OuterRef) for p in L.walk_plan(sub)
                        for ex in _plan_exprs(p) for n in E.walk(ex))
        if has_outer:
            raise NotSupportedError("correlated reference outside WHERE equality")

        # every inner expression the join must see: the corr key exprs, plus
        # each inner column a residual conjunct references (appended the same
        # way, so e.g. q21's `l2.l_suppkey <> l1.l_suppkey` survives the
        # SELECT-1 projection)
        res_slots: dict[int, int] = {}  # inner col index -> appended slot
        extra: list[tuple[E.Expr, T.Schema]] = [
            (ie, sc) for _, ie, sc in corr]
        for c, sc in residuals:
            for ncol in E.walk(c):
                if isinstance(ncol, E.Column) and ncol.index not in res_slots:
                    res_slots[ncol.index] = len(extra)
                    cc = copy.deepcopy(ncol)
                    extra.append((cc, sc))

        outer_keys, inner_cols = [], []
        base_n = len(sub.schema)
        if extra:
            for outer_entry, _, _ in corr:
                oc = E.Column(outer_entry.name, index=outer_entry.index)
                oc.dtype = outer_entry.dtype
                outer_keys.append(oc)
            if isinstance(sub, L.Project) and all(
                    sc == sub.input.schema for _, sc in extra):
                # extend the subquery's own projection: the stripped predicates
                # were bound against exactly its input schema
                base_n = len(sub.exprs)
                for k, (ie, _) in enumerate(extra):
                    sub.exprs.append(ie)
                    sub.names.append(f"__corr_{k}")
                sub.schema = T.Schema(list(sub.schema.fields) + [
                    T.Field(f"__corr_{k}", ie.dtype, True)
                    for k, (ie, _) in enumerate(extra)])
            elif all(sc == sub.schema for _, sc in extra):
                # keys bound against the subquery output itself: wrap once
                exprs, names = [], []
                for i, f in enumerate(sub.schema):
                    c = E.Column(f.name, index=i)
                    c.dtype = f.dtype
                    exprs.append(c)
                    names.append(f.name)
                base_n = len(exprs)
                for k, (ie, _) in enumerate(extra):
                    exprs.append(ie)
                    names.append(f"__corr_{k}")
                pr = L.Project(input=sub, exprs=exprs, names=names)
                pr.schema = T.Schema([T.Field(n, ex.dtype, True)
                                      for n, ex in zip(names, exprs)])
                sub = pr
            else:
                raise NotSupportedError(
                    "correlated predicate below a schema-changing operator "
                    "(aggregate/join) is not supported yet")
            for k in range(len(corr)):
                ic = E.Column(f"__corr_{k}", index=base_n + k)
                ic.dtype = extra[k][0].dtype
                inner_cols.append(ic)

        # re-base residual conjuncts against concat(outer, sub_output)
        residual = None
        if residuals:
            n_outer = len(outer_schema)

            def rebase(n):
                if isinstance(n, OuterRef):
                    c = E.Column(n.entry.name, index=n.entry.index)
                    c.dtype = n.entry.dtype
                    return c
                if isinstance(n, E.Column):
                    c = E.Column(n.name,
                                 index=n_outer + base_n + res_slots[n.index])
                    c.dtype = n.dtype
                    return c
                return n
            residual = _and_all([E.transform(c, rebase) for c, _ in residuals])
        return sub, outer_keys, inner_cols, residual

    # --- aggregates ---

    def _contains_agg(self, e: E.Expr) -> bool:
        return any(isinstance(n, E.Aggregate) for n in E.walk(e))

    def _build_aggregate(self, plan, scope, bound_groups, bound_proj, bound_having,
                         group_items, names):
        # collect distinct aggregate expressions
        aggs: list[E.Aggregate] = []

        def collect(e):
            for n in E.walk(e):
                if isinstance(n, E.Aggregate) and not any(_expr_eq(n, a) for a in aggs):
                    aggs.append(n)
        for b in bound_proj:
            collect(b)
        if bound_having is not None:
            collect(bound_having)
        for a in aggs:
            if a.arg is not None and self._contains_agg(a.arg):
                raise PlanError("nested aggregate functions are not allowed")
            a.dtype = agg_result_type(a.func, a.arg.dtype if a.arg else None)

        group_names = []
        for i, (g, gi) in enumerate(zip(bound_groups, group_items)):
            if isinstance(gi, E.Column):
                group_names.append(gi.name_hint())
            else:
                group_names.append(f"__group_{i}")
        agg_names = [f"__agg_{i}" for i in range(len(aggs))]

        node = L.Aggregate(input=plan, group_exprs=bound_groups,
                           group_names=group_names, aggs=aggs, agg_names=agg_names)
        gfields = [T.Field(n, g.dtype, True) for n, g in zip(group_names, bound_groups)]
        afields = [T.Field(n, a.dtype, True) for n, a in zip(agg_names, aggs)]
        node.schema = T.Schema(gfields + afields)

        # rewrite projections / having in terms of aggregate output
        def rewrite(e: E.Expr) -> E.Expr:
            for i, g in enumerate(bound_groups):
                if _expr_eq(e, g):
                    c = E.Column(group_names[i], index=i)
                    c.dtype = g.dtype
                    return c
            if isinstance(e, E.Aggregate):
                for j, a in enumerate(aggs):
                    if _expr_eq(e, a):
                        c = E.Column(agg_names[j], index=len(bound_groups) + j)
                        c.dtype = a.dtype
                        return c
                # a late aggregate (ORDER BY over an aggregate not in the SELECT
                # list): extend the Aggregate node in place
                e.dtype = agg_result_type(e.func, e.arg.dtype if e.arg else None)
                aggs.append(e)
                agg_names.append(f"__agg_{len(aggs) - 1}")
                node.schema = T.Schema(list(node.schema.fields) +
                                       [T.Field(agg_names[-1], e.dtype, True)])
                c = E.Column(agg_names[-1], index=len(node.schema) - 1)
                c.dtype = e.dtype
                return c
            n = copy.copy(e)
            if isinstance(n, E.Binary):
                n.left = rewrite(n.left)
                n.right = rewrite(n.right)
            elif isinstance(n, (E.Not, E.Negate, E.IsNull, E.Cast)):
                n.operand = rewrite(n.operand)
            elif isinstance(n, E.Case):
                n.whens = [(rewrite(c_), rewrite(v)) for c_, v in n.whens]
                n.else_ = rewrite(n.else_) if n.else_ is not None else None
            elif isinstance(n, E.InList):
                n.operand = rewrite(n.operand)
                n.items = [rewrite(i) for i in n.items]
            elif isinstance(n, E.Like):
                n.operand = rewrite(n.operand)
            elif isinstance(n, E.Func):
                n.args = [rewrite(a) for a in n.args]
            elif isinstance(n, E.Column):
                raise PlanError(
                    f"column {n.name!r} must appear in GROUP BY or an aggregate")
            return n

        new_proj = [rewrite(b) for b in bound_proj]
        new_having = rewrite(bound_having) if bound_having is not None else None
        return node, new_proj, new_having, Scope.from_schema(node.schema), rewrite

    def _filter(self, plan: L.LogicalPlan, pred: E.Expr) -> L.LogicalPlan:
        f = L.Filter(input=plan, predicate=pred)
        f.schema = plan.schema
        return f

    # --- expression binding ---

    def bind_expr(self, e: E.Expr, scope: Scope, plan) -> E.Expr:
        e = self._fold_intervals(copy.deepcopy(e))
        return self._bind_e(e, scope)

    def _bind_e(self, e: E.Expr, scope: Scope) -> E.Expr:
        if isinstance(e, OuterRef):
            return e
        if isinstance(e, E.Column):
            ent, lvl = scope.resolve(e.name)
            if ent is None:
                raise PlanError(f"column not found: {e.name}")
            if lvl > 0:
                o = OuterRef(name=e.name, entry=ent, level=lvl)
                o.dtype = ent.dtype
                return o
            c = E.Column(e.name, index=ent.index)
            c.dtype = ent.dtype
            return c
        if isinstance(e, E.Literal):
            e.dtype = e.literal_type or _literal_type_of(e.value)
            return e
        if isinstance(e, E.Alias):
            b = self._bind_e(e.operand, scope)
            a = E.Alias(operand=b, alias=e.alias)
            a.dtype = b.dtype
            return a
        if isinstance(e, E.Binary):
            left = self._bind_e(e.left, scope)
            right = self._bind_e(e.right, scope)
            n = E.Binary(op=e.op, left=left, right=right)
            if e.op in (E.BinOp.AND, E.BinOp.OR):
                for side in (left, right):
                    if side.dtype != T.BOOL:
                        raise PlanError(f"{e.op.value} requires boolean operands")
                n.dtype = T.BOOL
            elif e.op in E.COMPARISONS:
                _check_comparable(left, right, e.op)
                n.dtype = T.BOOL
            else:
                n.dtype = _arith_type(left, right, e.op)
            return n
        if isinstance(e, E.Not):
            b = self._bind_e(e.operand, scope)
            if b.dtype != T.BOOL:
                raise PlanError("NOT requires a boolean operand")
            n = E.Not(operand=b)
            n.dtype = T.BOOL
            return n
        if isinstance(e, E.Negate):
            b = self._bind_e(e.operand, scope)
            if not b.dtype.is_numeric:
                raise PlanError("unary minus requires a numeric operand")
            n = E.Negate(operand=b)
            n.dtype = b.dtype
            return n
        if isinstance(e, E.IsNull):
            b = self._bind_e(e.operand, scope)
            n = E.IsNull(operand=b, negated=e.negated)
            n.dtype = T.BOOL
            return n
        if isinstance(e, E.Cast):
            b = self._bind_e(e.operand, scope)
            n = E.Cast(operand=b, to=e.to)
            n.dtype = e.to
            return n
        if isinstance(e, E.Case):
            whens = [(self._bind_e(c, scope), self._bind_e(v, scope))
                     for c, v in e.whens]
            else_ = self._bind_e(e.else_, scope) if e.else_ is not None else None
            out = T.NULL
            for c, v in whens:
                if c.dtype != T.BOOL:
                    raise PlanError("CASE WHEN condition must be boolean")
                out = T.common_type(out, v.dtype)
            if else_ is not None:
                out = T.common_type(out, else_.dtype)
            if out == T.NULL:
                out = T.INT32
            n = E.Case(whens=whens, else_=else_)
            n.dtype = out
            return n
        if isinstance(e, E.InList):
            b = self._bind_e(e.operand, scope)
            items = [self._bind_e(i, scope) for i in e.items]
            n = E.InList(operand=b, items=items, negated=e.negated)
            n.dtype = T.BOOL
            return n
        if isinstance(e, E.Like):
            b = self._bind_e(e.operand, scope)
            if not b.dtype.is_string:
                raise PlanError("LIKE requires a string operand")
            n = E.Like(operand=b, pattern=e.pattern, negated=e.negated,
                       case_insensitive=e.case_insensitive)
            n.dtype = T.BOOL
            return n
        if isinstance(e, E.Func):
            name = e.name.lower()
            args = [self._bind_e(a, scope) for a in e.args]
            n = E.Func(name=name, args=args)
            if name in self.udfs:
                n.dtype = self.udfs[name].return_type(
                    [a.dtype for a in args])
            elif name in _FUNC_TYPES:
                rt = _FUNC_TYPES[name]
                n.dtype = rt if rt is not None else args[0].dtype
            elif name in ("coalesce", "nullif"):
                out = T.NULL
                for a in args:
                    out = T.common_type(out, a.dtype)
                n.dtype = out if out != T.NULL else T.INT32
            else:
                raise PlanError(f"unknown function: {name}")
            return n
        if isinstance(e, E.Window):
            args = [self._bind_e(a, scope) for a in e.args]
            part = [self._bind_e(p, scope) for p in e.partition_by]
            order = [self._bind_e(o, scope) for o in e.order_by]
            agg = None
            if e.agg is not None:
                warg = self._bind_e(e.agg.arg, scope) \
                    if e.agg.arg is not None else None
                agg = E.Aggregate(func=e.agg.func, arg=warg)
                agg.dtype = agg_result_type(
                    e.agg.func, warg.dtype if warg is not None else None)
            n = E.Window(func=e.func, agg=agg, args=args, partition_by=part,
                         order_by=order, ascending=list(e.ascending),
                         nulls_first=list(e.nulls_first))
            if e.func == "agg":
                n.dtype = agg.dtype
            elif e.func in ("lag", "lead"):
                if len(args) == 2 and not (
                        isinstance(args[1], E.Literal)
                        and isinstance(args[1].value, int)):
                    raise PlanError(f"{e.func} offset must be an integer "
                                    "literal")
                n.dtype = args[0].dtype
            else:
                n.dtype = T.INT64
            return n
        if isinstance(e, E.Aggregate):
            arg = self._bind_e(e.arg, scope) if e.arg is not None else None
            n = E.Aggregate(func=e.func, arg=arg, distinct=e.distinct)
            n.dtype = agg_result_type(e.func, arg.dtype if arg else None)
            return n
        if isinstance(e, E.ScalarSubquery):
            sub = self.bind_query(e.query, scope)
            return self._bind_scalar_subquery(e, sub, scope)
        if isinstance(e, E.Interval):
            raise PlanError("INTERVAL is only valid in +/- date arithmetic")
        if isinstance(e, (E.InSubquery, E.Exists)):
            raise NotSupportedError(
                f"{type(e).__name__} is only supported as a top-level WHERE conjunct")
        raise PlanError(f"cannot bind expression {e!r}")

    def _bind_scalar_subquery(self, e: E.ScalarSubquery, sub: L.LogicalPlan,
                              scope: Scope) -> E.Expr:
        if len(sub.schema) != 1:
            raise PlanError("scalar subquery must return exactly one column")
        has_outer = any(isinstance(n, OuterRef) for p in L.walk_plan(sub)
                        for ex in _plan_exprs(p) for n in E.walk(ex))
        if has_outer and not self._allow_corr_scalar:
            # only WHERE conjuncts have the group-by + join decorrelation
            # (_apply_corr_scalar); anywhere else the OuterRefs would leak to
            # the executor
            raise NotSupportedError(
                "correlated scalar subqueries are only supported in WHERE")
        n = E.ScalarSubquery(query=sub)  # query now holds the BOUND PLAN
        n.dtype = sub.schema.fields[0].dtype
        return n

    # --- interval folding ---

    def _fold_intervals(self, e: E.Expr) -> E.Expr:
        def fold(n: E.Expr) -> E.Expr:
            if isinstance(n, E.Binary) and n.op in (E.BinOp.ADD, E.BinOp.SUB):
                l, r = n.left, n.right
                if isinstance(r, E.Interval):
                    if isinstance(l, E.Literal) and l.literal_type is T.DATE32:
                        days = _shift_date(l.value, r,
                                           negate=(n.op is E.BinOp.SUB))
                        return E.Literal(value=days, literal_type=T.DATE32)
                    if r.months == 0:
                        # non-literal date +/- day interval: plain day arithmetic
                        d = E.Literal(value=r.days, literal_type=T.INT32)
                        return E.Binary(op=n.op, left=l, right=d)
                    raise NotSupportedError(
                        "month/year intervals require a literal date operand")
                if isinstance(l, E.Interval):
                    raise NotSupportedError("interval must be the right operand")
            return n
        return E.transform(e, fold)


# --- helpers ---------------------------------------------------------------------

def _shift_date(days_since_epoch: int, iv: E.Interval, negate: bool) -> int:
    d = _dt.date.fromordinal(_EPOCH_ORD + days_since_epoch)
    months = -iv.months if negate else iv.months
    day_shift = -iv.days if negate else iv.days
    if months:
        total = d.year * 12 + (d.month - 1) + months
        y, m = divmod(total, 12)
        import calendar
        dd = min(d.day, calendar.monthrange(y, m + 1)[1])
        d = _dt.date(y, m + 1, dd)
    d = d + _dt.timedelta(days=day_shift)
    return d.toordinal() - _EPOCH_ORD


def _literal_type_of(v) -> T.DataType:
    if v is None:
        return T.NULL
    if isinstance(v, bool):
        return T.BOOL
    if isinstance(v, int):
        return T.INT32 if -(2 ** 31) <= v < 2 ** 31 else T.INT64
    if isinstance(v, float):
        return T.FLOAT64
    if isinstance(v, str):
        return T.STRING
    raise PlanError(f"unsupported literal {v!r}")


def _check_comparable(left: E.Expr, right: E.Expr, op) -> None:
    a, b = left.dtype, right.dtype
    if a.is_string != b.is_string:
        raise PlanError(f"cannot compare {a} with {b}")
    if not a.is_string:
        try:
            T.common_type(a, b)
        except TypeError as ex:
            raise PlanError(str(ex)) from None


def _arith_type(left: E.Expr, right: E.Expr, op) -> T.DataType:
    a, b = left.dtype, right.dtype
    if a.id == T.TypeId.DATE32 and b.is_integer:
        return T.DATE32
    if b.id == T.TypeId.DATE32 and a.is_integer and op is E.BinOp.ADD:
        return T.DATE32
    if a.id == T.TypeId.DATE32 and b.id == T.TypeId.DATE32 and op is E.BinOp.SUB:
        return T.INT32  # date difference in days
    if not (a.is_numeric or a.id == T.TypeId.NULL) or \
            not (b.is_numeric or b.id == T.TypeId.NULL):
        raise PlanError(f"arithmetic on non-numeric types {a}, {b}")
    if op is E.BinOp.DIV:
        ct = T.common_type(a, b)
        return ct
    return T.common_type(a, b)


def _split_conjuncts(e: E.Expr) -> list[E.Expr]:
    if isinstance(e, E.Binary) and e.op is E.BinOp.AND:
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


_split_conjuncts_ast = _split_conjuncts


def _and_all(parts: list[E.Expr]) -> Optional[E.Expr]:
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        n = E.Binary(op=E.BinOp.AND, left=out, right=p)
        n.dtype = T.BOOL
        out = n
    return out


def _or_all(parts: list[E.Expr]) -> E.Expr:
    out = parts[0]
    for p in parts[1:]:
        n = E.Binary(op=E.BinOp.OR, left=out, right=p)
        n.dtype = T.BOOL
        out = n
    return out


def _coerce_key_lists(lks: list[E.Expr], rks: list[E.Expr]):
    pairs = [coerce_key_pair(lk, rk) for lk, rk in zip(lks, rks)]
    return [p[0] for p in pairs], [p[1] for p in pairs]


def coerce_key_pair(lk: E.Expr, rk: E.Expr) -> tuple[E.Expr, E.Expr]:
    """Equi-join keys must produce IDENTICAL hash/equality lane structures on both
    sides (exec/join.py builds 3 lanes for floats vs 1 for ints, and DATE32 days
    vs TIMESTAMP micros differ in unit), so coerce both sides to their common
    type — the same promotion _compile_numeric_binary applies to comparisons."""
    a, b = lk.dtype, rk.dtype
    if a == b or (a.is_string and b.is_string):
        return lk, rk
    ct = T.common_type(a, b)

    def cast(e: E.Expr) -> E.Expr:
        if e.dtype == ct:
            return e
        c = E.Cast(operand=e, to=ct)
        c.dtype = ct
        return c
    return cast(lk), cast(rk)


def _extract_equi_key(c: E.Expr, n_left: int):
    """If conjunct is `expr_L = expr_R` with sides fully on left/right of a join
    (column indices < n_left vs >= n_left), return (left_key, right_key with
    re-based indices); else None."""
    if not (isinstance(c, E.Binary) and c.op is E.BinOp.EQ):
        return None

    def side_of(e):
        idxs = [n.index for n in E.walk(e) if isinstance(n, E.Column)]
        if any(isinstance(n, OuterRef) for n in E.walk(e)):
            return None
        if not idxs:
            return "const"
        if all(i < n_left for i in idxs):
            return "L"
        if all(i >= n_left for i in idxs):
            return "R"
        return None

    sl, sr = side_of(c.left), side_of(c.right)
    if sl == "L" and sr == "R":
        lk, rk = c.left, c.right
    elif sl == "R" and sr == "L":
        lk, rk = c.right, c.left
    else:
        return None
    rk = copy.deepcopy(rk)
    for n in E.walk(rk):
        if isinstance(n, E.Column):
            n.index -= n_left
    return lk, rk


def _plan_has_outer(plan) -> bool:
    return isinstance(plan, L.LogicalPlan) and any(
        isinstance(n, OuterRef) for p in L.walk_plan(plan)
        for ex in _plan_exprs(p) for n in E.walk(ex))


def _contains_corr_scalar(e: E.Expr) -> bool:
    return any(isinstance(n, E.ScalarSubquery) and _plan_has_outer(n.query)
               for n in E.walk(e))


def _extract_corr_eq(c: E.Expr):
    """If conjunct is level-1 OuterRef = inner_expr (either order), return
    (outer_entry, inner_expr); else None. Deeper-nested references (level > 1)
    cannot be decorrelated against the immediate parent and must be rejected by
    the caller's has-outer check."""
    if not (isinstance(c, E.Binary) and c.op is E.BinOp.EQ):
        return None
    l, r = c.left, c.right
    if isinstance(l, OuterRef) and l.level == 1 and not any(
            isinstance(n, OuterRef) for n in E.walk(r)):
        return (l.entry, r)
    if isinstance(r, OuterRef) and r.level == 1 and not any(
            isinstance(n, OuterRef) for n in E.walk(l)):
        return (r.entry, l)
    return None


def _expr_eq(a: E.Expr, b: E.Expr) -> bool:
    """Structural equality of bound expressions (Column compares by index)."""
    if type(a) is not type(b):
        return False
    if isinstance(a, E.Column):
        return a.index == b.index
    if isinstance(a, E.Literal):
        return a.value == b.value and a.literal_type == b.literal_type
    if isinstance(a, E.Binary):
        return a.op is b.op and _expr_eq(a.left, b.left) and _expr_eq(a.right, b.right)
    if isinstance(a, (E.Not, E.Negate)):
        return _expr_eq(a.operand, b.operand)
    if isinstance(a, E.IsNull):
        return a.negated == b.negated and _expr_eq(a.operand, b.operand)
    if isinstance(a, E.Cast):
        return a.to == b.to and _expr_eq(a.operand, b.operand)
    if isinstance(a, E.Aggregate):
        if a.func is not b.func or a.distinct != b.distinct:
            return False
        if (a.arg is None) != (b.arg is None):
            return False
        return a.arg is None or _expr_eq(a.arg, b.arg)
    if isinstance(a, E.Func):
        return a.name == b.name and len(a.args) == len(b.args) and \
            all(_expr_eq(x, y) for x, y in zip(a.args, b.args))
    if isinstance(a, E.Alias):
        return _expr_eq(a.operand, b.operand)
    if isinstance(a, E.Case):
        if len(a.whens) != len(b.whens) or (a.else_ is None) != (b.else_ is None):
            return False
        for (c1, v1), (c2, v2) in zip(a.whens, b.whens):
            if not (_expr_eq(c1, c2) and _expr_eq(v1, v2)):
                return False
        return a.else_ is None or _expr_eq(a.else_, b.else_)
    if isinstance(a, E.InList):
        return a.negated == b.negated and _expr_eq(a.operand, b.operand) and \
            len(a.items) == len(b.items) and \
            all(_expr_eq(x, y) for x, y in zip(a.items, b.items))
    if isinstance(a, E.Like):
        return (a.pattern, a.negated, a.case_insensitive) == \
            (b.pattern, b.negated, b.case_insensitive) and \
            _expr_eq(a.operand, b.operand)
    return a is b


def _dedup_fields(fields: list[T.Field]) -> list[T.Field]:
    """Join output schema: rename right-side collisions with a `right_` prefix
    (parity with the reference's HashJoinExec schema combination,
    crates/engine/src/operators/hash_join.rs:42-66)."""
    seen: dict[str, int] = {}
    out = []
    for f in fields:
        name = f.name
        if name in seen:
            name = f"right_{name}"
            k = 1
            while name in seen:
                k += 1
                name = f"right{k}_{f.name}"
        seen[name] = 1
        out.append(T.Field(name, f.dtype, f.nullable))
    return out


def _plan_exprs(plan: L.LogicalPlan) -> list[E.Expr]:
    if isinstance(plan, L.Filter):
        return [plan.predicate]
    if isinstance(plan, L.Project):
        return list(plan.exprs)
    if isinstance(plan, L.Aggregate):
        return list(plan.group_exprs) + list(plan.aggs)
    if isinstance(plan, L.Join):
        out = list(plan.left_keys) + list(plan.right_keys)
        if plan.residual is not None:
            out.append(plan.residual)
        return out
    if isinstance(plan, L.Sort):
        return list(plan.keys)
    return []


def _replace_child(plan: L.LogicalPlan, i: int, new: L.LogicalPlan) -> None:
    if isinstance(plan, (L.Filter, L.Project, L.Aggregate, L.Sort, L.Limit,
                         L.Distinct)):
        plan.input = new
    elif isinstance(plan, (L.Join, L.SetOpJoin)):
        if i == 0:
            plan.left = new
        else:
            plan.right = new
    elif isinstance(plan, L.Union):
        plan.inputs[i] = new
