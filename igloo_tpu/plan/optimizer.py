"""Logical optimizer.

The reference leans on DataFusion's optimizer on its working path
(`into_optimized_plan`, crates/igloo/src/main.rs:109) and does nothing on its custom
path. We implement the passes that matter for the TPU execution model:

- constant folding (shrinks jit graphs, enables literal-only pruning)
- filter merge + predicate pushdown (through Project/Aggregate/Join/Union down to
  Scan.pushed_filters, so connectors can prune files/row-groups host-side before
  bytes ever move toward HBM)
- projection pruning (Scan.projection — decode only needed Parquet columns; on
  device this is the difference between shipping 16 lanes and 4)

All passes preserve bound-ness: Column.index stays consistent with each node's
input schema (pruning rewrites indices via child mappings).
"""
from __future__ import annotations

import copy
from typing import Optional

from igloo_tpu import types as T
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.plan.binder import (
    _and_all, _extract_equi_key, _split_conjuncts, coerce_key_pair,
)
from igloo_tpu.sql.ast import JoinType


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    _optimize_subqueries(plan)
    plan = fold_constants_pass(plan)
    plan = reorder_cross_joins(plan)
    plan = pushdown_filters(plan)
    plan = semi_join_reduction(plan)
    plan = reorder_adaptive_joins(plan)
    plan = prune_projections(plan)
    return plan


# --- magic-set / semi-join reduction ---------------------------------------

# a key-source subtree must scan at most this much to be cloned as the
# semi-join build side; the aggregate input must scan at least this much for
# the rewrite to pay off
_SEMI_BUILD_MAX_BYTES = 64 << 20
_SEMI_INPUT_MIN_BYTES = 64 << 20


def _est_scan_bytes(p: L.LogicalPlan, include_subqueries: bool = False
                    ) -> Optional[int]:
    """Total estimated source bytes under `p`; None when any scan is
    unsized. With `include_subqueries`, plans embedded in expression
    subqueries count too (the engine's host-routing cap uses this: a tiny
    outer query over a subquery on a huge table must not land on the host)."""
    from igloo_tpu.exec.chunked import estimated_bytes
    total = 0
    for n in L.walk_plan(p):
        if isinstance(n, L.Scan):
            if n.provider is None:
                continue
            nb = estimated_bytes(n.provider)
            if nb is None:
                return None
            total += nb
        if not include_subqueries:
            continue
        for e in _node_exprs(n):
            stack = [e]
            while stack:
                x = stack.pop()
                sub = getattr(x, "query", None)
                if isinstance(sub, L.LogicalPlan):
                    st = _est_scan_bytes(sub, include_subqueries=True)
                    if st is None:
                        return None
                    total += st
                stack.extend(x.children())
    return total


def _key_source(p: L.LogicalPlan, idx: int):
    """Trace output column `idx` of `p` to an UNDER-filtered source subtree:
    the subtree's values for that column are a SUPERSET of the values `p` can
    produce (filters/joins above only drop rows), which is exactly what a
    semi-join build side needs. Returns (subtree, col idx) or (None, 0)."""
    if isinstance(p, L.Filter):
        sub, si = _key_source(p.input, idx)
        if sub is p.input and si == idx:
            return p, idx  # nothing was cut below: keep the filter (tighter)
        return sub, si
    if isinstance(p, L.Project):
        e = p.exprs[idx]
        if isinstance(e, E.Alias):
            e = e.operand
        if not isinstance(e, E.Column):
            return None, 0
        sub, si = _key_source(p.input, e.index)
        if sub is p.input and si == e.index:
            return p, idx  # keep the projection node (schema stays aligned)
        return sub, si
    if isinstance(p, L.Join):
        lw = len(p.left.schema)
        if idx < lw and p.join_type in (JoinType.INNER, JoinType.CROSS,
                                        JoinType.LEFT, JoinType.SEMI,
                                        JoinType.ANTI):
            return _key_source(p.left, idx)
        if idx >= lw and p.join_type in (JoinType.INNER, JoinType.CROSS,
                                        JoinType.LEFT):
            # right side of a LEFT join adds NULL padding only; null keys
            # never equi-match, so the unpadded source is still a superset
            # of the matchable values
            return _key_source(p.right, idx - lw)
        return None, 0
    return p, idx


def semi_join_reduction(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Magic-set rewrite: Join(OUTER-SIDE, Aggregate-by-k) where the join key
    on the outer side traces to a SMALL subtree -> filter the aggregate's
    input with a semi join against that subtree's distinct keys.

    TPC-H q17 is the canonical case: the decorrelated per-part average
    aggregates ALL 6M lineitem rows into 200k groups, but the outer query
    joins the result against ~200 filtered parts — aggregating the other
    199,800 groups is pure waste (and on the static-shape device path, the
    full-width aggregate dominates the query). The reference has no analog
    (DataFusion's optimizer lacks magic sets too); the rewrite matters here
    because TPU aggregation cost scales with padded input lanes.

    Correctness: the semi join drops whole groups whose key is outside the
    source's key SUPERSET — groups that could never equi-match the outer
    side (null group keys included: null never equi-matches). Rows within
    retained groups are untouched, so aggregate values are identical."""
    for name in ("input", "left", "right"):
        ch = getattr(plan, name, None)
        if isinstance(ch, L.LogicalPlan):
            setattr(plan, name, semi_join_reduction(ch))
    if isinstance(plan, L.Union):
        plan.inputs = [semi_join_reduction(c) for c in plan.inputs]
    if not (isinstance(plan, L.Join) and
            plan.join_type in (JoinType.INNER, JoinType.LEFT, JoinType.SEMI)
            and len(plan.left_keys) == 1 and
            isinstance(plan.left_keys[0], E.Column) and
            isinstance(plan.right_keys[0], E.Column)):
        return plan
    # locate an Aggregate under identity projections on the right, with the
    # join key landing on one of its GROUP columns
    node, idx = plan.right, plan.right_keys[0].index
    while isinstance(node, L.Project):
        e = node.exprs[idx]
        if isinstance(e, E.Alias):
            e = e.operand
        if not isinstance(e, E.Column):
            return plan
        node, idx = node.input, e.index
    if not isinstance(node, L.Aggregate) or idx >= len(node.group_exprs):
        return plan
    if isinstance(node.input, L.Join) and \
            node.input.join_type is JoinType.SEMI:
        return plan  # already reduced
    in_bytes = _est_scan_bytes(node.input)
    if in_bytes is None or in_bytes < _SEMI_INPUT_MIN_BYTES:
        return plan
    src, src_idx = _key_source(plan.left, plan.left_keys[0].index)
    if src is None:
        return plan
    # the source must be SELECTIVE: an unfiltered base table as the build
    # side filters nothing (FK integrity makes every group survive) and its
    # distinct-keys subplan is pure cost — e.g. q18's o_orderkey IN (...)
    # traces to the bare orders scan and must NOT rewrite
    if not any(isinstance(n, L.Filter) or
               (isinstance(n, L.Scan) and n.pushed_filters)
               for n in L.walk_plan(src)):
        return plan
    sb = _est_scan_bytes(src)
    if sb is None or sb > _SEMI_BUILD_MAX_BYTES:
        return plan
    gk = node.group_exprs[idx]
    f = src.schema.fields[src_idx]
    if gk.dtype != f.dtype:
        return plan
    col = E.Column(f.name, index=src_idx)
    col.dtype = f.dtype
    proj = L.Project(input=L.copy_plan(src), exprs=[col], names=[f.name])
    proj.schema = T.Schema([f])
    dist = L.Distinct(input=proj)
    dist.schema = proj.schema
    bcol = E.Column(f.name, index=0)
    bcol.dtype = f.dtype
    semi = L.Join(left=node.input, right=dist, join_type=JoinType.SEMI,
                  left_keys=[copy.deepcopy(gk)], right_keys=[bcol])
    semi.schema = node.input.schema
    node.input = semi
    return plan


def _node_exprs(node: L.LogicalPlan) -> list:
    if isinstance(node, L.Filter):
        return [node.predicate]
    if isinstance(node, L.Project):
        return list(node.exprs)
    if isinstance(node, L.Aggregate):
        return list(node.group_exprs) + [a.arg for a in node.aggs
                                         if a.arg is not None]
    if isinstance(node, L.Join):
        out = list(node.left_keys) + list(node.right_keys)
        if node.residual is not None:
            out.append(node.residual)
        return out
    if isinstance(node, L.Sort):
        return list(node.keys)
    if isinstance(node, L.Window):
        return (list(node.partition_exprs) + list(node.order_exprs)
                + list(node.funcs))
    if isinstance(node, L.Scan):
        return list(node.pushed_filters)
    return []


def _optimize_subqueries(plan: L.LogicalPlan) -> None:
    """Run the FULL pass pipeline over every bound scalar-subquery plan.
    Without this, subquery joins stay in their raw bound shape — Filters over
    CROSS joins — which the executor expands as a full cross product (TPC-H
    Q11's HAVING subquery: |partsupp| x |supplier| = 8e9 candidate slots at
    SF1). Recursion through optimize() also covers nested subqueries."""
    for node in L.walk_plan(plan):
        for e in _node_exprs(node):
            for n in E.walk(e):
                if isinstance(n, E.ScalarSubquery) and \
                        isinstance(n.query, L.LogicalPlan):
                    n.query = optimize(n.query)


# --- join reorder (cross-product avoidance) ---------------------------------------


def reorder_cross_joins(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Reorder a comma-FROM cross-join chain by WHERE-predicate connectivity.

    The binder builds comma FROM lists as a left-deep CROSS chain in written
    order; pushdown turns spanning equalities into join keys PAIRWISE, so a
    prefix with no predicate edge stays a true cross join — TPC-H Q2's
    `FROM part, supplier, partsupp, ...` becomes part x supplier, an |P|x|S|
    candidate expansion whose static-shape program is catastrophic (the
    expand at 8M lanes compiles for tens of minutes on TPU).

    This pass flattens Filter-over-pure-CROSS chains and checks prefix
    connectivity under the filter's conjuncts. Chains already connected in
    written order are returned UNCHANGED (plans stay bit-identical); otherwise
    relations greedily reorder so every join has at least one predicate edge
    when one exists, and a Project on top restores the original column order
    so everything above is untouched."""
    for name in ("input", "left", "right"):
        ch = getattr(plan, name, None)
        if isinstance(ch, L.LogicalPlan):
            setattr(plan, name, reorder_cross_joins(ch))
    if isinstance(plan, L.Union):
        plan.inputs = [reorder_cross_joins(c) for c in plan.inputs]
    if not isinstance(plan, L.Filter):
        return plan
    # walk from the filter stack down to the cross chain through structures
    # that preserve the chain's column indexes as a PREFIX: further Filters
    # (conjuncts collected — the binder stacks one Filter per conjunct),
    # identity-prefix Projects, and Join left spines (e.g. the decorrelation
    # LEFT join wrapping the FROM chain)
    conjuncts: list[E.Expr] = []
    parent, pattr = None, None
    node: L.LogicalPlan = plan
    rels: list = []
    while True:
        if isinstance(node, L.Filter):
            conjuncts += _split_conjuncts(node.predicate)
            parent, pattr, node = node, "input", node.input
        elif isinstance(node, L.Project) and _is_identity_prefix(node):
            parent, pattr, node = node, "input", node.input
        elif isinstance(node, L.Join):
            rels = _flatten_cross(node)
            if len(rels) >= 3:
                break
            parent, pattr, node = node, "left", node.left
        else:
            return plan
    if len(rels) < 3:
        return plan

    offsets = []
    off = 0
    for r in rels:
        offsets.append(off)
        off += len(r.schema)

    def rel_of(col_idx: int) -> int:
        for i in range(len(rels) - 1, -1, -1):
            if col_idx >= offsets[i]:
                return i
        return 0

    width = off
    edges: set[tuple[int, int]] = set()
    for c in conjuncts:
        cols = _cols_of(c)
        if not cols or any(i >= width for i in cols):
            continue  # references columns outside the chain
        touched = {rel_of(i) for i in cols}
        if len(touched) == 2:
            a, b = sorted(touched)
            edges.add((a, b))

    def connected(i: int, placed: set[int]) -> bool:
        return any((min(i, p), max(i, p)) in edges for p in placed)

    order = [0]
    remaining = list(range(1, len(rels)))
    while remaining:
        nxt = next((i for i in remaining if connected(i, set(order))),
                   remaining[0])
        order.append(nxt)
        remaining.remove(nxt)
    # written order already avoids cross products (or nothing improves):
    # leave the plan bit-identical
    if order == list(range(len(rels))):
        return plan

    chain = rels[order[0]]
    for i in order[1:]:
        j = L.Join(left=chain, right=rels[i], join_type=JoinType.CROSS)
        j.schema = T.Schema(list(chain.schema.fields) +
                            list(rels[i].schema.fields))
        chain = j
    # restore the ORIGINAL column order above the reordered chain
    new_offsets = {}
    off = 0
    for i in order:
        new_offsets[i] = off
        off += len(rels[i].schema)
    exprs, names = [], []
    orig_schema = node.schema
    for i, r in enumerate(rels):
        for k, f in enumerate(r.schema.fields):
            c = E.Column(f.name, index=new_offsets[i] + k)
            c.dtype = f.dtype
            exprs.append(c)
            names.append(orig_schema.fields[offsets[i] + k].name)
    proj = L.Project(input=chain, exprs=exprs, names=names)
    proj.schema = orig_schema
    setattr(parent, pattr, proj)
    return plan


def _flatten_cross(j: L.LogicalPlan) -> list[L.LogicalPlan]:
    if isinstance(j, L.Join) and j.join_type is JoinType.CROSS \
            and not j.left_keys and j.residual is None:
        return _flatten_cross(j.left) + [j.right]
    return [j]


def _is_identity_prefix(p: L.Project) -> bool:
    """Every projected expr is Column(index == position): the project only
    drops trailing columns, so lower column indexes pass through unchanged."""
    return all(isinstance(e, E.Column) and e.index == i
               for i, e in enumerate(p.exprs))


# --- adaptive join reorder (observed cardinalities) -------------------------------


import threading

_adaptive_tls = threading.local()


def last_adaptive_decisions() -> list:
    """Reorder decisions from the most recent optimize() on this thread —
    the engine appends them to EXPLAIN output and the coordinator merges
    them into last_metrics["adaptive"] (docs/adaptive.md). Cleared at the
    start of every reorder pass, so a query that reorders nothing (or runs
    with IGLOO_ADAPTIVE=0) reports nothing."""
    return list(getattr(_adaptive_tls, "decisions", ()))


def reorder_adaptive_joins(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Reorder commutable INNER equi-join spines greedily by effective build
    size: smallest relation first, then smallest CONNECTED relation at each
    step, so the cheapest effective build side sorts/probes first and join
    intermediates stay narrow (q9's six-table chain, q18's chain above the
    semi join are the targets).

    Effective size is OBSERVED output cardinality x estimated row width when
    the AdaptiveStats store (exec/hints.py) holds an observation for the
    subtree's structural fingerprint — post-filter cardinality bakes the
    filter's real selectivity in — and `estimated_lane_bytes` of the
    subtree's scans otherwise. First run: estimates; later runs: observed
    (one recompile ever, thanks to the canonical shape families of
    docs/compile_cache.md).

    Only provably commutable spines rewrite: INNER nodes, all keys plain
    Columns, no residuals. Spines whose greedy order equals written order
    are returned UNCHANGED (the IGLOO_ADAPTIVE=0 kill switch then reproduces
    the same plans bit-identically); otherwise a Project on top restores the
    original column order so everything above is untouched."""
    from igloo_tpu.exec.hints import adaptive_enabled
    _adaptive_tls.decisions = []
    if not adaptive_enabled():
        return plan
    return _adaptive_visit(plan)


def _adaptive_visit(plan: L.LogicalPlan) -> L.LogicalPlan:
    if isinstance(plan, L.Join):
        flat = _flatten_inner_spine(plan)
        if flat is not None:
            rels, edges = flat
            order, source = _spine_order(rels, edges)
            if order is not None and order != list(range(len(rels))):
                rels = [_adaptive_visit(r) for r in rels]
                rebuilt = _rebuild_spine(plan, rels, edges, order)
                if rebuilt is not None:
                    from igloo_tpu.utils import tracing
                    tracing.counter("adaptive.reorder")
                    tracing.counter("adaptive.reorder_observed"
                                    if source == "observed"
                                    else "adaptive.reorder_estimated")
                    _adaptive_tls.decisions.append({
                        "strategy": "reorder",
                        "join_order": list(order),
                        "adaptive_source": source})
                    return rebuilt
    for name in ("input", "left", "right"):
        ch = getattr(plan, name, None)
        if isinstance(ch, L.LogicalPlan):
            setattr(plan, name, _adaptive_visit(ch))
    if isinstance(plan, L.Union):
        plan.inputs = [_adaptive_visit(c) for c in plan.inputs]
    return plan


def _flatten_inner_spine(plan: L.Join):
    """Flatten a left-deep spine of residual-free INNER equi-joins whose keys
    are all plain Columns -> (rels, edges) with edge endpoints as GLOBAL
    column indexes over the written-order concat schema; None when the shape
    doesn't commute or is under 3 relations."""
    rels: list = []
    edges: list = []

    def rec(node) -> None:
        if isinstance(node, L.Join) and node.join_type is JoinType.INNER \
                and node.left_keys and node.residual is None and \
                all(type(k) is E.Column
                    for k in node.left_keys + node.right_keys):
            rec(node.left)
            lw = len(node.left.schema)
            rels.append(node.right)
            for lk, rk in zip(node.left_keys, node.right_keys):
                edges.append((lk.index, lw + rk.index))
            return
        rels.append(node)

    rec(plan)
    if len(rels) < 3 or len(plan.schema) != sum(len(r.schema) for r in rels):
        return None
    return rels, edges


def _est_subtree_lane_bytes(p: L.LogicalPlan) -> Optional[int]:
    """Estimated decoded device-lane bytes of the scans under `p`; None when
    any scan is unsized (then written order stands — no guess is better than
    a wrong one)."""
    from igloo_tpu.exec.chunked import estimated_lane_bytes
    total = 0
    for n in L.walk_plan(p):
        if isinstance(n, L.Scan):
            if n.provider is None:
                return None
            nb = estimated_lane_bytes(n.provider)
            if nb is None:
                return None
            total += nb
    return total


def _spine_order(rels: list, edges: list):
    """Greedy smallest-connected-first order over the relation graph, or
    (None, ...) when any relation is unsized or the graph would force a
    cross join the written order avoided."""
    from igloo_tpu.exec.hints import adaptive_store, plan_fp, row_width_bytes
    store = adaptive_store()
    sizes: list = []
    observed = 0
    for r in rels:
        fp = plan_fp(r)
        rows = store.observed_rows(fp) if fp is not None else None
        if rows is not None:
            sizes.append(rows * row_width_bytes(r.schema))
            observed += 1
        else:
            est = _est_subtree_lane_bytes(r)
            if est is None:
                return None, None
            sizes.append(est)
    offsets, off = [], 0
    for r in rels:
        offsets.append(off)
        off += len(r.schema)

    def rel_of(g: int) -> int:
        for i in range(len(rels) - 1, -1, -1):
            if g >= offsets[i]:
                return i
        return 0

    rel_edges = {(rel_of(a), rel_of(b)) for a, b in edges}
    order = [min(range(len(rels)), key=lambda i: sizes[i])]
    remaining = [i for i in range(len(rels)) if i != order[0]]
    while remaining:
        conn = [i for i in remaining
                if any((p, i) in rel_edges or (i, p) in rel_edges
                       for p in order)]
        if not conn:
            return None, None  # disconnected: would introduce a cross join
        nxt = min(conn, key=lambda i: sizes[i])
        order.append(nxt)
        remaining.remove(nxt)
    return order, ("observed" if observed == len(rels) else
                   "estimated" if observed == 0 else "mixed")


def _rebuild_spine(spine: L.Join, rels: list, edges: list,
                   order: list) -> Optional[L.LogicalPlan]:
    """Left-deep INNER chain in `order` + a Project restoring the original
    column order. Every edge is consumed as a join key the moment its
    later-placed relation joins the chain; a cyclic edge whose endpoints are
    already co-resident becomes an equality filter above the chain."""
    offsets, off = [], 0
    for r in rels:
        offsets.append(off)
        off += len(r.schema)

    def rel_of(g: int) -> int:
        for i in range(len(rels) - 1, -1, -1):
            if g >= offsets[i]:
                return i
        return 0

    def gfield(g: int) -> T.Field:
        i = rel_of(g)
        return rels[i].schema.fields[g - offsets[i]]

    def col(name: str, idx: int, dtype) -> E.Column:
        c = E.Column(name, index=idx)
        c.dtype = dtype
        return c

    placed = {order[0]}
    chain: L.LogicalPlan = rels[order[0]]
    pos = {offsets[order[0]] + k: k
           for k in range(len(rels[order[0]].schema))}
    consumed = [False] * len(edges)
    for i in order[1:]:
        lkeys, rkeys = [], []
        for ei, (a, b) in enumerate(edges):
            if consumed[ei]:
                continue
            if rel_of(a) in placed and rel_of(b) == i:
                gl, gr = a, b
            elif rel_of(b) in placed and rel_of(a) == i:
                gl, gr = b, a
            else:
                continue
            consumed[ei] = True
            lf, rf = gfield(gl), gfield(gr)
            lkeys.append(col(lf.name, pos[gl], lf.dtype))
            rkeys.append(col(rf.name, gr - offsets[i], rf.dtype))
        if not lkeys:
            return None  # pragma: no cover - connectivity guaranteed above
        j = L.Join(left=chain, right=rels[i], join_type=JoinType.INNER,
                   left_keys=lkeys, right_keys=rkeys)
        j.schema = T.Schema(list(chain.schema.fields) +
                            list(rels[i].schema.fields))
        base = len(pos)
        for k in range(len(rels[i].schema)):
            pos[offsets[i] + k] = base + k
        placed.add(i)
        chain = j
    # restore the ORIGINAL column order (and names) above the new chain
    orig = spine.schema
    exprs = []
    for g in range(off):
        f = gfield(g)
        exprs.append(col(f.name, pos[g], f.dtype))
    proj = L.Project(input=chain, exprs=exprs, names=list(orig.names))
    proj.schema = orig
    # cyclic edges with both endpoints placed before consumption cannot
    # occur (each edge is consumed when its later relation is placed), but
    # guard anyway: any leftover becomes an equality filter above the
    # restoring projection, where the original global indexes are valid
    preds = []
    for ei, (a, b) in enumerate(edges):
        if not consumed[ei]:
            fa, fb = gfield(a), gfield(b)
            eq = E.Binary(op=E.BinOp.EQ, left=col(fa.name, a, fa.dtype),
                          right=col(fb.name, b, fb.dtype))
            eq.dtype = T.BOOL
            preds.append(eq)
    return _wrap_filter(proj, preds) if preds else proj


# --- constant folding -------------------------------------------------------------


def fold_constants_pass(plan: L.LogicalPlan) -> L.LogicalPlan:
    for node in L.walk_plan(plan):
        if isinstance(node, L.Filter):
            node.predicate = fold_expr(node.predicate)
        elif isinstance(node, L.Project):
            node.exprs = [fold_expr(e) for e in node.exprs]
        elif isinstance(node, L.Aggregate):
            node.group_exprs = [fold_expr(e) for e in node.group_exprs]
            for a in node.aggs:
                if a.arg is not None:
                    a.arg = fold_expr(a.arg)
        elif isinstance(node, L.Join):
            node.left_keys = [fold_expr(e) for e in node.left_keys]
            node.right_keys = [fold_expr(e) for e in node.right_keys]
            if node.residual is not None:
                node.residual = fold_expr(node.residual)
        elif isinstance(node, L.Sort):
            node.keys = [fold_expr(e) for e in node.keys]
    return plan


def _lit(value, dtype: T.DataType) -> E.Literal:
    lt = E.Literal(value=value, literal_type=dtype)
    lt.dtype = dtype
    return lt


def fold_expr(e: E.Expr) -> E.Expr:
    def fold(n: E.Expr) -> E.Expr:
        if isinstance(n, E.Binary):
            l, r = n.left, n.right
            # boolean short-circuits with one literal side
            if n.op is E.BinOp.AND:
                if isinstance(l, E.Literal) and l.value is True:
                    return r
                if isinstance(r, E.Literal) and r.value is True:
                    return l
                if (isinstance(l, E.Literal) and l.value is False) or \
                        (isinstance(r, E.Literal) and r.value is False):
                    return _lit(False, T.BOOL)
            if n.op is E.BinOp.OR:
                if isinstance(l, E.Literal) and l.value is False:
                    return r
                if isinstance(r, E.Literal) and r.value is False:
                    return l
                if (isinstance(l, E.Literal) and l.value is True) or \
                        (isinstance(r, E.Literal) and r.value is True):
                    return _lit(True, T.BOOL)
            if isinstance(l, E.Literal) and isinstance(r, E.Literal):
                folded = _fold_binary(n.op, l, r, n.dtype)
                if folded is not None:
                    return folded
        elif isinstance(n, E.Not):
            if isinstance(n.operand, E.Literal):
                v = n.operand.value
                return _lit(None if v is None else (not v), T.BOOL)
            if isinstance(n.operand, E.Not):
                return n.operand.operand
        elif isinstance(n, E.Negate) and isinstance(n.operand, E.Literal):
            v = n.operand.value
            return _lit(None if v is None else -v, n.dtype)
        elif isinstance(n, E.Cast) and isinstance(n.operand, E.Literal):
            folded = _fold_cast(n.operand, n.to)
            if folded is not None:
                return folded
        elif isinstance(n, E.IsNull) and isinstance(n.operand, E.Literal):
            isn = n.operand.value is None
            return _lit((not isn) if n.negated else isn, T.BOOL)
        return n
    return E.transform(e, fold)


def _fold_binary(op: E.BinOp, l: E.Literal, r: E.Literal,
                 out_dtype) -> Optional[E.Expr]:
    if l.value is None or r.value is None:
        if op in (E.BinOp.AND, E.BinOp.OR):
            return None  # Kleene logic handled at runtime
        return _lit(None, out_dtype or T.NULL)
    a, b = l.value, r.value
    try:
        if op is E.BinOp.ADD:
            v = a + b
        elif op is E.BinOp.SUB:
            v = a - b
        elif op is E.BinOp.MUL:
            v = a * b
        elif op is E.BinOp.DIV:
            if b == 0:
                return _lit(None, out_dtype or T.NULL)
            # SQL integer division TRUNCATES (matches the runtime kernel,
            # expr_compile _compile_numeric_binary) — Python // floors
            if out_dtype is not None and out_dtype.is_integer:
                v = _trunc_div(a, b)
            else:
                v = a / b
        elif op is E.BinOp.MOD:
            if b == 0:
                return _lit(None, out_dtype or T.NULL)
            v = a - _trunc_div(a, b) * b  # truncating remainder, sign of a
        elif op is E.BinOp.EQ:
            return _lit(a == b, T.BOOL)
        elif op is E.BinOp.NEQ:
            return _lit(a != b, T.BOOL)
        elif op is E.BinOp.LT:
            return _lit(a < b, T.BOOL)
        elif op is E.BinOp.LTE:
            return _lit(a <= b, T.BOOL)
        elif op is E.BinOp.GT:
            return _lit(a > b, T.BOOL)
        elif op is E.BinOp.GTE:
            return _lit(a >= b, T.BOOL)
        else:
            return None
    except TypeError:
        return None
    return _lit(v, out_dtype or l.dtype)


def _trunc_div(a, b):
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _fold_cast(lit: E.Literal, to: T.DataType) -> Optional[E.Expr]:
    v = lit.value
    if v is None:
        return _lit(None, to)
    try:
        if to.is_integer:
            return _lit(int(v), to)
        if to.is_float:
            return _lit(float(v), to)
        if to.id == T.TypeId.BOOL:
            return _lit(bool(v), to)
    except (TypeError, ValueError):
        return None
    return None  # string/date casts handled at runtime


# --- predicate pushdown -----------------------------------------------------------


def pushdown_filters(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Push filter conjuncts as deep as legal. Returns a rewritten tree."""
    plan = _pushdown(plan, [])
    return plan


def _cols_of(e: E.Expr) -> set[int]:
    return {n.index for n in E.walk(e) if isinstance(n, E.Column)}


def _has_scalar_subquery(e: E.Expr) -> bool:
    return any(isinstance(n, E.ScalarSubquery) for n in E.walk(e))


def _remap_cols(e: E.Expr, mapping: dict[int, int]) -> E.Expr:
    e = copy.deepcopy(e)
    for n in E.walk(e):
        if isinstance(n, E.Column):
            n.index = mapping[n.index]
    return e


def _wrap_filter(plan: L.LogicalPlan, preds: list[E.Expr]) -> L.LogicalPlan:
    pred = _and_all([p for p in preds if not _is_true_lit(p)])
    if pred is None:
        return plan
    f = L.Filter(input=plan, predicate=pred)
    f.schema = plan.schema
    return f


def _is_true_lit(p: E.Expr) -> bool:
    return isinstance(p, E.Literal) and p.value is True


def _pushdown(plan: L.LogicalPlan, preds: list[E.Expr]) -> L.LogicalPlan:
    """`preds` are conjuncts bound against `plan`'s OUTPUT schema, to be applied
    above it unless they can sink further."""
    if isinstance(plan, L.Filter):
        inner = _split_conjuncts(plan.predicate)
        return _pushdown(plan.input, preds + inner)

    if isinstance(plan, L.Project):
        sinkable, stuck = [], []
        for p in preds:
            if _has_scalar_subquery(p):
                stuck.append(p)
                continue
            # substitute projected exprs into the predicate
            def sub(n):
                if isinstance(n, E.Column):
                    return copy.deepcopy(plan.exprs[n.index])
                return n
            sinkable.append(E.transform(copy.deepcopy(p), sub))
        plan.input = _pushdown(plan.input, sinkable)
        plan.schema = plan.schema  # unchanged
        return _wrap_filter(plan, stuck)

    if isinstance(plan, L.Aggregate):
        k = len(plan.group_exprs)
        sinkable, stuck = [], []
        for p in preds:
            cols = _cols_of(p)
            # k == 0 (global aggregate) must keep filters above: it emits one
            # row even over empty input, so sinking flips "no rows" to "one row"
            if k > 0 and all(i < k for i in cols) and not _has_scalar_subquery(p):
                def sub(n):
                    if isinstance(n, E.Column):
                        return copy.deepcopy(plan.group_exprs[n.index])
                    return n
                sinkable.append(E.transform(copy.deepcopy(p), sub))
            else:
                stuck.append(p)
        plan.input = _pushdown(plan.input, sinkable)
        return _wrap_filter(plan, stuck)

    if isinstance(plan, L.Join):
        n_left = len(plan.left.schema)
        jt = plan.join_type
        # Comma-list FROM items bind as CROSS joins with the WHERE equalities
        # left as predicates. Materializing the cross product (|L|x|R| candidate
        # slots) before filtering is catastrophic for the static-shape executor,
        # so equality conjuncts spanning exactly both sides become join keys
        # here, and any other both-sided conjunct becomes a residual (evaluated
        # during candidate expansion, before the output batch is sized).
        if jt in (JoinType.INNER, JoinType.CROSS):
            remaining = []
            for p in preds:
                pair = None if _has_scalar_subquery(p) else \
                    _extract_equi_key(p, n_left)
                if pair is not None:
                    lk, rk = coerce_key_pair(*pair)
                    plan.left_keys.append(lk)
                    plan.right_keys.append(rk)
                    jt = plan.join_type = JoinType.INNER
                else:
                    remaining.append(p)
            preds, remaining = remaining, []
            for p in preds:
                cols = _cols_of(p)
                if cols and not _has_scalar_subquery(p) and \
                        any(i < n_left for i in cols) and \
                        any(i >= n_left for i in cols):
                    plan.residual = _and_all(
                        ([plan.residual] if plan.residual is not None else [])
                        + [p])
                else:
                    remaining.append(p)
            preds = remaining
        semi = jt in (JoinType.SEMI, JoinType.ANTI)
        n_out_left = n_left
        left_preds, right_preds, stuck = [], [], []
        can_left = jt in (JoinType.INNER, JoinType.LEFT, JoinType.CROSS,
                          JoinType.SEMI, JoinType.ANTI)
        can_right = jt in (JoinType.INNER, JoinType.RIGHT, JoinType.CROSS)
        for p in preds:
            cols = _cols_of(p)
            if _has_scalar_subquery(p):
                stuck.append(p)
            elif cols and all(i < n_out_left for i in cols) and can_left:
                left_preds.append(p)
            elif not semi and cols and all(i >= n_out_left for i in cols) and can_right:
                right_preds.append(_remap_cols(p, {i: i - n_left
                                                   for i in range(n_left, n_left + len(plan.right.schema))}))
            else:
                stuck.append(p)
        # residual of an inner join can also sink if one-sided
        if plan.residual is not None and jt in (JoinType.INNER,):
            keep = []
            for c in _split_conjuncts(plan.residual):
                cols = _cols_of(c)
                if cols and all(i < n_left for i in cols):
                    left_preds.append(c)
                elif cols and all(i >= n_left for i in cols):
                    right_preds.append(_remap_cols(
                        c, {i: i - n_left for i in cols}))
                else:
                    keep.append(c)
            plan.residual = _and_all(keep)
        plan.left = _pushdown(plan.left, left_preds)
        plan.right = _pushdown(plan.right, right_preds)
        return _wrap_filter(plan, stuck)

    if isinstance(plan, L.Union):
        plan.inputs = [_pushdown(ch, [copy.deepcopy(p) for p in preds])
                       for ch in plan.inputs]
        return plan

    if isinstance(plan, (L.Distinct,)):
        plan.input = _pushdown(plan.input, preds)
        return plan

    if isinstance(plan, L.Scan):
        pushable = [p for p in preds if not _has_scalar_subquery(p)]
        plan.pushed_filters = list(pushable)
        # exact filters still applied above the scan (providers prune best-effort)
        return _wrap_filter(plan, preds)

    if isinstance(plan, (L.Sort, L.Limit)):
        # pushing below Sort is fine (stable), below Limit is NOT
        if isinstance(plan, L.Sort):
            plan.input = _pushdown(plan.input, preds)
            return plan
        plan.input = _pushdown(plan.input, [])
        return _wrap_filter(plan, preds)

    # SetOpJoin, Values, anything else: stop sinking
    for i, ch in enumerate(plan.children()):
        new = _pushdown(ch, [])
        _replace_child(plan, i, new)
    return _wrap_filter(plan, preds)


def _replace_child(plan, i, new):
    from igloo_tpu.plan.binder import _replace_child as rc
    rc(plan, i, new)


# --- projection pruning -----------------------------------------------------------


def prune_projections(plan: L.LogicalPlan) -> L.LogicalPlan:
    new_plan, mapping = _prune(plan, set(range(len(plan.schema))))
    assert len(mapping) == len(plan.schema), "root schema must be preserved"
    return new_plan


def _prune(plan: L.LogicalPlan, required: set[int]):
    """Prune `plan` so only `required` output columns (by index) are produced.
    Returns (new_plan, mapping old_index -> new_index). A node may keep more than
    required (mapping then covers all kept columns)."""
    if isinstance(plan, L.Scan):
        names = plan.schema.names
        keep = sorted(required) if required else [0] if names else []
        if not keep and names:
            keep = [0]  # always keep at least one column to carry row count
        if len(keep) == len(names):
            return plan, {i: i for i in range(len(names))}
        plan.projection = [names[i] for i in keep]
        plan.schema = T.Schema([plan.schema.fields[i] for i in keep])
        return plan, {old: new for new, old in enumerate(keep)}

    if isinstance(plan, L.Project):
        keep = sorted(required)
        child_req = set()
        for i in keep:
            child_req |= _cols_of(plan.exprs[i])
        for e in plan.exprs:
            if _has_scalar_subquery(e):
                for n in E.walk(e):
                    if isinstance(n, E.ScalarSubquery):
                        n.query = prune_projections(n.query)
        plan.input, cmap = _prune(plan.input, child_req)
        plan.exprs = [_remap_cols(plan.exprs[i], cmap) for i in keep]
        plan.names = [plan.names[i] for i in keep]
        plan.schema = T.Schema([plan.schema.fields[i] for i in keep])
        return plan, {old: new for new, old in enumerate(keep)}

    if isinstance(plan, L.Filter):
        child_req = set(required) | _cols_of(plan.predicate)
        for n in E.walk(plan.predicate):
            if isinstance(n, E.ScalarSubquery):
                n.query = prune_projections(n.query)
        plan.input, cmap = _prune(plan.input, child_req)
        plan.predicate = _remap_cols(plan.predicate, cmap)
        plan.schema = plan.input.schema
        return plan, cmap

    if isinstance(plan, L.Aggregate):
        child_req = set()
        for g in plan.group_exprs:
            child_req |= _cols_of(g)
        for a in plan.aggs:
            if a.arg is not None:
                child_req |= _cols_of(a.arg)
        plan.input, cmap = _prune(plan.input, child_req)
        plan.group_exprs = [_remap_cols(g, cmap) for g in plan.group_exprs]
        for a in plan.aggs:
            if a.arg is not None:
                a.arg = _remap_cols(a.arg, cmap)
        return plan, {i: i for i in range(len(plan.schema))}

    if isinstance(plan, L.Join):
        n_left = len(plan.left.schema)
        semi = plan.join_type in (JoinType.SEMI, JoinType.ANTI)
        lreq, rreq = set(), set()
        for i in required:
            if i < n_left:
                lreq.add(i)
            else:
                rreq.add(i - n_left)
        for k in plan.left_keys:
            lreq |= _cols_of(k)
        for k in plan.right_keys:
            rreq |= _cols_of(k)
        if plan.residual is not None:
            for i in _cols_of(plan.residual):
                if i < n_left:
                    lreq.add(i)
                else:
                    rreq.add(i - n_left)
        plan.left, lmap = _prune(plan.left, lreq)
        plan.right, rmap = _prune(plan.right, rreq)
        plan.left_keys = [_remap_cols(k, lmap) for k in plan.left_keys]
        plan.right_keys = [_remap_cols(k, rmap) for k in plan.right_keys]
        new_n_left = len(plan.left.schema)
        # combined mapping always covers both sides: the residual may reference
        # right-side columns even in semi/anti joins (NOT IN rewrite)
        comb = {}
        for old, new in lmap.items():
            comb[old] = new
        for old, new in rmap.items():
            comb[old + n_left] = new + new_n_left
        if plan.residual is not None:
            plan.residual = _remap_cols(plan.residual, comb)
        if semi:
            plan.schema = plan.left.schema
            return plan, lmap
        old_fields = plan.schema.fields
        kept_old = sorted(comb)
        from igloo_tpu.plan.binder import _dedup_fields
        plan.schema = T.Schema(_dedup_fields(
            [T.Field(old_fields[i].name if i < len(old_fields) else "c",
                     (list(plan.left.schema) + list(plan.right.schema))[comb[i]].dtype,
                     True) for i in kept_old]))
        return plan, {old: k for k, old in enumerate(kept_old)}

    if isinstance(plan, L.Sort):
        child_req = set(required)
        for k in plan.keys:
            child_req |= _cols_of(k)
        plan.input, cmap = _prune(plan.input, child_req)
        plan.keys = [_remap_cols(k, cmap) for k in plan.keys]
        plan.schema = plan.input.schema
        return plan, cmap

    if isinstance(plan, L.Limit):
        plan.input, cmap = _prune(plan.input, required)
        plan.schema = plan.input.schema
        return plan, cmap

    if isinstance(plan, (L.Distinct, L.Union, L.SetOpJoin, L.Values)):
        # positional semantics: all columns required
        all_req_children = []
        for i, ch in enumerate(plan.children()):
            new, cmap = _prune(ch, set(range(len(ch.schema))))
            assert len(cmap) == len(ch.schema)
            all_req_children.append(new)
            _replace_child(plan, i, new)
        return plan, {i: i for i in range(len(plan.schema))}

    # unknown node: require everything below
    for i, ch in enumerate(plan.children()):
        new, _ = _prune(ch, set(range(len(ch.schema))))
        _replace_child(plan, i, new)
    return plan, {i: i for i in range(len(plan.schema))}
