"""Expression IR.

The reference delegates expression representation to DataFusion's `PhysicalExpr`
(crates/engine/src/operators/projection.rs:12-16, filter.rs:13-16 hold
`Arc<dyn PhysicalExpr>`); we own the IR because it must lower to jnp element-wise
graphs fused into each fragment's jit function (SURVEY.md §2 #7 "expression compiler").

Expressions are built untyped by the SQL parser, then *bound* (names resolved, types
inferred) by the planner. `dtype` is filled in during binding.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field as dc_field
from typing import Optional

from igloo_tpu import types as T


class BinOp(enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"
    EQ = "="
    NEQ = "<>"
    LT = "<"
    LTE = "<="
    GT = ">"
    GTE = ">="
    AND = "and"
    OR = "or"


COMPARISONS = {BinOp.EQ, BinOp.NEQ, BinOp.LT, BinOp.LTE, BinOp.GT, BinOp.GTE}
ARITHMETIC = {BinOp.ADD, BinOp.SUB, BinOp.MUL, BinOp.DIV, BinOp.MOD}


@dataclass
class Expr:
    """Base expression node. `dtype` is None until bound."""
    dtype: Optional[T.DataType] = dc_field(default=None, init=False, compare=False)

    def name_hint(self) -> str:
        return "expr"

    def children(self) -> list["Expr"]:
        return []


@dataclass
class Column(Expr):
    name: str
    # Resolved during binding: index into the input schema.
    index: Optional[int] = dc_field(default=None, compare=False)

    def name_hint(self) -> str:
        return self.name.split(".")[-1]

    def __repr__(self) -> str:
        return f"col({self.name})"


@dataclass
class Literal(Expr):
    value: object  # python int/float/str/bool/None; dates as int days, ts as int us
    literal_type: Optional[T.DataType] = None

    def name_hint(self) -> str:
        return str(self.value)

    def __repr__(self) -> str:
        return f"lit({self.value!r})"


@dataclass
class Interval(Expr):
    """INTERVAL literal; exists only pre-folding (date arithmetic constant-folds)."""
    days: int = 0
    months: int = 0

    def __repr__(self) -> str:
        return f"interval(days={self.days}, months={self.months})"


@dataclass
class Binary(Expr):
    op: BinOp
    left: Expr
    right: Expr

    def children(self):
        return [self.left, self.right]

    def name_hint(self) -> str:
        return f"{self.left.name_hint()} {self.op.value} {self.right.name_hint()}"

    def __repr__(self) -> str:
        return f"({self.left!r} {self.op.value} {self.right!r})"


@dataclass
class Not(Expr):
    operand: Expr

    def children(self):
        return [self.operand]

    def __repr__(self) -> str:
        return f"not({self.operand!r})"


@dataclass
class Negate(Expr):
    operand: Expr

    def children(self):
        return [self.operand]

    def __repr__(self) -> str:
        return f"(-{self.operand!r})"


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False

    def children(self):
        return [self.operand]

    def __repr__(self) -> str:
        return f"is_{'not_' if self.negated else ''}null({self.operand!r})"


@dataclass
class Cast(Expr):
    operand: Expr
    to: T.DataType = None  # type: ignore[assignment]

    def children(self):
        return [self.operand]

    def name_hint(self) -> str:
        return self.operand.name_hint()

    def __repr__(self) -> str:
        return f"cast({self.operand!r} as {self.to})"


@dataclass
class Case(Expr):
    """CASE WHEN c THEN v ... ELSE e END (searched form; simple form is desugared)."""
    whens: list[tuple[Expr, Expr]] = dc_field(default_factory=list)
    else_: Optional[Expr] = None

    def children(self):
        out = []
        for c, v in self.whens:
            out += [c, v]
        if self.else_ is not None:
            out.append(self.else_)
        return out

    def __repr__(self) -> str:
        return f"case({self.whens!r}, else={self.else_!r})"


@dataclass
class InList(Expr):
    operand: Expr
    items: list[Expr] = dc_field(default_factory=list)
    negated: bool = False

    def children(self):
        return [self.operand] + self.items

    def __repr__(self) -> str:
        return f"in({self.operand!r}, {self.items!r}, neg={self.negated})"


@dataclass
class Like(Expr):
    operand: Expr
    pattern: str = ""
    negated: bool = False
    case_insensitive: bool = False

    def children(self):
        return [self.operand]

    def __repr__(self) -> str:
        return f"like({self.operand!r}, {self.pattern!r})"


@dataclass
class Func(Expr):
    """Scalar function call: abs, upper, lower, capitalize, length, substr, concat,
    extract_year/month/day, coalesce, round, floor, ceil, sqrt, ..."""
    name: str = ""
    args: list[Expr] = dc_field(default_factory=list)

    def children(self):
        return self.args

    def name_hint(self) -> str:
        return self.name

    def __repr__(self) -> str:
        return f"{self.name}({self.args!r})"


class AggFunc(enum.Enum):
    SUM = "sum"
    COUNT = "count"
    MIN = "min"
    MAX = "max"
    AVG = "avg"
    COUNT_STAR = "count_star"


@dataclass
class Aggregate(Expr):
    """Aggregate function reference inside a SELECT/HAVING. The planner hoists these
    into the Aggregate plan node; they never reach the expression compiler directly."""
    func: AggFunc = AggFunc.COUNT_STAR
    arg: Optional[Expr] = None
    distinct: bool = False

    def children(self):
        return [self.arg] if self.arg is not None else []

    def name_hint(self) -> str:
        if self.func is AggFunc.COUNT_STAR:
            return "count(*)"
        return f"{self.func.value}({self.arg.name_hint()})"

    def __repr__(self) -> str:
        return f"{self.func.value}({self.arg!r}{', distinct' if self.distinct else ''})"


@dataclass
class Window(Expr):
    """Window function: fn(args) OVER (PARTITION BY ... ORDER BY ...).

    `func` is "row_number" | "rank" | "dense_rank" | "lag" | "lead", or an
    aggregate applied over the window (`agg` set, func == "agg"). With an
    ORDER BY, aggregates use the SQL default frame (RANGE UNBOUNDED PRECEDING
    .. CURRENT ROW — running totals over peer groups); without one they span
    the whole partition. The reference executes these through DataFusion
    (crates/engine/src/lib.rs:54-57); the TPU design is a segmented-scan
    kernel (exec/window.py)."""
    func: str = ""
    agg: Optional["Aggregate"] = None
    args: list[Expr] = dc_field(default_factory=list)   # lag/lead: value[, offset]
    partition_by: list[Expr] = dc_field(default_factory=list)
    order_by: list[Expr] = dc_field(default_factory=list)
    ascending: list[bool] = dc_field(default_factory=list)
    nulls_first: list[bool] = dc_field(default_factory=list)

    def children(self):
        out = list(self.args) + list(self.partition_by) + list(self.order_by)
        if self.agg is not None and self.agg.arg is not None:
            out.append(self.agg.arg)
        return out

    def name_hint(self) -> str:
        return self.agg.name_hint() if self.agg is not None else self.func

    def __repr__(self) -> str:
        inner = repr(self.agg) if self.agg is not None else \
            f"{self.func}({self.args!r})"
        return (f"window({inner} part={self.partition_by!r} "
                f"order={self.order_by!r} asc={self.ascending} "
                f"nf={self.nulls_first})")


@dataclass
class Alias(Expr):
    operand: Expr = None  # type: ignore[assignment]
    alias: str = ""

    def children(self):
        return [self.operand]

    def name_hint(self) -> str:
        return self.alias

    def __repr__(self) -> str:
        return f"({self.operand!r} as {self.alias})"


@dataclass
class Star(Expr):
    """SELECT * placeholder; expanded by the planner."""
    qualifier: Optional[str] = None

    def __repr__(self) -> str:
        return f"{self.qualifier + '.' if self.qualifier else ''}*"


@dataclass
class ScalarSubquery(Expr):
    """(SELECT single value); the planner evaluates uncorrelated ones eagerly."""
    query: object = None  # ast.SelectStmt (avoid circular import)

    def __repr__(self) -> str:
        return "scalar_subquery(...)"


@dataclass
class InSubquery(Expr):
    operand: Expr = None  # type: ignore[assignment]
    query: object = None
    negated: bool = False

    def children(self):
        return [self.operand]

    def __repr__(self) -> str:
        return f"in_subquery({self.operand!r}, neg={self.negated})"


@dataclass
class Exists(Expr):
    query: object = None
    negated: bool = False

    def __repr__(self) -> str:
        return f"exists(neg={self.negated})"


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def transform(e: Expr, fn) -> Expr:
    """Bottom-up rewrite: fn applied to each node after its children are rewritten."""
    import copy
    n = copy.copy(e)
    if isinstance(n, Binary):
        n.left = transform(n.left, fn)
        n.right = transform(n.right, fn)
    elif isinstance(n, (Not, Negate, IsNull, Cast)):
        n.operand = transform(n.operand, fn)
    elif isinstance(n, Case):
        n.whens = [(transform(c, fn), transform(v, fn)) for c, v in n.whens]
        n.else_ = transform(n.else_, fn) if n.else_ is not None else None
    elif isinstance(n, InList):
        n.operand = transform(n.operand, fn)
        n.items = [transform(i, fn) for i in n.items]
    elif isinstance(n, Like):
        n.operand = transform(n.operand, fn)
    elif isinstance(n, Func):
        n.args = [transform(a, fn) for a in n.args]
    elif isinstance(n, Aggregate):
        n.arg = transform(n.arg, fn) if n.arg is not None else None
    elif isinstance(n, Alias):
        n.operand = transform(n.operand, fn)
    elif isinstance(n, InSubquery):
        n.operand = transform(n.operand, fn)
    elif isinstance(n, Window):
        n.args = [transform(a, fn) for a in n.args]
        n.partition_by = [transform(p, fn) for p in n.partition_by]
        n.order_by = [transform(o, fn) for o in n.order_by]
        if n.agg is not None:
            n.agg = transform(n.agg, fn)
    return fn(n)


def columns_in(e: Expr) -> set[str]:
    return {n.name for n in walk(e) if isinstance(n, Column)}
