"""CDC: source-change detection driving cache invalidation.

The reference's cdc crate is an empty stub ("TODO: Implement CDC logic",
crates/cdc/src/lib.rs:9) whose declared purpose (README "Intelligent Caching")
is invalidating the cache when a source changes. This is that capability:

- every connector exposes a cheap `snapshot()` token (file mtimes/sizes for
  Parquet/CSV, metadata version for Iceberg — see connectors/*.py); the batch
  cache already validates tokens lazily on each hit (exec/cache.py), so even
  without a watcher stale data is never served;
- `SourceWatcher` adds EAGER invalidation + notification: poll() diffs the
  current tokens against the last seen ones, evicts changed tables from the
  engine's batch cache, and fires registered callbacks (the distributed tier
  uses this to broadcast invalidations to workers);
- `watch()` runs poll() on a background thread at a fixed interval.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from igloo_tpu.exec.cache import provider_snapshot
from igloo_tpu.utils import tracing

log = logging.getLogger("igloo_tpu")

# lock discipline (igloo-lint lock-discipline): the registration path
# (on_change, any thread) and the poll sweep (watch thread) share both the
# seen-token map and the callback list
_GUARDED_BY = {"_lock": ("_seen", "_callbacks")}


class SourceWatcher:
    def __init__(self, engine, interval_s: float = 5.0):
        self.engine = engine
        self.interval_s = interval_s
        self._seen: dict[str, object] = {}
        self._callbacks: list[Callable[[str], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def on_change(self, fn: Callable[[str], None]) -> None:
        """Register a callback fired with the table name on each change.
        Lock-guarded: registration may race the watch thread's poll()
        (list.append alone would also race a concurrent snapshot read)."""
        with self._lock:
            self._callbacks.append(fn)

    def poll(self) -> list[str]:
        """One sweep: returns the list of tables whose source changed, after
        evicting them from the engine's batch cache. Callbacks run OUTSIDE
        the lock (a slow subscriber must not stall registration) and a
        raising callback is counted (`cdc.callback_errors`) and logged
        instead of killing the watch thread — one bad subscriber cannot
        turn eager invalidation off for everyone else."""
        changed = []
        with self._lock:
            for name in self.engine.catalog.names():
                provider = self.engine.catalog.maybe_get(name)
                if provider is None:
                    continue
                tok = provider_snapshot(provider)
                prev = self._seen.get(name)
                if prev is not None and prev != tok:
                    self.engine.batch_cache.invalidate_table(name)
                    host = getattr(self.engine, "host_cache", None)
                    if host is not None:
                        host.invalidate_table(name)
                    changed.append(name)
                self._seen[name] = tok
            callbacks = list(self._callbacks)
        for name in changed:
            for fn in callbacks:
                try:
                    fn(name)
                except Exception:
                    tracing.counter("cdc.callback_errors")
                    log.exception("cdc: on_change callback failed for "
                                  "table %r", name)
        return changed

    def watch(self) -> "SourceWatcher":
        """Start background polling; idempotent. Restartable after stop()."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll()
                except Exception:  # pragma: no cover - never kill the thread
                    import logging
                    logging.getLogger("igloo_tpu").exception("cdc poll failed")
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="igloo-cdc")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)
            self._thread = None
