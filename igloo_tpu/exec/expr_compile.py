"""Expression compiler: bound Expr IR -> jnp element-wise graph.

Plays the role of DataFusion's `create_physical_expr` in the reference
(crates/engine/src/physical_planner.rs:60-64), but targets XLA: each expression
compiles to a pure function over device column lanes, returning `(values, nulls)`.
These functions compose into ONE `jax.jit` computation per fragment, so scan→filter→
project fuse with no intermediate materialization (SURVEY.md §7 design stance).

SQL three-valued logic: every compiled node yields `(vals, nulls)` with `nulls` an
optional bool lane (True = NULL). Kleene AND/OR; comparisons/arithmetic propagate NULL.

Strings: device lanes hold sorted-dictionary ids (see exec/batch.py). The compiler
turns string predicates into id comparisons / lookup-table gathers, and string
functions into host-side dictionary transforms + id remaps. String-producing
expressions therefore carry their output `DictInfo` statically (`Compiled.out_dict`).
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from igloo_tpu import types as T
from igloo_tpu.exec.batch import DeviceBatch, DictInfo, wide_values
from igloo_tpu.plan import expr as E


class Env:
    """Column environment a compiled expression reads from: device lanes of the input
    batch, indexed the same way the binder resolved Column.index, plus the const
    pool arrays (dictionary-derived LUTs) for this execution."""

    def __init__(self, values: list, nulls: list, consts: tuple = ()):
        self.values = values
        self.nulls = nulls
        self.consts = consts

    @staticmethod
    def from_batch(batch: DeviceBatch, consts: tuple = ()) -> "Env":
        # wide_values is THE carrier decode point for operators: columns are
        # carrier-resident in HBM (exec/codec.py), and every compiled
        # expression — filters, projections, join/group/sort keys, DISTINCT —
        # reads lanes through this Env inside a jitted program, so the widen
        # fuses into the consumer and no wide lane ever materializes in HBM.
        return Env([wide_values(c) for c in batch.columns],
                   [c.nulls for c in batch.columns], consts)


class ConstPool:
    """Host-computed arrays (dictionary LUTs, per-entry hash lanes, parsed-cast
    tables) that compiled expressions read as runtime ARGUMENTS instead of
    trace-time constants. This is what keeps dictionary CONTENT out of the jit
    compile-cache key: two executions whose dictionaries differ only in content
    (same length bucket) reuse one compiled executable and just pass different
    const arrays (fixes round-1 verdict: DictInfo in static aux forced a full
    recompile per new dictionary).

    Arrays are padded to power-of-two lengths so the (shape, dtype) signature —
    which IS part of the cache key — buckets well."""

    def __init__(self):
        self.arrays: list[np.ndarray] = []

    # pad memo keyed on the SOURCE array's id (e.g. DictInfo.hashes, which is
    # stable for a table's lifetime): repeated queries re-adding the same host
    # array get the identical padded array object back, which is what makes the
    # device-upload memo below actually hit across executions.
    _PAD_MEMO: dict = {}
    _PAD_MEMO_MAX = 512

    @classmethod
    def _padded(cls, arr: np.ndarray) -> np.ndarray:
        # id() key is SAFE here: the entry pins `arr` (ent[0]) and every hit
        # validates `ent[0] is arr`, so a recycled id can never match
        key = id(arr)  # lint: allow(cache-key)
        ent = cls._PAD_MEMO.get(key)
        if ent is not None and ent[0] is arr:
            return ent[1]
        out = np.ascontiguousarray(arr)
        from igloo_tpu.exec.batch import round_capacity
        if out.ndim == 1:
            cap = round_capacity(max(out.shape[0], 1))
            if cap != out.shape[0]:
                padded = np.zeros((cap,), dtype=out.dtype)
                padded[: out.shape[0]] = out
                out = padded
        elif out.ndim == 2:
            c0 = round_capacity(max(out.shape[0], 1))
            c1 = round_capacity(max(out.shape[1], 1))
            if (c0, c1) != out.shape:
                padded = np.zeros((c0, c1), dtype=out.dtype)
                padded[: out.shape[0], : out.shape[1]] = out
                out = padded
        if len(cls._PAD_MEMO) >= cls._PAD_MEMO_MAX:
            for k in list(cls._PAD_MEMO)[: cls._PAD_MEMO_MAX // 2]:
                del cls._PAD_MEMO[k]
        cls._PAD_MEMO[key] = (arr, out)
        return out

    def add(self, arr: np.ndarray) -> int:
        self.arrays.append(self._padded(arr))
        return len(self.arrays) - 1

    def signature(self) -> tuple:
        return tuple((a.shape, str(a.dtype)) for a in self.arrays)

    # process-wide host-array -> device-array memo: repeated executions reuse
    # HBM-resident const buffers (dictionary hash lanes, LUTs) instead of
    # re-uploading per query (round-2 advisor finding). Keyed on id() with the
    # host array kept alive by the value tuple, so an id can't be recycled
    # while its entry is live; bounded FIFO eviction keeps it from growing
    # without bound when dictionaries churn.
    _DEVICE_MEMO: dict = {}
    _DEVICE_MEMO_MAX = 512

    @classmethod
    def _to_device(cls, a: np.ndarray):
        # id() key is SAFE here: the value tuple pins `a` and hits validate
        # `ent[0] is a` (see the memo comment above)
        ent = cls._DEVICE_MEMO.get(id(a))  # lint: allow(cache-key)
        if ent is not None and ent[0] is a:
            return ent[1]
        dev = jnp.asarray(a)
        if len(cls._DEVICE_MEMO) >= cls._DEVICE_MEMO_MAX:
            for k in list(cls._DEVICE_MEMO)[: cls._DEVICE_MEMO_MAX // 2]:
                del cls._DEVICE_MEMO[k]
        cls._DEVICE_MEMO[id(a)] = (a, dev)  # lint: allow(cache-key)
        return dev

    def device_args(self) -> tuple:
        return tuple(self._to_device(a) for a in self.arrays)


@dataclass
class Compiled:
    fn: Callable[[Env], tuple]  # Env -> (vals, nulls|None)
    dtype: T.DataType
    out_dict: Optional[DictInfo] = None  # set iff dtype is STRING
    # (lo, hi) host-known value bounds for integer-family outputs (bare column
    # refs / int literals); feeds the direct-join strategy choice. None = unknown.
    out_bounds: Optional[tuple] = None


class ExprCompileError(Exception):
    pass


def _or_nulls(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _unify_dicts(da: Optional[DictInfo], db: Optional[DictInfo]):
    """Merge two sorted dictionaries; returns (union, lut_a, lut_b) where lut_x maps
    old ids -> union ids. Host-side; dictionaries are small relative to data."""
    va = da.values if da is not None else np.asarray([], dtype=object)
    vb = db.values if db is not None else np.asarray([], dtype=object)
    union = np.asarray(sorted(set(va.tolist()) | set(vb.tolist())), dtype=object)
    uinfo = DictInfo.from_values(union)
    ustr = union.astype(str)
    lut_a = np.searchsorted(ustr, va.astype(str)).astype(np.int32) if len(va) else np.zeros(0, np.int32)
    lut_b = np.searchsorted(ustr, vb.astype(str)).astype(np.int32) if len(vb) else np.zeros(0, np.int32)
    return uinfo, lut_a, lut_b


def rank_lane(c: Compiled, comp: "ExprCompiler") -> Compiled:
    """Order-comparable lane for a string expression: the id lane itself when
    the dictionary is sorted (ids are ranks), else a gather through the
    lazily-computed rank LUT. Appends a mark — sortedness is dictionary
    CONTENT, so it must influence the caller's compile-cache key."""
    needs = c.out_dict is not None and not c.out_dict.is_sorted
    comp.marks.append(("rank_lane", needs))
    if not needs:
        return c
    ri = comp.pool.add(c.out_dict.ranks())

    def fn(env):
        v, nl = c.fn(env)
        return _gather_const(v, env.consts[ri]), nl
    return Compiled(fn, c.dtype, None)


def _remap_ids(ids, lut: np.ndarray):
    if len(lut) == 0:
        return jnp.zeros_like(ids)
    return jnp.take(jnp.asarray(lut), jnp.clip(ids, 0, len(lut) - 1))


def _gather_const(ids, lut):
    """Gather through a (padded) const-pool array passed at runtime. Live-row
    ids are always < the true dictionary length, so clipping to the padded
    length is safe; dead lanes gather padding, which nothing reads."""
    return jnp.take(lut, jnp.clip(ids, 0, lut.shape[0] - 1))


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", flags=re.DOTALL)


# --- date math (civil calendar <-> days since 1970-01-01; vectorized, int ops only,
#     after Howard Hinnant's algorithms — jit/TPU friendly) -----------------------

def civil_from_days(z):
    z = z.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil_py(y: int, m: int, d: int) -> int:
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# ---------------------------------------------------------------------------------

class ExprCompiler:
    """Compiles bound expressions against a fixed input batch *prototype* (schema +
    per-column dictionaries). The produced callables are jit-traceable.

    Dictionary-derived values feed the callables through `pool` (see ConstPool);
    every structural decision that depends on dictionary content (not just its
    shape) is appended to `marks`, and (pool.signature(), marks) joins the
    executor's compile-cache key — so a cached executable is only reused when
    the new compile would have traced the identical program."""

    def __init__(self, dicts: list, pool: Optional[ConstPool] = None,
                 bounds: Optional[list] = None):
        self.dicts = dicts  # per input-column Optional[DictInfo]
        self.bounds = bounds  # per input-column Optional[(lo, hi)]; None = all unknown
        self.pool = pool if pool is not None else ConstPool()
        self.marks: list = []

    @staticmethod
    def for_batch(batch: DeviceBatch, pool: Optional[ConstPool] = None) -> "ExprCompiler":
        return ExprCompiler([c.dictionary for c in batch.columns], pool,
                            bounds=[c.bounds for c in batch.columns])

    def compile(self, e: E.Expr) -> Compiled:
        m = getattr(self, "_c_" + type(e).__name__.lower(), None)
        if m is None:
            raise ExprCompileError(f"cannot compile {type(e).__name__}: {e!r}")
        return m(e)

    # --- leaves ---

    def _c_column(self, e: E.Column) -> Compiled:
        idx = e.index
        if idx is None:
            raise ExprCompileError(f"unbound column {e.name}")
        d = self.dicts[idx] if idx < len(self.dicts) else None
        b = self.bounds[idx] if self.bounds and idx < len(self.bounds) else None
        return Compiled(lambda env: (env.values[idx], env.nulls[idx]), e.dtype,
                        d, out_bounds=b)

    def _c_literal(self, e: E.Literal) -> Compiled:
        dt = e.dtype or e.literal_type
        if e.value is None:
            return Compiled(
                lambda env: (jnp.zeros_like(env.values[0] if env.values else jnp.zeros(1), dtype=jnp.int32),
                             jnp.ones(env.values[0].shape if env.values else (1,), dtype=bool)),
                T.NULL, None)
        if dt is not None and dt.is_string:
            dinfo = DictInfo.from_values([e.value])
            return Compiled(lambda env: (jnp.zeros(_cap(env), dtype=jnp.int32), None), dt, dinfo)
        np_dtype = dt.device_dtype() if dt else np.dtype("float64")
        val = np_dtype.type(e.value)
        return Compiled(lambda env: (jnp.full(_cap(env), val, dtype=np_dtype), None), dt, None)

    def _c_alias(self, e: E.Alias) -> Compiled:
        return self.compile(e.operand)

    def _c_cast(self, e: E.Cast) -> Compiled:
        c = self.compile(e.operand)
        to = e.to
        if c.dtype.id == T.TypeId.DATE32 and to.id == T.TypeId.TIMESTAMP:
            def fn(env):
                vals, nulls = c.fn(env)
                return vals.astype(jnp.int64) * np.int64(86_400_000_000), nulls
            return Compiled(fn, to, None)
        if c.dtype.id == T.TypeId.TIMESTAMP and to.id == T.TypeId.DATE32:
            def fn(env):
                vals, nulls = c.fn(env)
                return jnp.floor_divide(vals, np.int64(86_400_000_000)).astype(jnp.int32), nulls
            return Compiled(fn, to, None)
        if c.dtype.is_string and not to.is_string:
            # cast string -> numeric/temporal: parse the dictionary host-side
            d = c.out_dict
            dlen = len(d) if d is not None else 0
            parsed = np.zeros(max(dlen, 1), dtype=to.device_dtype())
            bad = np.zeros(max(dlen, 1), dtype=bool)
            for i, v in enumerate(d.values if d else []):
                if to.is_temporal:
                    # ISO date/timestamp strings. Unparseable entries become
                    # NULL (bad-flag), matching the numeric branch below: the
                    # dictionary covers the WHOLE column as scanned, so entries
                    # excluded by filters must not poison the query.
                    import datetime as _dt
                    try:
                        if to.id == T.TypeId.DATE32:
                            dd = _dt.date.fromisoformat(str(v).strip())
                            parsed[i] = dd.toordinal() - _dt.date(1970, 1, 1).toordinal()
                        else:
                            ts = _dt.datetime.fromisoformat(str(v).strip())
                            if ts.tzinfo is not None:
                                ts = ts.astimezone(_dt.timezone.utc) \
                                    .replace(tzinfo=None)
                            parsed[i] = (ts - _dt.datetime(1970, 1, 1)) \
                                // _dt.timedelta(microseconds=1)
                    except (ValueError, TypeError):
                        bad[i] = True
                    continue
                try:
                    parsed[i] = to.device_dtype().type(float(v) if to.is_float else int(float(v)))
                except (ValueError, TypeError):
                    bad[i] = True
            pi, bi = self.pool.add(parsed), self.pool.add(bad)

            def fn(env):
                vals, nulls = c.fn(env)
                return (_gather_const(vals, env.consts[pi]),
                        _or_nulls(nulls, _gather_const(vals, env.consts[bi])))
            return Compiled(fn, to, None)
        if not c.dtype.is_string and to.is_string:
            raise ExprCompileError("cast to string is evaluated host-side only")
        np_dtype = to.device_dtype()

        def fn(env):
            vals, nulls = c.fn(env)
            return vals.astype(np_dtype), nulls
        return Compiled(fn, to, c.out_dict if to.is_string else None)

    # --- boolean / null ---

    def _c_not(self, e: E.Not) -> Compiled:
        c = self.compile(e.operand)

        def fn(env):
            vals, nulls = c.fn(env)
            return ~vals, nulls
        return Compiled(fn, T.BOOL, None)

    def _c_negate(self, e: E.Negate) -> Compiled:
        c = self.compile(e.operand)

        def fn(env):
            vals, nulls = c.fn(env)
            return -vals, nulls
        return Compiled(fn, c.dtype, None)

    def _c_isnull(self, e: E.IsNull) -> Compiled:
        c = self.compile(e.operand)
        neg = e.negated

        def fn(env):
            vals, nulls = c.fn(env)
            isn = nulls if nulls is not None else jnp.zeros(vals.shape, dtype=bool)
            return (~isn if neg else isn), None
        return Compiled(fn, T.BOOL, None)

    # --- binary ---

    def _c_binary(self, e: E.Binary) -> Compiled:
        lc, rc = self.compile(e.left), self.compile(e.right)
        op = e.op
        if op in (E.BinOp.AND, E.BinOp.OR):
            return self._compile_kleene(op, lc, rc)
        if lc.dtype.is_string and rc.dtype.is_string:
            return self._compile_string_compare(op, lc, rc)
        if lc.dtype.is_string or rc.dtype.is_string:
            raise ExprCompileError(f"type mismatch in {e!r}")
        return self._compile_numeric_binary(op, lc, rc, e.dtype)

    def _compile_kleene(self, op, lc: Compiled, rc: Compiled) -> Compiled:
        if op is E.BinOp.AND:
            def fn(env):
                lv, ln = lc.fn(env)
                rv, rn = rc.fn(env)
                val = lv & rv
                if ln is None and rn is None:
                    return val, None
                lt = lv | (ln if ln is not None else False)
                rt = rv | (rn if rn is not None else False)
                ln_ = ln if ln is not None else jnp.zeros(lv.shape, bool)
                rn_ = rn if rn is not None else jnp.zeros(rv.shape, bool)
                # NULL unless one side is definitively FALSE
                nulls = (ln_ | rn_) & lt & rt
                return val & ~nulls, nulls
        else:
            def fn(env):
                lv, ln = lc.fn(env)
                rv, rn = rc.fn(env)
                val = lv | rv
                if ln is None and rn is None:
                    return val, None
                lf = ~lv | (ln if ln is not None else False)
                rf = ~rv | (rn if rn is not None else False)
                ln_ = ln if ln is not None else jnp.zeros(lv.shape, bool)
                rn_ = rn if rn is not None else jnp.zeros(rv.shape, bool)
                nulls = (ln_ | rn_) & lf & rf
                return val & ~nulls, nulls
        return Compiled(fn, T.BOOL, None)

    def _compile_numeric_binary(self, op, lc: Compiled, rc: Compiled, out_dtype) -> Compiled:
        if op in E.COMPARISONS:
            res_dtype = T.BOOL
            wd = T.common_type(lc.dtype, rc.dtype).device_dtype()
        else:
            res_dtype = out_dtype or T.common_type(lc.dtype, rc.dtype)
            wd = res_dtype.device_dtype()
        integer_div = op is E.BinOp.DIV and res_dtype.is_integer
        # DATE32 lanes are days, TIMESTAMP lanes are microseconds: when the two mix,
        # scale the date side up so comparisons/arithmetic share one unit.
        scale_l = (lc.dtype.id == T.TypeId.DATE32 and rc.dtype.id == T.TypeId.TIMESTAMP)
        scale_r = (rc.dtype.id == T.TypeId.DATE32 and lc.dtype.id == T.TypeId.TIMESTAMP)

        def fn(env):
            lv, ln = lc.fn(env)
            rv, rn = rc.fn(env)
            if scale_l:
                lv = lv.astype(jnp.int64) * np.int64(86_400_000_000)
            if scale_r:
                rv = rv.astype(jnp.int64) * np.int64(86_400_000_000)
            lvw = lv.astype(wd) if lv.dtype != wd else lv
            rvw = rv.astype(wd) if rv.dtype != wd else rv
            nulls = _or_nulls(ln, rn)
            if op is E.BinOp.ADD:
                out = lvw + rvw
            elif op is E.BinOp.SUB:
                out = lvw - rvw
            elif op is E.BinOp.MUL:
                out = lvw * rvw
            elif op is E.BinOp.DIV:
                if integer_div:  # SQL truncating integer division; x/0 -> NULL
                    zero = rvw == 0
                    safe = jnp.where(zero, 1, rvw)
                    q = jnp.trunc(lvw.astype(jnp.float64) / safe.astype(jnp.float64)).astype(wd)
                    out = jnp.where(zero, 0, q)
                    nulls = _or_nulls(nulls, zero)
                else:
                    zero = rvw == 0
                    out = jnp.where(zero, 0, lvw / jnp.where(zero, 1, rvw))
                    nulls = _or_nulls(nulls, zero)
            elif op is E.BinOp.MOD:
                zero = rvw == 0
                safe = jnp.where(zero, 1, rvw)
                out = lvw - jnp.trunc(lvw.astype(jnp.float64) / safe.astype(jnp.float64)).astype(wd) * safe
                nulls = _or_nulls(nulls, zero)
            elif op is E.BinOp.EQ:
                out = lvw == rvw
            elif op is E.BinOp.NEQ:
                out = lvw != rvw
            elif op is E.BinOp.LT:
                out = lvw < rvw
            elif op is E.BinOp.LTE:
                out = lvw <= rvw
            elif op is E.BinOp.GT:
                out = lvw > rvw
            else:
                out = lvw >= rvw
            return out, nulls
        return Compiled(fn, res_dtype, None)

    def _compile_string_compare(self, op, lc: Compiled, rc: Compiled) -> Compiled:
        """Compare two string expressions. Same-dictionary columns compare by id
        (sorted dictionary => ids are lexicographic ranks; unsorted => order
        comparisons go through the rank LUT); otherwise remap both through the
        union dictionary host-side, then compare ids."""
        same = lc.out_dict is rc.out_dict and lc.out_dict is not None
        self.marks.append(("strcmp_same", same))
        if same:
            li = ri = None
            if op not in (E.BinOp.EQ, E.BinOp.NEQ):
                lc = rank_lane(lc, self)
                rc = rank_lane(rc, self)
        else:
            _, lut_l, lut_r = _unify_dicts(lc.out_dict, rc.out_dict)
            li, ri = self.pool.add(lut_l), self.pool.add(lut_r)

        def fn(env):
            lv, ln = lc.fn(env)
            rv, rn = rc.fn(env)
            if li is not None:
                lv = _gather_const(lv, env.consts[li])
                rv = _gather_const(rv, env.consts[ri])
            nulls = _or_nulls(ln, rn)
            if op is E.BinOp.EQ:
                out = lv == rv
            elif op is E.BinOp.NEQ:
                out = lv != rv
            elif op is E.BinOp.LT:
                out = lv < rv
            elif op is E.BinOp.LTE:
                out = lv <= rv
            elif op is E.BinOp.GT:
                out = lv > rv
            elif op is E.BinOp.GTE:
                out = lv >= rv
            else:
                raise ExprCompileError(f"string op {op}")
            return out, nulls
        return Compiled(fn, T.BOOL, None)

    # --- CASE / IN / LIKE ---

    def _c_case(self, e: E.Case) -> Compiled:
        whens = [(self.compile(c), self.compile(v)) for c, v in e.whens]
        else_c = self.compile(e.else_) if e.else_ is not None else None
        out_dtype = e.dtype
        if out_dtype.is_string:
            branches = [v for _, v in whens] + ([else_c] if else_c else [])
            all_vals = sorted({str(v) for b in branches if b.out_dict is not None
                               for v in b.out_dict.values})
            out_dict = DictInfo.from_values(np.asarray(all_vals, dtype=object))
            ustr = out_dict.values.astype(str) if len(out_dict) else np.asarray([], dtype=str)
            luts = []
            for b in branches:
                bv = b.out_dict.values if b.out_dict is not None else np.asarray([], dtype=object)
                luts.append(self.pool.add(
                    np.searchsorted(ustr, bv.astype(str)).astype(np.int32)
                    if len(bv) else np.zeros(0, np.int32)))
        else:
            luts = None
            out_dict = None
        wd = out_dtype.device_dtype()

        def fn(env):
            vals = [v.fn(env) for _, v in whens]
            conds = [c.fn(env) for c, _ in whens]
            if else_c is not None:
                ev, en = else_c.fn(env)
            else:
                ev = jnp.zeros(_cap(env), dtype=wd)
                en = jnp.ones(_cap(env), dtype=bool)
            if luts is not None:
                vals = [(_gather_const(v, env.consts[luts[i]]), nn)
                        for i, (v, nn) in enumerate(vals)]
                if else_c is not None:
                    ev = _gather_const(ev, env.consts[luts[-1]])
            out = ev.astype(wd)
            out_null = en if en is not None else jnp.zeros(_cap(env), bool)
            # fold from last WHEN to first so earlier WHENs win
            for (cv, cn), (vv, vn) in zip(reversed(conds), reversed(vals)):
                take = cv & (~cn if cn is not None else True)
                out = jnp.where(take, vv.astype(wd), out)
                vn_ = vn if vn is not None else jnp.zeros(_cap(env), bool)
                out_null = jnp.where(take, vn_, out_null)
            return out, out_null
        return Compiled(fn, out_dtype, out_dict)

    def _c_inlist(self, e: E.InList) -> Compiled:
        c = self.compile(e.operand)
        neg = e.negated
        has_null_item = any(isinstance(i, E.Literal) and i.value is None for i in e.items)
        items = [i for i in e.items if not (isinstance(i, E.Literal) and i.value is None)]
        if c.dtype.is_string:
            # membership over the dictionary host-side -> id lookup table
            for i in items:
                if not isinstance(i, E.Literal):
                    raise ExprCompileError("string IN list items must be literals")
            item_vals = {i.value for i in items}
            d = c.out_dict
            dlen = len(d) if d is not None else 0
            lut = np.zeros(max(dlen, 1), dtype=bool)
            for i, v in enumerate(d.values if d is not None else []):
                lut[i] = v in item_vals
            lj = self.pool.add(lut)

            def fn(env):
                vals, nulls = c.fn(env)
                out = _gather_const(vals, env.consts[lj])
                if has_null_item:
                    # x IN (..., NULL): NULL unless a real match; NOT IN never TRUE
                    nulls = _or_nulls(nulls, ~out)
                return (~out if neg else out), nulls
            return Compiled(fn, T.BOOL, None)
        item_cs = [self.compile(i) for i in items]
        # SQL compares in the common type: widen both sides (a=1 IN (1.5) is FALSE,
        # not a truncated match)
        wide = c.dtype
        for ic in item_cs:
            wide = T.common_type(wide, ic.dtype)
        wd = wide.device_dtype()

        def fn(env):
            vals, nulls = c.fn(env)
            vw = vals.astype(wd)
            out = jnp.zeros(vals.shape, dtype=bool)
            for ic in item_cs:
                iv, _ = ic.fn(env)
                out = out | (vw == iv.astype(wd))
            if has_null_item:
                nulls = _or_nulls(nulls, ~out)
            return (~out if neg else out), nulls
        return Compiled(fn, T.BOOL, None)

    def _c_like(self, e: E.Like) -> Compiled:
        c = self.compile(e.operand)
        if not c.dtype.is_string:
            raise ExprCompileError("LIKE on non-string")
        rx = _like_to_regex(e.pattern.lower() if e.case_insensitive else e.pattern)
        d = c.out_dict
        lut = np.zeros(max(len(d) if d else 0, 1), dtype=bool)
        for i, v in enumerate(d.values if d else []):
            s = str(v).lower() if e.case_insensitive else str(v)
            lut[i] = rx.match(s) is not None
        neg = e.negated
        lj = self.pool.add(lut)

        def fn(env):
            vals, nulls = c.fn(env)
            out = _gather_const(vals, env.consts[lj])
            return (~out if neg else out), nulls
        return Compiled(fn, T.BOOL, None)

    # --- scalar functions ---

    def _c_func(self, e: E.Func) -> Compiled:
        name = e.name.lower()
        args = [self.compile(a) for a in e.args]
        if name in _STRING_FUNCS:
            return self._compile_string_func(name, e, args)
        if name in ("year", "month", "day", "extract_year", "extract_month", "extract_day"):
            which = name.split("_")[-1]
            c = args[0]

            def fn(env, _which=which):
                vals, nulls = c.fn(env)
                if c.dtype.id == T.TypeId.TIMESTAMP:
                    vals = jnp.floor_divide(vals, np.int64(86_400_000_000)).astype(jnp.int32)
                y, m, d = civil_from_days(vals)
                return {"year": y, "month": m, "day": d}[_which].astype(jnp.int32), nulls
            return Compiled(fn, T.INT32, None)
        if name == "coalesce":
            out_dtype = e.dtype
            if out_dtype.is_string:
                # unify all argument dictionaries so every branch's ids decode
                # against one output dictionary
                all_vals = sorted({str(v) for a in args if a.out_dict is not None
                                   for v in a.out_dict.values})
                od = DictInfo.from_values(np.asarray(all_vals, dtype=object))
                ustr = od.values.astype(str) if len(od) else np.asarray([], dtype=str)
                luts = []
                for a in args:
                    av = a.out_dict.values if a.out_dict is not None else np.asarray([], dtype=object)
                    luts.append(self.pool.add(
                        np.searchsorted(ustr, av.astype(str)).astype(np.int32)
                        if len(av) else np.zeros(0, np.int32)))
            else:
                od, luts = None, None

            def fn(env):
                out_v = None
                out_n = None
                for i, c in enumerate(args):
                    v, nn = c.fn(env)
                    if luts is not None:
                        v = _gather_const(v, env.consts[luts[i]])
                    v = v.astype(out_dtype.device_dtype())
                    if out_v is None:
                        out_v, out_n = v, (nn if nn is not None else jnp.zeros(v.shape, bool))
                    else:
                        take = out_n
                        out_v = jnp.where(take, v, out_v)
                        nn_ = nn if nn is not None else jnp.zeros(v.shape, bool)
                        out_n = out_n & nn_
                return out_v, out_n
            return Compiled(fn, out_dtype, od)
        if name == "nullif":
            a, b = args
            unify = a.dtype.is_string and b.dtype.is_string and \
                a.out_dict is not b.out_dict
            self.marks.append(("nullif_unify", unify))
            if unify:
                _, lut_a, lut_b = _unify_dicts(a.out_dict, b.out_dict)
                ai, bi = self.pool.add(lut_a), self.pool.add(lut_b)
            else:
                ai = bi = None

            def fn(env):
                av, an = a.fn(env)
                bv, bn = b.fn(env)
                acmp = _gather_const(av, env.consts[ai]) if ai is not None else av
                bcmp = _gather_const(bv, env.consts[bi]) if bi is not None else bv
                eq = (acmp == bcmp) & (~bn if bn is not None else True)
                return av, _or_nulls(an, eq)
            return Compiled(fn, a.dtype, a.out_dict)
        unary = {
            "abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil, "sqrt": jnp.sqrt,
            "exp": jnp.exp, "ln": jnp.log, "log": jnp.log, "log10": jnp.log10,
            "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "sign": jnp.sign,
        }
        if name in unary:
            c = args[0]
            f = unary[name]
            out_dtype = e.dtype

            def fn(env):
                vals, nulls = c.fn(env)
                return f(vals.astype(out_dtype.device_dtype())), nulls
            return Compiled(fn, out_dtype, None)
        if name == "round":
            c = args[0]
            digits = _literal_int_arg(name, e.args, 1) if len(e.args) > 1 else 0
            scale = 10.0 ** digits

            def fn(env):
                vals, nulls = c.fn(env)
                return jnp.round(vals.astype(jnp.float64) * scale) / scale, nulls
            return Compiled(fn, T.FLOAT64, None)
        if name in ("power", "pow"):
            a, b = args

            def fn(env):
                av, an = a.fn(env)
                bv, bn = b.fn(env)
                return jnp.power(av.astype(jnp.float64), bv.astype(jnp.float64)), _or_nulls(an, bn)
            return Compiled(fn, T.FLOAT64, None)
        raise ExprCompileError(f"unknown function {name}")

    def _compile_string_func(self, name: str, e: E.Func, args: list) -> Compiled:
        """String functions evaluate over the dictionary on host; device ids remap."""
        c = args[0]
        d = c.out_dict or DictInfo.from_values([])

        def str_transform(f):
            new_vals = [f(str(v)) for v in d.values]
            uniq, inverse = np.unique(np.asarray(new_vals, dtype=object).astype(str), return_inverse=True)
            new_dict = DictInfo.from_values(uniq.astype(object))
            li = self.pool.add(inverse.astype(np.int32)
                               if len(new_vals) else np.zeros(0, np.int32))

            def fn(env):
                vals, nulls = c.fn(env)
                return _gather_const(vals, env.consts[li]), nulls
            return Compiled(fn, T.STRING, new_dict)

        if name == "upper":
            return str_transform(lambda s: s.upper())
        if name == "lower":
            return str_transform(lambda s: s.lower())
        if name == "capitalize":
            # parity with the reference's capitalize UDF (crates/engine/src/lib.rs:71-95):
            # first char upper, rest lower
            return str_transform(lambda s: (s[:1].upper() + s[1:].lower()) if s else s)
        if name == "trim":
            return str_transform(lambda s: s.strip())
        if name in ("left", "right"):
            n_chars = _literal_int_arg(name, e.args, 1)
            if name == "left":
                return str_transform(lambda s: s[:n_chars])
            return str_transform(lambda s: s[-n_chars:] if n_chars else "")
        if name in ("substr", "substring"):
            start = _literal_int_arg(name, e.args, 1)
            length = _literal_int_arg(name, e.args, 2) if len(e.args) > 2 else None
            i0 = max(start - 1, 0)

            def sub(s):
                return s[i0: i0 + length] if length is not None else s[i0:]
            return str_transform(sub)
        if name in ("length", "char_length", "character_length"):
            lens = np.asarray([len(str(v)) for v in d.values], dtype=np.int32)
            lj = self.pool.add(lens)

            def fn(env):
                vals, nulls = c.fn(env)
                return _gather_const(vals, env.consts[lj]), nulls
            return Compiled(fn, T.INT32, None)
        if name == "concat":
            # concat of string exprs: only dictionary-expressible when arity small;
            # compile as pairwise host product — practical for low-cardinality dims
            if len(args) == 1:
                return args[0]
            left = args[0]
            for right in args[1:]:
                left = self._concat2(left, right)
            return left
        raise ExprCompileError(f"unknown string function {name}")

    def _concat2(self, lc: Compiled, rc: Compiled) -> Compiled:
        dl = lc.out_dict or DictInfo.from_values([])
        dr = rc.out_dict or DictInfo.from_values([])
        nl, nr = max(len(dl), 1), max(len(dr), 1)
        if nl * nr > 1_000_000:
            raise ExprCompileError("concat dictionary product too large")
        prod = np.asarray([str(a) + str(b) for a in (dl.values if len(dl) else [""])
                           for b in (dr.values if len(dr) else [""])], dtype=object)
        uniq, inverse = np.unique(prod.astype(str), return_inverse=True)
        new_dict = DictInfo.from_values(uniq.astype(object))
        lj = self.pool.add(inverse.astype(np.int32).reshape(nl, nr))

        def fn(env):
            lv, ln = lc.fn(env)
            rv, rn = rc.fn(env)
            lut = env.consts[lj]
            li = jnp.clip(lv, 0, lut.shape[0] - 1)
            ri = jnp.clip(rv, 0, lut.shape[1] - 1)
            return lut[li, ri], _or_nulls(ln, rn)
        return Compiled(fn, T.STRING, new_dict)


_STRING_FUNCS = {"upper", "lower", "capitalize", "trim", "substr", "substring",
                 "length", "char_length", "character_length", "concat", "left", "right"}


def _literal_int_arg(fname: str, args: list, i: int) -> int:
    """Dictionary-level string transforms need static (literal) count arguments."""
    if i >= len(args):
        raise ExprCompileError(f"{fname} expects an argument at position {i + 1}")
    a = args[i]
    if not isinstance(a, E.Literal) or isinstance(a.value, bool) or \
            not isinstance(a.value, (int, float)):
        raise ExprCompileError(f"{fname} argument {i + 1} must be an integer literal")
    return int(a.value)


def _cap(env: Env) -> int:
    return env.values[0].shape[0] if env.values else 1
