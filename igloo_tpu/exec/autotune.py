"""Per-shape Pallas kernel autotuner (ROADMAP item 1, PR19).

The dispatch planners (exec/dispatch.py) choose block/table shapes from
module-level defaults — one window, one block size, one bucket shift for
every capacity. The way "Ragged Paged Attention" kernels ship per-shape
tuning tables (PAPERS.md), this module sweeps a small candidate grid per
(kernel, canonical capacity) pair, benchmarks each candidate on synthetic
lanes, and persists the winners in a JSON tuning table beside the XLA
compile cache (the `_JsonStore` idiom, exec/hints.py):

    {"version": 3,
     "entries": {"probe/65536":  {"window": 16, "block": 1024,
                                  "bucket_shift": 3},
                 "segagg/65536": {"ways": 8, "block": 1024},
                 "scatter/65536": {"block": 1024}, ...}}

Keys are ``<kernel>/<canonical capacity>`` — capacities are family members
(exec/capacity.py), so the table stays as small as the engine's shape
vocabulary. ``dispatch.cache_token()`` folds ``table_version()`` into every
jit cache key: adopting new winners (a local sweep OR a cluster-replicated
table) bumps the version and can never serve a trace planned under the old
shapes.

Knobs:
  ``IGLOO_TPU_AUTOTUNE``   0 = off (module defaults, version 0) | auto
                           (default: consult persisted winners; never sweep
                           inline) | sweep (benchmark candidates at first
                           real use of a (kernel, capacity) pair)
  ``IGLOO_AUTOTUNE_TABLE`` explicit table path (tests / shared clusters);
                           default: ``autotune.json`` beside the XLA cache,
                           in-memory only when no cache dir is configured.

Cluster replication rides the EXISTING compile-cache transfer: the table
file lives beside the cache entries, so workers pull it at registration and
push it on heartbeats through the same ``compile_cache_get``/``put`` Flight
actions. The one twist is that the table is MUTABLE — two sides may hold
different versions — so this module registers a merge hook with
``compile_cache.write_entry``: incoming bytes are merged entry-wise (the
higher-version side wins), and adoption resets the process singleton so the
next ``cache_token()`` sees the new version.

Access policy: this module and ``exec/dispatch.py`` are the ONLY legal
importers of ``pallas_kernels`` (igloo-lint ``pallas-dispatch`` rule) — the
sweep benchmarks candidates by invoking the kernels directly, outside the
dispatch ladder, on synthetic lanes that never touch query data.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from igloo_tpu.exec.hints import _JsonStore
from igloo_tpu.utils import tracing

# lock discipline (igloo-lint lock-discipline): the singleton is shared by
# every executor and the Flight merge hook runs on RPC handler threads
_GUARDED_BY = {"_lock": ("_data", "_dirty")}

AUTOTUNE_ENV = "IGLOO_TPU_AUTOTUNE"
TABLE_PATH_ENV = "IGLOO_AUTOTUNE_TABLE"

#: the table's file name beside the XLA cache — also its compile-cache
#: entry name on the wire (cluster/worker.py pull/push)
TABLE_ENTRY = "autotune.json"

#: candidate grids per kernel: small on purpose — every candidate costs a
#: benchmark run, and the winning shapes plug into the planners' eligibility
#: clamps unchanged (a tuned block is still forced through pow2_block)
CANDIDATES = {
    "probe": [{"window": w, "block": b, "bucket_shift": s}
              for w in (8, 16, 32) for b in (512, 1024) for s in (2, 3)],
    "segagg": [{"ways": w, "block": b}
               for w in (4, 8, 16) for b in (512, 1024)],
    "scatter": [{"block": b} for b in (256, 1024, 4096)],
    "match": [{"window": w, "block": b}
              for w in (8, 16, 32) for b in (512, 1024)],
    "topk": [{"block": b} for b in (512, 1024, 2048)],
}

#: timed repetitions per candidate (plus one warmup/compile run)
_BENCH_REPS = 2


def mode() -> str:
    """Normalized ``IGLOO_TPU_AUTOTUNE``: 0 | auto | sweep."""
    raw = os.environ.get(AUTOTUNE_ENV, "auto").strip().lower()
    return raw if raw in ("0", "sweep") else "auto"


class TuningTable(_JsonStore):
    """{"version": int, "entries": {"<kernel>/<cap>": {param: int}}} with
    the `_JsonStore` atomic-flush/never-fail contract. The version bumps on
    every local winner adoption and on every merge that changed anything —
    it exists solely to flip ``dispatch.cache_token()``."""

    def _coerce(self, raw: dict) -> dict:
        entries = {}
        for k, v in raw.get("entries", {}).items():
            if isinstance(k, str) and isinstance(v, dict):
                entries[k] = {p: int(x) for p, x in v.items()
                              if isinstance(x, (int, float))}
        return {"version": int(raw.get("version", 0)), "entries": entries}

    def version(self) -> int:
        with self._lock:
            return int(self._data.get("version", 0))

    def lookup(self, kernel: str, cap: int) -> Optional[dict]:
        with self._lock:
            rec = self._data.get("entries", {}).get(f"{kernel}/{int(cap)}")
            return dict(rec) if rec is not None else None

    def record(self, kernel: str, cap: int, params: dict) -> None:
        clean = {p: int(x) for p, x in params.items()}
        with self._lock:
            entries = self._data.setdefault("entries", {})
            key = f"{kernel}/{int(cap)}"
            if entries.get(key) != clean:
                entries[key] = clean
                self._data["version"] = int(self._data.get("version", 0)) + 1
                self._dirty = True
        self.flush()

    def merge_raw(self, raw: dict) -> bool:
        """Adopt a remote table: entry-wise, the higher-version side wins on
        conflicts; the merged version is max(local, remote), +1 when the
        merge changed local entries (so BOTH sides converge to a version at
        least as new as either input). Returns True when anything changed."""
        other = self._coerce(raw if isinstance(raw, dict) else {})
        with self._lock:
            ours = int(self._data.get("version", 0))
            theirs = other["version"]
            entries = self._data.setdefault("entries", {})
            changed = False
            for k, v in other["entries"].items():
                if k not in entries or (theirs > ours and entries[k] != v):
                    if entries.get(k) != v:
                        entries[k] = v
                        changed = True
            if changed:
                self._data["version"] = max(ours, theirs) + 1
                self._dirty = True
            elif theirs > ours:
                self._data["version"] = theirs
                self._dirty = True
                changed = True
        if changed:
            self.flush()
        return changed

    def snapshot(self) -> dict:
        with self._lock:
            return json.loads(json.dumps(
                {"version": self._data.get("version", 0),
                 "entries": self._data.get("entries", {})}))


_singleton_lock = threading.Lock()
_singleton: Optional[TuningTable] = None


def table() -> TuningTable:
    """Process-wide tuning table. Path precedence mirrors
    ``hints.adaptive_store()``: IGLOO_AUTOTUNE_TABLE env > beside the
    persistent XLA cache > in-memory only."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            path = os.environ.get(TABLE_PATH_ENV)
            if path is None:
                from igloo_tpu import compile_cache
                cache_dir = compile_cache.active_dir()
                if cache_dir:
                    path = os.path.join(cache_dir, TABLE_ENTRY)
            _singleton = TuningTable(path or None)
        return _singleton


def reset_table() -> None:
    """Drop the process singleton (tests re-point IGLOO_AUTOTUNE_TABLE; the
    compile-cache merge hook re-reads an updated file)."""
    global _singleton
    with _singleton_lock:
        _singleton = None


def table_version() -> int:
    """The component ``dispatch.cache_token()`` folds into every jit key —
    0 whenever autotuning is off (plans then never read the table)."""
    if mode() == "0":
        return 0
    return table().version()


def shapes(kernel: str, cap: int) -> dict:
    """Tuned shape overrides for (kernel, canonical capacity) — {} when off
    or untuned (module defaults apply). In sweep mode, a miss for a swept
    kernel benchmarks the candidate grid right here (first real use) and
    persists the winner."""
    if mode() == "0":
        return {}
    t = table()
    rec = t.lookup(kernel, cap)
    if rec is not None:
        tracing.counter("autotune.hit")
        return rec
    if mode() == "sweep" and kernel in CANDIDATES:
        rec = sweep(kernel, cap)
        if rec is not None:
            return rec
    tracing.counter("autotune.miss")
    return {}


# --- candidate benchmarking -------------------------------------------------


def _bench_candidate(kernel: str, cap: int, params: dict) -> Optional[float]:
    """Wall seconds for one candidate on synthetic lanes at `cap`, or None
    when the candidate cannot run (shape ineligibility, compile failure).
    Kernels run exactly as dispatch would invoke them — interpret mode off
    TPU — on deterministic synthetic data sized like a real operand set."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from igloo_tpu.exec import dispatch, pallas_kernels
    from igloo_tpu.exec.capacity import pow2_block

    on, interp = dispatch.kernel_state()
    if not on:
        return None
    rng = np.random.default_rng(cap ^ 0x5EED)

    def timed(fn):
        try:
            jax.block_until_ready(fn())  # warmup / compile
            t0 = time.perf_counter()
            for _ in range(_BENCH_REPS):
                jax.block_until_ready(fn())
            return (time.perf_counter() - t0) / _BENCH_REPS
        except Exception:
            return None

    if kernel == "probe":
        block = pow2_block(cap, int(params["block"]))
        window = int(params["window"])
        nbuckets = min(max(cap >> int(params["bucket_shift"]), 8),
                       dispatch.PROBE_MAX_BUCKETS)
        build = jnp.asarray(np.sort(rng.integers(0, cap, cap)).astype(np.int64))
        probe = jnp.asarray(rng.integers(0, cap, cap).astype(np.int64))
        return timed(lambda: pallas_kernels.hash_probe_bounds(
            build, probe, nbuckets, window, block, interp))
    if kernel == "segagg":
        ways = int(params["ways"])
        block = pow2_block(cap, int(params["block"]))
        nbuckets = max(min(cap * ways, dispatch.DIRECT_SEG_SMALL_LIMIT)
                       // ways, 8)
        packed = jnp.asarray(rng.integers(0, max(cap // 8, 2), cap)
                             .astype(np.int64))
        live = jnp.ones((cap,), bool)
        vals = jnp.asarray(rng.integers(0, 1000, cap).astype(np.int64))
        return timed(lambda: pallas_kernels.hash_segagg(
            packed, live, ("sum",), [live, vals], nbuckets, ways, block,
            interp))
    if kernel == "scatter":
        block = pow2_block(cap, int(params["block"]))
        lanes = [jnp.asarray(rng.integers(0, 1 << 62, cap, dtype=np.int64)
                             .astype(np.uint64)) for _ in range(2)]
        live = jnp.ones((cap,), bool)
        return timed(lambda: pallas_kernels.hash_scatter(
            lanes, live, 64, block, interp))
    if kernel == "match":
        window = int(params["window"])
        block = pow2_block(cap, int(params["block"]))
        counts = rng.integers(0, 3, cap).astype(np.int32)
        prefix = np.cumsum(counts) - counts
        return timed(lambda: pallas_kernels.match_owner_table(
            jnp.asarray(prefix.astype(np.int64)), jnp.asarray(counts), cap,
            window, block, interp))
    if kernel == "topk":
        block = pow2_block(cap, int(params["block"]))
        k = min(64, block)
        keys = jnp.asarray(rng.integers(0, 1 << 40, cap).astype(np.int64))
        return timed(lambda: pallas_kernels.blocked_topk(
            keys, k, block, interp))
    return None


def sweep(kernel: str, cap: int) -> Optional[dict]:
    """Benchmark the candidate grid for (kernel, cap), persist the winner,
    and return its params (None when no candidate ran)."""
    tracing.counter("autotune.sweep")
    best, best_t = None, None
    for params in CANDIDATES.get(kernel, []):
        t = _bench_candidate(kernel, cap, params)
        if t is not None and (best_t is None or t < best_t):
            best, best_t = params, t
    if best is not None:
        table().record(kernel, cap, best)
    return best


def sweep_offline(kernels=None, caps=None) -> dict:
    """Offline sweep entry point (scripts/autotune_sweep.py): sweep every
    (kernel, capacity) pair and return {key: {params, seconds}}."""
    from igloo_tpu.exec.capacity import canonical_capacity, tuning_capacities
    kernels = list(kernels or CANDIDATES)
    caps = [canonical_capacity(c) for c in (caps or tuning_capacities())]
    out = {}
    for kern in kernels:
        for cap in caps:
            best = sweep(kern, cap)
            if best is not None:
                out[f"{kern}/{cap}"] = best
    return out


# --- cluster replication (compile-cache transfer merge hook) ----------------


def _merge_entry(existing: Optional[bytes], incoming: bytes) -> bytes:
    """compile_cache.write_entry hook for the table's entry: merge instead
    of first-writer-wins (the table is the one MUTABLE entry beside the
    immutable XLA programs)."""
    try:
        raw = json.loads(incoming.decode())
    except Exception:
        return existing if existing is not None else incoming
    t = table()
    t.merge_raw(raw)
    return json.dumps(t.snapshot()).encode()


def _on_adopted() -> None:
    """After the merged file lands: drop the singleton so the next
    ``table_version()`` (and therefore ``dispatch.cache_token()``) reads the
    adopted table."""
    reset_table()


def register_with_compile_cache() -> None:
    from igloo_tpu import compile_cache
    compile_cache.register_merge(TABLE_ENTRY, _merge_entry, _on_adopted)


register_with_compile_cache()
