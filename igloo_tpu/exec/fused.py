"""Whole-plan fusion: compile an entire logical plan into ONE jitted function.

The staged executor (exec/executor.py) dispatches one jit per plan node. On a
tunneled TPU every dispatch costs a host<->device round trip (~100-300 ms
measured), so an 11-stage TPC-H Q3 pays ~3 s of pure RTT while the device work
is tens of milliseconds. This module realizes SURVEY.md §7's design stance —
"each fragment lowers to ONE `jax.jit` computation" — end to end: the whole
query becomes a single XLA program: one dispatch, one small fetch.

The reference has no analog: its operators stream record batches through async
channels per node (crates/engine/src/physical_plan.rs:28-47), an architecture
that would serialize on the TPU's dispatch latency exactly like the staged path.

**Adaptive capacity hints.** Static shapes mean intermediate batches are padded
to their worst case (a filtered 6M-row lineitem keeps 8M lanes); carrying full
width through joins/aggregates/sorts costs ~0.1-1 s per 8M-lane gather/scatter.
Observed live counts from each run are recorded as per-node cardinality hints
(standard adaptive query execution, keyed by the node's structural fingerprint
— data changes change scan fingerprints and so invalidate hints naturally).
On later runs the program compacts intermediates down to the hinted power-of-two
capacity INSIDE the fused program; a deferred `n > capacity` flag triggers one
repair re-run with corrected hints, so results are always exact. Direct inner
joins go further: with a hint, build-side columns are gathered only AFTER the
probe-side compaction, at hinted width (lazy materialization).

Correctness flags collected across the program (direct-join duplicate keys,
speculative join capacity overflow, compaction overflow) come back in the same
single fetch; only a raised flag or an oversized result costs extra round trips.

Raises FusionUnsupported for shapes that need host decisions (non-speculative
joins past the capacity budget, distinct aggregates, set ops, unions); the
caller falls back to the staged executor.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Callable, Optional

import jax.numpy as jnp

from igloo_tpu import types as T
from igloo_tpu.exec import dispatch
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.aggregate import (
    AggSpec, aggregate_batch, distinct_batch, minmax_order_arg, seg_dims_for,
)
from igloo_tpu.exec.batch import (
    MIN_CAPACITY, DeviceBatch, DeviceColumn, round_capacity,
)
from igloo_tpu.exec.expr_compile import (
    ConstPool, Env, ExprCompiler, rank_lane,
)
from igloo_tpu.exec.join import (
    choose_direct_build, direct_join_phase, direct_probe, expand_phase,
    make_key_hash_idxs, probe_phase,
)
from igloo_tpu.exec.sort_limit import limit_batch, sort_batch, topk_batch
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import tracing


class FusionUnsupported(Exception):
    """This plan needs host-side decisions between stages; use the staged path."""


@dataclass
class NodeMeta:
    """Host-side metadata mirror of a node's output batch: what expression
    compilation and join planning need, computed without running the device."""
    schema: T.Schema
    dicts: list
    bounds: list
    capacity: int


@dataclass
class Ctx:
    """Trace-time side channels: flag/stat ids are assigned at compile time,
    values filled during tracing (dict keys are static pytree aux, so the
    ordering of appends never matters)."""
    flags: dict = field(default_factory=dict)  # id -> device bool
    stats: dict = field(default_factory=dict)  # id -> device int64 live count


# NodeFn: (leaves, consts, ctx) -> DeviceBatch (jit-traceable)
NodeFn = Callable

# node outputs wider than this become adaptive-compaction candidates
ADAPTIVE_CAPACITY = 1 << 18
# only compact when the hinted capacity shrinks the batch at least this much
ADAPTIVE_SHRINK = 4


class FusedCompiler:
    """One-shot compiler: plan -> (run, leaves, pool, cache_key, out_meta)."""

    # results at or under this capacity come back in the single fetch
    FETCH_CAPACITY = 1 << 12

    def __init__(self, executor):
        self.ex = executor
        self.pool = ConstPool()
        self.leaves: list[DeviceBatch] = []
        self.marks: list = []
        self.fps: list = []
        # hint-INDEPENDENT fingerprints: same node entries as fps but without
        # adopted-hint artifacts (acompact markers, lazy-join want sizes).
        # Hint keys derive from these, so adopting one node's hint never
        # changes another node's key — all hints adopt in ONE re-run instead
        # of cascading one plan level per run.
        self.hfps: list = []
        self.flag_tags: list = []   # flag id -> ("dup"|"overflow"|"compact", key)
        self.stat_keys: list = []   # stat id -> nhint cache key
        # negative-cache keys of every Pallas kernel this program planned:
        # the executor's compile-failure rung bans them all and recompiles
        # on the sort path when the program fails to lower
        self.pallas_bans: list = []

    # --- side-channel ids -------------------------------------------------

    def _push(self, fp, hint_fp="same") -> None:
        """Append a node fingerprint; hint_fp=None skips the hint list,
        any other value replaces the entry there."""
        self.fps.append(fp)
        if hint_fp == "same":
            self.hfps.append(fp)
        elif hint_fp is not None:
            self.hfps.append(hint_fp)

    def _new_flag(self, tag) -> int:
        self.flag_tags.append(tag)
        return len(self.flag_tags) - 1

    def _new_stat(self, key) -> int:
        self.stat_keys.append(key)
        return len(self.stat_keys) - 1

    def _hint(self, key) -> Optional[int]:
        v = self.ex._cache.get(("nhint", key))
        if v is None and self.ex._hints is not None:
            v = self.ex._hints.get(key)  # persistent store (fresh process)
            if v is not None:
                self.ex._cache[("nhint", key)] = v
        return int(v) if v is not None else None

    # --- public -----------------------------------------------------------

    def compile(self, plan: L.LogicalPlan):
        fn, meta = self._c(plan)
        # program-shape telemetry: how many plan nodes one dispatch covers
        # (the whole point of fusion) — system.metrics hist_max shows the
        # largest program this process compiled
        tracing.histogram("fused.nodes", len(self.fps))
        fetch_cap = self.FETCH_CAPACITY

        def run(leaves, consts):
            ctx = Ctx()
            out = fn(leaves, consts, ctx)
            n = jnp.sum(out.live.astype(jnp.int64))
            if out.capacity > fetch_cap:
                spec = K.compact_to(out, fetch_cap)
            else:
                spec = out
            return out, spec, n, ctx.flags, ctx.stats

        key = ("fused", tuple(self.fps), self.pool.signature(),
               tuple(self.marks), fetch_cap, dispatch.cache_token())
        return run, key, meta

    # --- dispatch ---------------------------------------------------------

    _ADAPTIVE_NODES = ("filter", "join", "aggregate", "distinct")

    def _c(self, plan: L.LogicalPlan):
        name = type(plan).__name__.lower()
        m = getattr(self, "_c_" + name, None)
        if m is None:
            raise FusionUnsupported(type(plan).__name__)
        fn, meta = m(plan)
        if meta.schema is not plan.schema and meta.schema != plan.schema:
            meta = NodeMeta(plan.schema, meta.dicts, meta.bounds, meta.capacity)

            def renamed(leaves, consts, ctx, _fn=fn, _s=plan.schema):
                b = _fn(leaves, consts, ctx)
                return DeviceBatch(_s, b.columns, b.live)
            fn = renamed
        if name in self._ADAPTIVE_NODES and meta.capacity > ADAPTIVE_CAPACITY:
            fn, meta = self._adaptive(fn, meta, name)
        return fn, meta

    def _adaptive(self, fn: NodeFn, meta: NodeMeta, kind: str):
        """Record this node's live count as a cardinality hint; when a prior
        run's hint shows a strong shrink, compact to the hinted capacity inside
        the program, flagging overflow (exact repair re-run with fresh hints)."""
        hkey = (kind, tuple(self.hfps))
        sid = self._new_stat(hkey)
        hint = self._hint(hkey)
        want = round_capacity(max(hint, 1)) if hint is not None else None
        if want is not None and want * ADAPTIVE_SHRINK <= meta.capacity:
            fid = self._new_flag(("compact", hkey))
            self._push(("acompact", want), hint_fp=None)

            def cfn(leaves, consts, ctx):
                out = fn(leaves, consts, ctx)
                n = jnp.sum(out.live.astype(jnp.int64))
                ctx.stats[sid] = n
                ctx.flags[fid] = n > want
                return K.compact_to(out, want)
            return cfn, NodeMeta(meta.schema, meta.dicts, meta.bounds, want)

        def sfn(leaves, consts, ctx):
            out = fn(leaves, consts, ctx)
            ctx.stats[sid] = jnp.sum(out.live.astype(jnp.int64))
            return out
        return sfn, meta

    def _compiler_for(self, meta: NodeMeta) -> ExprCompiler:
        return ExprCompiler(meta.dicts, self.pool, bounds=meta.bounds)

    def _compile_exprs(self, exprs, comp: ExprCompiler):
        """Resolve scalar subqueries (recursively executing them NOW, host
        side), then compile. Returns (resolved, compiled)."""
        resolved = [self.ex._resolve_subqueries(e) for e in exprs]
        out = [comp.compile(e) for e in resolved]
        return resolved, out

    # --- leaves -----------------------------------------------------------

    def _c_scan(self, plan: L.Scan):
        batch = self.ex._exec_scan(plan)
        idx = len(self.leaves)
        self.leaves.append(batch)
        meta = NodeMeta(plan.schema, [c.dictionary for c in batch.columns],
                        [c.bounds for c in batch.columns], batch.capacity)
        # NOTE: deliberately content-light — dictionary content feeds compiled
        # code through ConstPool args (pool.signature() keys sizes). Bounds
        # join the key only in CANONICAL form (quantized grid, see
        # exec/capacity.py): every bounds-derived static decision that shapes
        # the program (direct-join base/size, seg_dims offsets, pack radices)
        # is pushed into the key by its own node, so coarsening here is sound
        # and lets near scale factors share one fused program.
        from igloo_tpu.exec.capacity import canonical_direct_table
        self._push(("scan", plan.table, tuple(plan.projection or ()),
                    repr(plan.pushed_filters), plan.partition,
                    plan.schema, batch.capacity,
                    tuple(c.nulls is not None for c in batch.columns),
                    tuple(canonical_direct_table(b[0], b[1])
                          if b is not None else None
                          for b in meta.bounds),
                    # carrier form shapes the traced program (widen ops +
                    # carrier dtypes): wide vs int8-offset vs scaled columns
                    # must key distinct fused executables
                    tuple((str(c.values.dtype), c.carrier.key())
                          if c.carrier is not None else None
                          for c in batch.columns)))

        def fn(leaves, consts, ctx, _i=idx):
            return leaves[_i]
        return fn, meta

    # --- row-wise ---------------------------------------------------------

    def _c_filter(self, plan: L.Filter):
        cfn, meta = self._c(plan.input)
        comp = self._compiler_for(meta)
        res, [c] = self._compile_exprs([plan.predicate], comp)
        self.marks.extend(comp.marks)
        self._push(("filter", repr(res[0])))

        def fn(leaves, consts, ctx):
            b = cfn(leaves, consts, ctx)
            env = Env.from_batch(b, consts)
            v, nl = c.fn(env)
            keep = b.live & v
            if nl is not None:
                keep = keep & ~nl
            return DeviceBatch(b.schema, b.columns, keep)
        return fn, meta

    def _c_project(self, plan: L.Project):
        cfn, meta = self._c(plan.input)
        comp = self._compiler_for(meta)
        res, comps = self._compile_exprs(plan.exprs, comp)
        self.marks.extend(comp.marks)
        self._push(("project", tuple(repr(e) for e in res), plan.schema))
        out_schema = plan.schema

        def fn(leaves, consts, ctx):
            b = cfn(leaves, consts, ctx)
            env = Env.from_batch(b, consts)
            cols = []
            for cc, f in zip(comps, out_schema.fields):
                v, nl = cc.fn(env)
                want = f.dtype.device_dtype()
                if v.dtype != want:
                    v = v.astype(want)
                cols.append(DeviceColumn(f.dtype, v, nl, None))
            return DeviceBatch(out_schema, cols, b.live)
        out_meta = NodeMeta(out_schema, [cc.out_dict for cc in comps],
                            [cc.out_bounds for cc in comps], meta.capacity)
        return fn, out_meta

    # --- joins ------------------------------------------------------------

    def _c_join(self, plan: L.Join):
        lfn, lmeta = self._c(plan.left)
        rfn, rmeta = self._c(plan.right)
        jt = plan.join_type
        compL = self._compiler_for(lmeta)
        lres, lk = self._compile_exprs(plan.left_keys, compL)
        compR = self._compiler_for(rmeta)
        rres, rk = self._compile_exprs(plan.right_keys, compR)
        self.marks.extend(compL.marks)
        self.marks.extend(compR.marks)
        use_lk, use_rk = ([], []) if jt is JoinType.CROSS else (lk, rk)
        residual = None
        rres2 = []
        if plan.residual is not None:
            compB = ExprCompiler(lmeta.dicts + rmeta.dicts, self.pool,
                                 bounds=lmeta.bounds + rmeta.bounds)
            r = self.ex._resolve_subqueries(plan.residual)
            rres2 = [r]
            residual = compB.compile(r)
            self.marks.extend(compB.marks)

        if jt in (JoinType.SEMI, JoinType.ANTI):
            out_dicts = list(lmeta.dicts)
            out_bounds = list(lmeta.bounds)
        else:
            out_dicts = list(lmeta.dicts) + list(rmeta.dicts)
            out_bounds = list(lmeta.bounds) + list(rmeta.bounds)
        out_dicts = out_dicts[: len(plan.schema)]
        out_bounds = out_bounds[: len(plan.schema)]

        # jfp_core is capacity-free: hint keys derive from it so that child
        # hint adoption (which shrinks child capacities) never changes this
        # join's hint key. The full jfp (with caps) keys programs/negatives.
        jfp_core = ("join", tuple(repr(e) for e in lres),
                    tuple(repr(e) for e in rres),
                    tuple(repr(e) for e in rres2), jt)
        jfp = jfp_core + (lmeta.capacity, rmeta.capacity)

        pick = None
        if use_lk:
            banned = frozenset(
                s for s in ("left", "right")
                if self.ex._cache.get(("nodirect", jfp_core, s)))
            pick = choose_direct_build(use_lk, use_rk, lmeta.capacity,
                                       rmeta.capacity, jt, banned=banned)
        if pick is not None:
            return self._c_join_direct(plan, jfp, jfp_core, pick, lfn, lmeta,
                                       rfn, rmeta, use_lk, use_rk, residual,
                                       out_dicts, out_bounds)

        # speculative sorted-probe join: static match capacity, deferred
        # overflow flag. Past the budget a host sync would be required.
        spec_cap = round_capacity(max(lmeta.capacity, rmeta.capacity))
        if jt is JoinType.CROSS or spec_cap > self.ex._SPECULATIVE_JOIN_BUDGET:
            raise FusionUnsupported("join needs a host capacity sync")
        lhx = make_key_hash_idxs(use_lk, self.pool)
        rhx = make_key_hash_idxs(use_rk, self.pool)
        if jt in (JoinType.SEMI, JoinType.ANTI):
            out_cap = lmeta.capacity
        else:
            out_cap = spec_cap
            if jt in (JoinType.LEFT, JoinType.FULL):
                out_cap += lmeta.capacity
            if jt in (JoinType.RIGHT, JoinType.FULL):
                out_cap += rmeta.capacity
        # Pallas hash-probe dispatch (docs/kernels.md): a host decision, so
        # it joins the node fingerprint; the kernel's overflow flag rides
        # the fused flag channel and negative-caches this join onto the
        # sort path. The tag key uses the STAGED executor's jfp_core format
        # ("|"-joined exprs + join type) so a fused overflow's ban is
        # visible to the exact staged re-run and vice versa.
        pfp_core = ("|".join(repr(e) for e in lres + rres + rres2), jt)
        pplan = None
        if use_lk:
            pplan = dispatch.plan_probe(
                rmeta.capacity, lmeta.capacity,
                banned=bool(self.ex._cache.get(("nopallas_probe",
                                                pfp_core))))
        # Pallas match-materialization dispatch rides the same conventions:
        # plan in the fingerprint, window overflow on the flag channel,
        # staged-format ban key shared across tiers
        mplan = dispatch.plan_match(
            lmeta.capacity, spec_cap,
            banned=bool(self.ex._cache.get(("nopallas_match", pfp_core))))
        self._push(("join_sorted",) + jfp[1:] + (spec_cap, plan.schema,
                                                 pplan, mplan),
                   hint_fp=("join_sorted",) + jfp_core[1:] + (plan.schema,))
        fid = self._new_flag(("overflow", jfp))
        pfid = None
        if pplan is not None:
            pfid = self._new_flag(("pallas_probe", pfp_core))
            self.pallas_bans.append(("nopallas_probe", pfp_core))
        mfid = None
        if mplan is not None and mplan[1] == "kernel":
            mfid = self._new_flag(("pallas_match", pfp_core))
            self.pallas_bans.append(("nopallas_match", pfp_core))

        def fn(leaves, consts, ctx):
            lb = lfn(leaves, consts, ctx)
            rb = rfn(leaves, consts, ctx)
            p = probe_phase(lb, rb, use_lk, use_rk, lhx, rhx, consts,
                            probe_plan=pplan)
            ctx.flags[fid] = p.total > spec_cap
            if pfid is not None:
                ctx.flags[pfid] = p.ovf
            out = expand_phase(lb, rb, p, spec_cap, jt, residual,
                               plan.schema, consts, match_plan=mplan)
            if mplan is not None:
                out, movf = out
                if mfid is not None:
                    ctx.flags[mfid] = movf
            return out
        return fn, NodeMeta(plan.schema, out_dicts, out_bounds, out_cap)

    def _c_join_direct(self, plan, jfp, jfp_core, pick, lfn, lmeta, rfn,
                       rmeta, use_lk, use_rk, residual, out_dicts, out_bounds):
        jt = plan.join_type
        # canonical positional table (see choose_direct_build): blo/tsize are
        # family-quantized shape-class constants, safe in the fused cache key
        side, (blo, tsize), ki = pick
        swapped = side == "left"
        pks = use_rk if swapped else use_lk
        bks = use_lk if swapped else use_rk
        pkey, bkey = pks[ki], bks[ki]
        extra = [(pks[i], bks[i]) for i in range(len(pks)) if i != ki]
        probe_cap = rmeta.capacity if swapped else lmeta.capacity
        probe_is_left = not swapped
        fid = self._new_flag(("dup", (jfp_core, side)))

        # lazy inner join under a cardinality hint: run the probe at full
        # width, compact (probe cols + match index) down to the hinted
        # capacity, and only then gather build-side columns — narrow-width
        # materialization instead of N full-width gathers
        hkey = ("joinout", jfp_core, tuple(self.hfps))
        hint = self._hint(hkey) if jt is JoinType.INNER else None
        if hint is None and jt is JoinType.INNER:
            # fall back to the STAGED path's observed live count for this
            # same join (same jfp_core + capacities): plans that start life
            # on the staged executor (fusion rejected while capacities were
            # unhinted) seed the fused lazy join on their first fused
            # compile instead of needing one more adoption round
            hint = self.ex._staged_hint(("sjoin_live", jfp_core))
        want = round_capacity(max(hint, 1)) if hint is not None else None
        if want is not None and want * ADAPTIVE_SHRINK <= probe_cap:
            sid = self._new_stat(hkey)
            ofid = self._new_flag(("compact", hkey))
            self._push(("join_lazy",) + jfp[1:] +
                       (side, blo, tsize, ki, want, plan.schema),
                       hint_fp=("join_direct",) + jfp_core[1:] +
                       (plan.schema,))

            def fn(leaves, consts, ctx):
                lb = lfn(leaves, consts, ctx)
                rb = rfn(leaves, consts, ctx)
                pb, bb = (rb, lb) if swapped else (lb, rb)
                ok, bidx, dup = direct_probe(pb, bb, pkey, bkey, blo,
                                             tsize, swapped, residual,
                                             consts, extra)
                ctx.flags[fid] = dup
                n = jnp.sum(ok.astype(jnp.int64))
                ctx.stats[sid] = n
                ctx.flags[ofid] = n > want
                perm = K.compact_perm(ok)[:want]
                live = jnp.take(ok, perm)
                p_cols = [replace(c, values=jnp.take(c.values, perm),
                                  nulls=jnp.take(c.nulls, perm)
                                  if c.nulls is not None else None,
                                  dictionary=None, bounds=None)
                          for c in pb.columns]
                nbidx = jnp.clip(jnp.take(bidx, perm), 0, bb.capacity - 1)
                b_cols = K.gather_batch(bb, nbidx)
                l_cols, r_cols = (b_cols, p_cols) if swapped \
                    else (p_cols, b_cols)
                return DeviceBatch(plan.schema, l_cols + r_cols, live)
            return fn, NodeMeta(plan.schema, out_dicts, out_bounds, want)

        if jt is JoinType.INNER:
            sid = self._new_stat(hkey)
        else:
            sid = None
        if jt in (JoinType.SEMI, JoinType.ANTI):
            out_cap = lmeta.capacity
        else:
            build_cap = lmeta.capacity if swapped else rmeta.capacity
            build_preserved = (
                jt is JoinType.FULL
                or (jt is JoinType.LEFT and not probe_is_left)
                or (jt is JoinType.RIGHT and probe_is_left))
            out_cap = probe_cap + (build_cap if build_preserved else 0)
        self._push(("join_direct",) + jfp[1:] +
                   (side, blo, tsize, ki, plan.schema),
                   hint_fp=("join_direct",) + jfp_core[1:] + (plan.schema,))

        def fn(leaves, consts, ctx):
            lb = lfn(leaves, consts, ctx)
            rb = rfn(leaves, consts, ctx)
            pb, bb = (rb, lb) if swapped else (lb, rb)
            out, dup = direct_join_phase(pb, bb, pkey, bkey, blo, tsize,
                                         swapped, jt, residual,
                                         plan.schema, consts,
                                         extra_keys=extra)
            ctx.flags[fid] = dup
            if sid is not None:
                ctx.stats[sid] = jnp.sum(out.live.astype(jnp.int64))
            return out
        return fn, NodeMeta(plan.schema, out_dicts, out_bounds, out_cap)

    # --- aggregates -------------------------------------------------------

    def _c_aggregate(self, plan: L.Aggregate):
        if any(a.distinct for a in plan.aggs):
            raise FusionUnsupported("distinct aggregate")
        cfn, meta = self._c(plan.input)
        comp = self._compiler_for(meta)
        gres, groups = self._compile_exprs(plan.group_exprs, comp)
        specs = []
        ares = []
        for a in plan.aggs:
            if a.arg is not None:
                [r], [arg] = self._compile_exprs([a.arg], comp)
                ares.append(r)
            else:
                arg = None
            out_dict = arg.out_dict if (arg is not None and a.dtype.is_string) \
                else None
            specs.append(AggSpec(a.func, arg, a.dtype, out_dict,
                                 order_arg=minmax_order_arg(a.func, arg, comp)))
        self.marks.extend(comp.marks)
        from igloo_tpu.plan.expr import AggFunc as _AF
        n_scatters = sum(
            2 if a.func is _AF.AVG else 1 for a in plan.aggs)
        seg_dims = seg_dims_for(groups, n_aggs=n_scatters,
                                input_capacity=meta.capacity)
        # packed-key single-sort path when the scatter path doesn't apply;
        # a host decision (bounds / dictionary sizes) -> part of the fused key
        pack_spec = None
        if seg_dims is None and groups:
            pack_spec = K.plan_group_packing(groups, self.pool)
            if pack_spec is not None:
                tracing.counter("pack.agg")
        # Pallas one-pass hash aggregation (docs/kernels.md): full-cover
        # pack required; the table-overflow flag rides the fused flag
        # channel and negative-caches this aggregate onto the sort path.
        # The tag key mirrors the staged executor's afp_core format so bans
        # cross the fused/staged boundary.
        afp_core = ("agg", "|".join(repr(e) for e in gres + ares),
                    tuple((a.func, a.dtype) for a in plan.aggs))
        pallas_agg = None
        if seg_dims is None and pack_spec is not None:
            pallas_agg = dispatch.plan_segagg(
                pack_spec, len(groups), meta.capacity,
                banned=bool(self.ex._cache.get(("nopallas_agg", afp_core))))
        afid = None
        if pallas_agg is not None:
            afid = self._new_flag(("pallas_agg", afp_core))
            self.pallas_bans.append(("nopallas_agg", afp_core))
        self._push(("agg", tuple(repr(e) for e in gres + ares),
                    tuple((a.func, a.dtype) for a in plan.aggs),
                    plan.schema, seg_dims, pack_spec, pallas_agg))
        out_schema = plan.schema

        def fn(leaves, consts, ctx):
            b = cfn(leaves, consts, ctx)
            if pallas_agg is None:
                return aggregate_batch(b, groups, specs, out_schema, consts,
                                       seg_dims=seg_dims,
                                       pack_spec=pack_spec)
            out, ovf = aggregate_batch(b, groups, specs, out_schema, consts,
                                       seg_dims=seg_dims,
                                       pack_spec=pack_spec,
                                       pallas_agg=pallas_agg)
            ctx.flags[afid] = ovf
            return out
        if not groups:
            cap = MIN_CAPACITY
        elif seg_dims is not None:
            prod = 1
            for d, _off in seg_dims:
                prod *= d
            cap = round_capacity(prod + 1)
        elif pallas_agg is not None:
            cap = dispatch.segagg_table_rows(pallas_agg)
        else:
            cap = meta.capacity
        out_meta = NodeMeta(out_schema,
                            [g.out_dict for g in groups] +
                            [s.out_dict for s in specs],
                            [g.out_bounds for g in groups] +
                            [None] * len(specs), cap)
        return fn, out_meta

    def _c_distinct(self, plan: L.Distinct):
        cfn, meta = self._c(plan.input)
        self._push(("distinct",))

        def fn(leaves, consts, ctx):
            return distinct_batch(cfn(leaves, consts, ctx))
        return fn, meta

    def _c_window(self, plan: L.Window):
        from igloo_tpu.exec.window import compile_window, window_batch
        cfn, meta = self._c(plan.input)
        comp = self._compiler_for(meta)
        wfp, pk, okeys, specs, wdicts, wbounds = compile_window(
            plan, comp, self.ex._resolve_subqueries)
        self.marks.extend(comp.marks)
        self._push(("window", wfp, plan.schema))
        asc, nf = list(plan.ascending), list(plan.nulls_first)
        out_schema = plan.schema

        def fn(leaves, consts, ctx):
            return window_batch(cfn(leaves, consts, ctx), pk, okeys, asc, nf,
                                specs, out_schema, consts)
        return fn, NodeMeta(out_schema, list(meta.dicts) + wdicts,
                            list(meta.bounds) + wbounds, meta.capacity)

    # --- ordering ---------------------------------------------------------

    def _c_sort(self, plan: L.Sort):
        cfn, meta = self._c(plan.input)
        comp = self._compiler_for(meta)
        res, keys = self._compile_exprs(plan.keys, comp)
        keys = [rank_lane(k, comp) if k.dtype.is_string else k for k in keys]
        self.marks.extend(comp.marks)
        # pack the longest integer-family key prefix into one sort lane
        pack = K.plan_prefix_packing(keys, plan.ascending, plan.nulls_first,
                                     self.pool)
        if pack is not None:
            tracing.counter("pack.sort")
        self._push(("sort", tuple(repr(e) for e in res),
                    tuple(plan.ascending), tuple(plan.nulls_first), pack))
        asc, nf = list(plan.ascending), list(plan.nulls_first)

        def fn(leaves, consts, ctx):
            return sort_batch(cfn(leaves, consts, ctx), keys, asc, nf, consts,
                              pack=pack)
        return fn, meta

    def _c_limit(self, plan: L.Limit):
        if isinstance(plan.input, L.Sort) and plan.limit is not None:
            return self._c_limit_sort(plan, plan.input)
        cfn, meta = self._c(plan.input)
        self._push(("limit", plan.limit, plan.offset))

        def fn(leaves, consts, ctx):
            return limit_batch(cfn(leaves, consts, ctx), plan.limit,
                               plan.offset)
        return fn, meta

    def _c_limit_sort(self, plan: L.Limit, sp: L.Sort):
        """ORDER BY + LIMIT fusion (docs/kernels.md): dispatch.plan_topk
        replaces the full argsort with a partial top-k when LIMIT + OFFSET
        is small against the batch and the prefix packing covers every key.
        The decline path pushes fingerprints BYTE-IDENTICAL to the unfused
        sort + limit pair, so program keys and hint keys never move when the
        plan says no."""
        cfn, meta = self._c(sp.input)
        comp = self._compiler_for(meta)
        res, keys = self._compile_exprs(sp.keys, comp)
        keys = [rank_lane(k, comp) if k.dtype.is_string else k for k in keys]
        self.marks.extend(comp.marks)
        pack = K.plan_prefix_packing(keys, sp.ascending, sp.nulls_first,
                                     self.pool)
        if pack is not None:
            tracing.counter("pack.sort")
        asc, nf = list(sp.ascending), list(sp.nulls_first)
        k_total = plan.limit + plan.offset
        # ban key mirrors the staged executor's topk core (cross-tier rule)
        tfp_core = ("|".join(repr(e) for e in res), tuple(sp.ascending),
                    tuple(sp.nulls_first))
        tplan = dispatch.plan_topk(
            meta.capacity, k_total,
            pack is not None and pack[1] == len(keys),
            banned=bool(self.ex._cache.get(("nopallas_topk", tfp_core))))
        if tplan is None:
            self._push(("sort", tuple(repr(e) for e in res),
                        tuple(sp.ascending), tuple(sp.nulls_first), pack))
            self._push(("limit", plan.limit, plan.offset))

            def fn(leaves, consts, ctx):
                b = sort_batch(cfn(leaves, consts, ctx), keys, asc, nf,
                               consts, pack=pack)
                return limit_batch(b, plan.limit, plan.offset)
            return fn, meta
        out_cap = round_capacity(k_total)
        self._push(("topk", tuple(repr(e) for e in res),
                    tuple(sp.ascending), tuple(sp.nulls_first), pack, tplan,
                    plan.limit, plan.offset, out_cap))
        if tplan[1] == "pallas":
            self.pallas_bans.append(("nopallas_topk", tfp_core))

        def fn(leaves, consts, ctx):
            return topk_batch(cfn(leaves, consts, ctx), keys, consts, pack,
                              tplan, plan.limit, plan.offset, out_cap)
        return fn, NodeMeta(meta.schema, meta.dicts, meta.bounds, out_cap)
