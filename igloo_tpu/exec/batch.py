"""DeviceBatch: the TPU-resident columnar batch.

This is the engine's universal data representation on device, playing the role Arrow
`RecordBatch` plays in the reference (reference crates/engine/src/physical_plan.rs:10-17
streams RecordBatch between operators). Design differences are deliberate TPU choices:

- **Static shapes.** Every column is padded to a power-of-two `capacity`; a `live`
  boolean lane marks real rows. Filters do not compact (the reference's FilterExec
  eagerly materializes filtered batches, crates/engine/src/operators/filter.rs:39-68);
  we AND into the selection mask so downstream ops fuse into one XLA computation with
  no dynamic shapes. Compaction happens only where required (joins, shuffles, output),
  via a stable sort on the mask — still static-shaped.

- **Strings never touch HBM.** String columns are dictionary-encoded at scan time;
  the device sees int32 ids. Small dictionaries (<= HIGH_CARD_THRESHOLD uniques) are
  lexicographically sorted, so ORDER BY / MIN / MAX / range predicates work directly
  on ids; high-cardinality dictionaries stay UNSORTED (`DictInfo.is_sorted=False` —
  never compare such ids for order; order-sensitive operators must go through
  `DictInfo.ranks()` via `expr_compile.rank_lane`). Equality/LIKE/functions evaluate
  host-side over the dictionary and become id-lookups on device; cross-table string
  comparisons (join keys) go through per-entry 64-bit hashes (see `DictInfo.hashes`).

- **Nulls are a separate bool lane** (True = null), mirroring Arrow validity bitmaps
  but kept as full bool lanes for VPU-friendly masking.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from igloo_tpu.types import (
    BOOL, DATE32, FLOAT32, FLOAT64, INT32, INT64, STRING, TIMESTAMP,
    DataType, Field, Schema, TypeId,
)

from igloo_tpu.exec.capacity import MIN_CAPACITY, canonical_capacity


def round_capacity(n: int) -> int:
    """Pad row counts to the canonical shape family so XLA recompiles rarely
    (shape bucketing; cf. SURVEY.md §7 hard part 5). Delegates to the
    engine-wide capacity policy (exec/capacity.py): exact pow2 for small
    batches, a coarser geometric family with hysteresis above 2^16 so
    neighboring scale factors lower to the same compiled programs."""
    return canonical_capacity(n)


# 64-bit mixing constants (splitmix64 finalizer) used for dictionary/string hashing.
_SM64_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SM64_C2 = np.uint64(0x94D049BB133111EB)


def hash64_bytes(values: Sequence[object], seed: int = 0) -> np.ndarray:
    """Host-side 64-bit FNV-1a + splitmix64-finalized hash of string values
    (dictionary entries). Prefers the native C path (igloo_tpu.native,
    hash64.c — per-entry byte loop in C); falls back to a numpy
    implementation vectorized over entries (the python-level loop is over the
    max string LENGTH, not entries×bytes). Both produce identical results."""
    n = len(values)
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    bufs = [(v.encode("utf-8") if isinstance(v, str) else bytes(v)) if v is not None else None
            for v in values]
    from igloo_tpu import native
    fast = native.hash64_batch(bufs, seed)
    if fast is not None:
        return fast
    # numpy fallback: bound the (entries x max_len) working matrix — a
    # 6M-entry comment column would otherwise materialize gigabytes at once.
    # Chunk over the ALREADY-encoded bufs (not `values`) so nothing encodes twice.
    _CHUNK = 1 << 18
    if n > _CHUNK:
        return np.concatenate([_hash64_np(bufs[i: i + _CHUNK], seed)
                               for i in range(0, n, _CHUNK)])
    return _hash64_np(bufs, seed)


def _hash64_np(bufs: list, seed: int) -> np.ndarray:
    n = len(bufs)
    lengths = np.asarray([len(b) if b is not None else 0 for b in bufs], dtype=np.int64)
    none_mask = np.asarray([b is None for b in bufs], dtype=bool)
    max_len = int(lengths.max()) if n else 0
    mat = np.zeros((n, max_len), dtype=np.uint64)
    if max_len:
        flat = np.frombuffer(b"".join(b for b in bufs if b is not None), dtype=np.uint8)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths[:-1], out=starts[1:])
        rows, cols = np.nonzero(np.arange(max_len)[None, :] < lengths[:, None])
        mat[rows, cols] = flat[starts[rows] + cols]
    with np.errstate(over="ignore"):
        h = np.full(n, np.uint64(seed) + np.uint64(0x9E3779B97F4A7C15), dtype=np.uint64)
        prime = np.uint64(0x100000001B3)
        for j in range(max_len):
            active = j < lengths
            nh = (h ^ mat[:, j]) * prime
            h = np.where(active, nh, h)
        # splitmix64 finalize
        h ^= h >> np.uint64(30)
        h *= _SM64_C1
        h ^= h >> np.uint64(27)
        h *= _SM64_C2
        h ^= h >> np.uint64(31)
        h[none_mask] = np.uint64(seed) ^ np.uint64(0x9E3779B97F4A7C15)
    return h


@dataclass(frozen=True)
class DictInfo:
    """Host-side dictionary for a STRING column.

    values:  np object array of python strings. `is_sorted` marks the normal
             (lexicographically sorted) encoding, where ids double as ranks and
             order comparisons work directly on id lanes. High-cardinality
             columns (> HIGH_CARD_THRESHOLD uniques, e.g. TPC-H comment
             columns) skip the sort: ids are first-occurrence order
             (is_sorted=False) — equality/grouping/joins/output still work on
             ids, and order-sensitive operators gather through the lazily
             computed `ranks()` LUT instead.
    hashes:  uint64[len] per-entry hash (seed 0)   — device-gatherable for join keys.
    hashes2: uint64[len] independent hash (seed 1) — collision guard (128-bit effective).
    """
    values: np.ndarray
    hashes: np.ndarray
    hashes2: np.ndarray
    is_sorted: bool = True

    @staticmethod
    def from_values(values: Sequence[object]) -> "DictInfo":
        arr = np.asarray(list(values), dtype=object)
        return DictInfo(arr, hash64_bytes(arr, seed=0), hash64_bytes(arr, seed=1))

    def ranks(self) -> np.ndarray:
        """int32[len]: lexicographic rank per id. Identity for sorted
        dictionaries; computed once (and cached) for unsorted ones — only
        queries that actually ORDER/MIN/MAX/compare the column pay the sort."""
        r = getattr(self, "_ranks", None)
        if r is None:
            if self.is_sorted:
                r = np.arange(len(self.values), dtype=np.int32)
            else:
                order = np.argsort(self.values.astype(str), kind="stable")
                r = np.empty(len(self.values), dtype=np.int32)
                r[order] = np.arange(len(self.values), dtype=np.int32)
            object.__setattr__(self, "_ranks", r)
        return r

    def __len__(self) -> int:
        return len(self.values)

    # DictInfo rides in jit static aux data (pytree aux of DeviceColumn): hash/eq
    # by content fingerprint so identical dictionaries share compile-cache entries.
    def _fingerprint(self) -> int:
        fp = getattr(self, "_fp", None)
        if fp is None:
            fp = hash((len(self.values), self.hashes.tobytes()))
            object.__setattr__(self, "_fp", fp)
        return fp

    def __hash__(self) -> int:
        return self._fingerprint()

    def __eq__(self, other) -> bool:
        # exact content equality (only reached after a fingerprint bucket match,
        # so the array compare is rare): a fingerprint collision must NOT alias
        # two dictionaries in the jit compile cache
        return isinstance(other, DictInfo) and \
            self._fingerprint() == other._fingerprint() and \
            np.array_equal(self.hashes, other.hashes) and \
            np.array_equal(self.hashes2, other.hashes2)


@dataclass
class DeviceColumn:
    """One column: a padded device lane + optional null lane + host dictionary.

    When `carrier` is set, `values` holds the NARROW transfer carrier
    (exec/codec.py) rather than the engine lane dtype — the compressed form is
    the resident form. Operators that need actual values widen at the point of
    use via `wide_values` (in-jit: XLA fuses the cast/divide into the
    consumer, so the wide lane exists only transiently inside the program);
    operators that only move/mask/gather rows (filters via masks, compaction,
    resize, exchange staging) keep the carrier untouched. `carrier_arg` is the
    0-d runtime payload (real offset / scale divisor) matching the CANONICAL
    spec in `carrier` — see codec.upload_columns for why it is runtime data."""
    dtype: DataType
    values: jax.Array              # [capacity], carrier dtype when `carrier` is set
    nulls: Optional[jax.Array]     # [capacity] bool, True = null; None = no nulls
    dictionary: Optional[DictInfo] = None  # STRING columns only
    # host-side (lo, hi) value bounds for integer-family columns, computed at
    # scan time and propagated (never widened) through filters/joins/sorts.
    # Powers the direct "array join" fast path (exec/join.py direct_join):
    # dense PK-FK joins become scatter+gather instead of sorts. None = unknown.
    bounds: Optional[tuple] = None
    carrier: Optional["WidenSpec"] = None   # codec.WidenSpec; None = wide lane
    carrier_arg: Optional[jax.Array] = None  # 0-d offset/scale payload

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    def with_nulls(self, nulls: Optional[jax.Array]) -> "DeviceColumn":
        return replace(self, nulls=nulls)


def wide_values(col: DeviceColumn) -> jax.Array:
    """The column's engine-lane values, widening the resident carrier in-jit
    if there is one. THE single decode point for device operators: call this
    (inside a jitted program — Env.from_batch does) instead of reading
    `.values` wherever actual values are consumed. Traced or eager."""
    spec = col.carrier
    if spec is None:
        return col.values
    if spec.scale != 1.0:
        return spec.widen(col.values, scale_arg=col.carrier_arg)
    if spec.offset:
        return spec.widen(col.values, offset_arg=col.carrier_arg)
    return spec.widen(col.values)


def materialize(col: DeviceColumn) -> DeviceColumn:
    """Eagerly widen a column to its engine lane (carrier dropped). Boundary
    escape hatch for code paths that cannot carry the carrier metadata —
    today: sharding a batch across the device mesh (parallel/mesh.py), where a
    0-d carrier_arg cannot take a row-sharded PartitionSpec."""
    if col.carrier is None:
        return col
    return replace(col, values=wide_values(col), carrier=None, carrier_arg=None)


def materialize_batch(batch: "DeviceBatch") -> "DeviceBatch":
    if all(c.carrier is None for c in batch.columns):
        return batch
    return replace(batch, columns=[materialize(c) for c in batch.columns])


@dataclass
class DeviceBatch:
    """A batch of rows resident in device memory (HBM)."""
    schema: Schema
    columns: list[DeviceColumn]
    live: jax.Array                # [capacity] bool selection mask

    @property
    def capacity(self) -> int:
        return int(self.live.shape[0])

    def column(self, name: str) -> DeviceColumn:
        return self.columns[self.schema.index_of(name)]

    def num_live(self) -> int:
        """Host sync: count of selected rows."""
        return int(jnp.sum(self.live))

    def nbytes(self) -> int:
        total = self.live.nbytes
        for c in self.columns:
            total += c.values.nbytes
            if c.nulls is not None:
                total += c.nulls.nbytes
        return total

    # ---- construction -------------------------------------------------------

    @staticmethod
    def empty(schema: Schema, capacity: int = MIN_CAPACITY) -> "DeviceBatch":
        cols = []
        for f in schema:
            vals = jnp.zeros((capacity,), dtype=f.dtype.device_dtype())
            cols.append(DeviceColumn(f.dtype, vals, None,
                                     DictInfo.from_values([]) if f.dtype.is_string else None))
        return DeviceBatch(schema, cols, jnp.zeros((capacity,), dtype=bool))


# --- pytree registration: DeviceBatch/DeviceColumn flow straight through jax.jit
# (arrays are leaves; dtype/schema/dictionaries are static aux so the compile
# cache keys on them — shape bucketing + dictionary fingerprints keep it small)

jax.tree_util.register_pytree_node(
    DeviceColumn,
    # carrier_arg is a leaf (0-d runtime payload; a None simply vanishes from
    # the leaf list), the canonical WidenSpec is static aux (frozen/hashable)
    # so the compile cache keys on carrier form — wide vs int8-offset vs
    # scaled-decimal columns compile distinct programs, as they must.
    lambda c: ((c.values, c.nulls, c.carrier_arg),
               (c.dtype, c.dictionary, c.bounds, c.carrier)),
    lambda aux, ch: DeviceColumn(aux[0], ch[0], ch[1], aux[1],
                                 aux[2] if len(aux) > 2 else None,
                                 aux[3] if len(aux) > 3 else None,
                                 ch[2] if len(ch) > 2 else None),
)

jax.tree_util.register_pytree_node(
    DeviceBatch,
    lambda b: ((b.columns, b.live), b.schema),
    lambda aux, ch: DeviceBatch(aux, ch[0], ch[1]),
)


# ---------------------------------------------------------------------------
# Arrow <-> device conversion (the host/HBM boundary; replaces the reference's
# in-process RecordBatch streaming, crates/engine/src/operators/parquet_scan.rs:40-85)
# ---------------------------------------------------------------------------

_ARROW_TO_TYPE = {
    pa.bool_(): BOOL,
    pa.int8(): INT32, pa.int16(): INT32, pa.int32(): INT32,
    pa.uint8(): INT32, pa.uint16(): INT32,
    pa.int64(): INT64, pa.uint32(): INT64, pa.uint64(): INT64,
    pa.float32(): FLOAT32,
    pa.float64(): FLOAT64,
    pa.date32(): DATE32,
    pa.string(): STRING, pa.large_string(): STRING, pa.utf8(): STRING,
}


def arrow_type_to_dtype(t: pa.DataType) -> DataType:
    if t in _ARROW_TO_TYPE:
        return _ARROW_TO_TYPE[t]
    if pa.types.is_timestamp(t):
        return TIMESTAMP
    if pa.types.is_decimal(t):
        return FLOAT64  # TPC-H decimals computed in float64 on device
    if pa.types.is_dictionary(t):
        return arrow_type_to_dtype(t.value_type)
    if pa.types.is_date64(t):
        return DATE32
    raise TypeError(f"unsupported arrow type {t}")


def schema_from_arrow(s: pa.Schema) -> Schema:
    return Schema([Field(f.name, arrow_type_to_dtype(f.type), f.nullable) for f in s])


def dtype_to_arrow(d: DataType) -> pa.DataType:
    return {
        TypeId.BOOL: pa.bool_(), TypeId.INT32: pa.int32(), TypeId.INT64: pa.int64(),
        TypeId.FLOAT32: pa.float32(), TypeId.FLOAT64: pa.float64(),
        TypeId.STRING: pa.string(), TypeId.DATE32: pa.date32(),
        TypeId.TIMESTAMP: pa.timestamp("us"), TypeId.NULL: pa.int32(),
    }[d.id]


# above this many distinct values a column keeps its dictionary UNSORTED
# (first-occurrence order from Arrow's C++ hash encoder): sorting millions of
# near-unique strings host-side (e.g. TPC-H l_comment at SF1, ~6M uniques)
# would dwarf query time, and only order-sensitive operators need ranks
HIGH_CARD_THRESHOLD = 1 << 16


def _encode_string_column(arr: pa.ChunkedArray, dict_info: Optional[DictInfo]):
    """Dictionary-encode via Arrow's C++ hash encoder. Small dictionaries are
    re-sorted so ids double as lexicographic ranks; high-cardinality ones stay
    unsorted (DictInfo.is_sorted=False, see HIGH_CARD_THRESHOLD). If
    `dict_info` is given, ids are assigned against it (table-unified
    dictionary); values absent from it are an error (scan builds the union up
    front)."""
    combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    null_mask = None
    if combined.null_count:
        null_mask = np.asarray(combined.is_null())

    if dict_info is None:
        if not pa.types.is_dictionary(combined.type):
            combined = combined.dictionary_encode()
        import pyarrow.compute as pc
        indices = pc.fill_null(combined.indices, 0)
        ids = np.asarray(indices).astype(np.int32)
        dvals = combined.dictionary.to_numpy(zero_copy_only=False)
        dvals = np.asarray(dvals, dtype=object)
        if len(dvals) <= HIGH_CARD_THRESHOLD:
            order = np.argsort(dvals.astype(str), kind="stable")
            lut = np.empty(len(dvals), dtype=np.int32)
            lut[order] = np.arange(len(dvals), dtype=np.int32)
            if len(dvals):
                ids = lut[ids]
            dict_info = DictInfo.from_values(dvals[order])
        else:
            dict_info = DictInfo(dvals, hash64_bytes(dvals, seed=0),
                                 hash64_bytes(dvals, seed=1), is_sorted=False)
        return ids, null_mask, dict_info

    # pre-unified dictionary: assign ids against it
    if pa.types.is_dictionary(combined.type):
        combined = combined.cast(pa.string()) \
            if not pa.types.is_large_string(combined.type.value_type) \
            else combined.cast(pa.large_string())
    np_vals = combined.to_numpy(zero_copy_only=False)
    safe = np.asarray(["" if v is None else v for v in np_vals], dtype=object)
    if len(dict_info) == 0:
        if len(np_vals) and not all(v is None for v in np_vals):
            raise ValueError("string values present but unified dictionary is empty")
        return np.zeros(len(np_vals), dtype=np.int32), null_mask, dict_info
    if dict_info.is_sorted:
        dstr = dict_info.values.astype(str)
        ids = np.searchsorted(dstr, safe.astype(str)).astype(np.int32)
        ids = np.clip(ids, 0, len(dict_info) - 1)
        ok = dstr[ids] == safe.astype(str)
    else:
        # vectorized lookup against an UNSORTED dictionary: binary-search the
        # rank-ordered values (ranks() caches the sort) instead of a per-row
        # python dict probe — O(rows log uniques) in numpy C, not an
        # interpreter loop over millions of rows
        ranks = dict_info.ranks()
        order = np.empty(len(ranks), dtype=np.int64)
        order[ranks] = np.arange(len(ranks))
        dstr = dict_info.values.astype(str)
        sorted_vals = dstr[order]
        svals = safe.astype(str)
        pos = np.clip(np.searchsorted(sorted_vals, svals), 0,
                      len(sorted_vals) - 1)
        ok = sorted_vals[pos] == svals
        ids = np.where(ok, order[pos], 0).astype(np.int32)
    if null_mask is not None:
        ok = ok | null_mask
    if not ok.all():
        missing = sorted({str(v) for v, o in zip(safe, ok) if not o})[:5]
        raise ValueError(f"string values not in unified dictionary: {missing}")
    return ids, null_mask, dict_info


def _arrow_column_to_numpy(arr: pa.ChunkedArray, dtype: DataType):
    combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    if pa.types.is_decimal(combined.type):
        combined = combined.cast(pa.float64())
    if pa.types.is_timestamp(combined.type):
        combined = combined.cast(pa.timestamp("us"))
    null_mask = None
    if combined.null_count:
        null_mask = np.asarray(pa.compute.is_null(combined).to_numpy(zero_copy_only=False))
        if dtype.id == TypeId.BOOL:
            fill = False
        elif dtype.is_float:
            fill = 0.0
        else:
            # fill integer-family nulls with the non-null MIN, not 0: null
            # lanes are masked everywhere (like dead lanes), but a 0 fill in
            # e.g. a timestamp column would drag the value range to [0, hi]
            # and defeat the offset-shrink transfer codec (exec/codec.py)
            mn = pa.compute.min(combined).as_py()
            fill = 0 if mn is None else mn
        combined = pa.compute.fill_null(combined, fill)
    np_vals = combined.to_numpy(zero_copy_only=False)
    np_vals = np.asarray(np_vals).astype(dtype.device_dtype(), copy=False)
    return np_vals, null_mask


_BOUNDED_IDS = (TypeId.INT32, TypeId.INT64, TypeId.DATE32, TypeId.TIMESTAMP)


def _int_bounds(np_vals: np.ndarray, null_mask, dtype: DataType):
    """(min, max) over non-null values of an integer-family column; None when
    the column is empty, all-null, or not integer-typed. Host-side stats that
    ride DeviceColumn.bounds into the planner's join-strategy choice."""
    if dtype.id not in _BOUNDED_IDS or len(np_vals) == 0:
        return None
    valid = np_vals if null_mask is None else np_vals[~null_mask]
    if len(valid) == 0:
        return None
    return (int(valid.min()), int(valid.max()))




def host_decode_column(arr: pa.ChunkedArray, f: Field,
                       dictionaries: Optional[dict[str, DictInfo]] = None):
    """Arrow column -> host-side (np_vals, null_mask, dinfo, bounds) in the
    engine lane dtype (string columns become int32 dictionary ids)."""
    if f.dtype.is_string:
        pre = dictionaries.get(f.name) if dictionaries else None
        ids, null_mask, dinfo = _encode_string_column(arr, pre)
        return ids, null_mask, dinfo, None
    np_vals, null_mask = _arrow_column_to_numpy(arr, f.dtype)
    bounds = _int_bounds(np_vals, null_mask, f.dtype)
    return np_vals, null_mask, None, bounds


def device_columns(decoded: list, fields: list, cap: int,
                   device=None) -> list[DeviceColumn]:
    """Upload host-decoded columns as DeviceColumns, narrowed losslessly
    (exec/codec.py) — and kept narrow: the carrier array IS the resident
    `values` lane, with the WidenSpec riding along so operators widen at the
    point of use. Dead lanes (index >= n) carry the codec pad value — kernels
    must never read them unmasked (they were arbitrary zeros before too)."""
    from igloo_tpu.exec.codec import upload_columns
    plans = []
    for (np_vals, null_mask, _dinfo, _bounds) in decoded:
        lane = np_vals.dtype
        plans.append((np_vals, lane, cap))
        if null_mask is not None:
            plans.append((null_mask, None, cap))
    dev = upload_columns(plans, device=device)
    cols: list[DeviceColumn] = []
    i = 0
    for f, (np_vals, null_mask, dinfo, bounds) in zip(fields, decoded):
        dev_vals, spec, carg = dev[i]
        i += 1
        nulls = None
        if null_mask is not None:
            nulls = dev[i][0]
            i += 1
        cols.append(DeviceColumn(f.dtype, dev_vals, nulls, dinfo, bounds,
                                 spec, carg))
    return cols


def from_arrow(
    table: pa.Table,
    schema: Optional[Schema] = None,
    capacity: Optional[int] = None,
    dictionaries: Optional[dict[str, DictInfo]] = None,
    device=None,
    null_fields: Optional[set] = None,
) -> DeviceBatch:
    """pyarrow Table -> DeviceBatch (host decode -> narrowed device_put into
    HBM -> on-device widen, one dispatch for the whole batch). Columns named
    in `null_fields` always get a null lane (all-False when the data has no
    nulls): the GRACE partition pipeline forces one shape per leaf across all
    partitions so null-free buckets key the same compiled programs as bucket
    siblings that do carry nulls."""
    from igloo_tpu.exec.codec import live_lane
    if schema is None:
        schema = schema_from_arrow(table.schema)
    n = table.num_rows
    cap = capacity or round_capacity(n)
    decoded = [host_decode_column(table.column(f.name), f, dictionaries)
               for f in schema]
    if null_fields:
        decoded = [(v, np.zeros(n, dtype=bool)
                    if nm is None and f.name in null_fields else nm, di, b)
                   for f, (v, nm, di, b) in zip(schema, decoded)]
    cols = device_columns(decoded, list(schema), cap, device=device)
    return DeviceBatch(schema, cols, live_lane(cap, n, device=device))


def to_arrow(batch: DeviceBatch) -> pa.Table:
    """DeviceBatch -> pyarrow Table on host, dropping dead lanes, decoding dictionaries,
    re-applying null masks. Order of surviving rows is preserved.

    All device buffers are fetched in ONE `jax.device_get` call: it issues every
    per-array copy_to_host_async before blocking, so the host pays one device
    roundtrip instead of one per column — on a tunneled TPU a roundtrip is
    ~100ms, so per-column fetches dominated warm query time (round-2 weak #1)."""
    host_live, host_vals, host_nulls, host_cargs = jax.device_get(
        (batch.live, [c.values for c in batch.columns],
         [c.nulls for c in batch.columns],
         [c.carrier_arg for c in batch.columns]))
    from igloo_tpu.utils.stats import record_fetch
    record_fetch((host_live, host_vals, host_nulls))
    return arrow_from_host(batch, host_live, host_vals, host_nulls, host_cargs)


def arrow_from_host(batch: DeviceBatch, host_live, host_vals, host_nulls,
                    host_cargs=None) -> pa.Table:
    """Build the pyarrow Table from already-fetched host copies of a batch's
    device buffers (see `to_arrow`; the executor also calls this directly after
    a speculative compact-and-fetch). Carrier-resident columns are fetched
    NARROW (the whole point) and widened here on the host, after the dead-lane
    drop and before dictionary/date/timestamp decode — bit-identical to the
    device widen (codec.host_widen)."""
    if host_cargs is None:
        if any(c.carrier is not None for c in batch.columns):
            host_cargs = jax.device_get(
                [c.carrier_arg for c in batch.columns])
        else:
            host_cargs = [None] * len(batch.columns)
    from igloo_tpu.exec.codec import host_widen
    idx = np.nonzero(host_live)[0]
    arrays, fields = [], []
    for f, c, hv, hn, hc in zip(batch.schema, batch.columns, host_vals,
                                host_nulls, host_cargs):
        vals = hv[idx]
        nulls = hn[idx] if hn is not None else None
        if c.carrier is not None:
            vals = host_widen(c.carrier, vals, hc)
        if f.dtype.is_string:
            d = c.dictionary.values if c.dictionary is not None and len(c.dictionary) else np.asarray([], dtype=object)
            if len(d):
                ids = np.clip(vals, 0, len(d) - 1)
                py = d[ids]
            else:
                py = np.asarray([""] * len(vals), dtype=object)
            if nulls is not None:
                py = py.copy()
                py[nulls] = None
            arrays.append(pa.array(py, type=pa.string()))
        elif f.dtype.id == TypeId.DATE32:
            a = pa.array(vals.astype("int32"), type=pa.int32()).cast(pa.date32())
            if nulls is not None:
                a = pa.compute.if_else(pa.array(~nulls), a, pa.scalar(None, type=pa.date32()))
            arrays.append(a)
        elif f.dtype.id == TypeId.TIMESTAMP:
            a = pa.array(vals.astype("int64"), type=pa.int64()).cast(pa.timestamp("us"))
            if nulls is not None:
                a = pa.compute.if_else(pa.array(~nulls), a, pa.scalar(None, type=pa.timestamp("us")))
            arrays.append(a)
        else:
            if nulls is not None:
                arrays.append(pa.array(vals, mask=nulls))
            else:
                arrays.append(pa.array(vals))
        fields.append(pa.field(f.name, arrays[-1].type, f.nullable))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
