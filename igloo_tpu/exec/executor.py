"""Executor: optimized logical plan -> DeviceBatch pipeline.

This is the TPU counterpart of the reference's custom physical path
(`PhysicalPlanner::create_physical_plan` + operator `execute()` streams,
crates/engine/src/physical_planner.rs:23-140, physical_plan.rs:28-47) — with the
key architectural inversion from SURVEY.md §7: instead of streaming RecordBatches
through async operator objects, each pipeline region (scan -> filter -> project)
compiles into ONE jitted function over a DeviceBatch, and blocking operators
(aggregate / join / sort) are separate jitted stages stitched by host code.

Host syncs happen only where shapes must be decided (join candidate totals,
capacity shrinking between stages) — each is one scalar readback.

Jit compile caching is fingerprint-based: (node expression fingerprint, input
batch prototype) -> compiled callable, so repeated queries over the same tables
reuse executables across QueryEngine.execute calls.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.errors import ExecError, NotSupportedError, PlanError
from igloo_tpu.exec import dispatch
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.aggregate import (
    AggSpec, aggregate_batch, distinct_batch, minmax_order_arg, seg_dims_for,
)
from igloo_tpu.exec.batch import (
    DeviceBatch, DeviceColumn, DictInfo, device_columns, from_arrow,
    host_decode_column, round_capacity, to_arrow, wide_values,
)
from igloo_tpu.exec.expr_compile import (
    Compiled, ConstPool, Env, ExprCompiler, _unify_dicts,
)
from igloo_tpu.exec.join import (
    choose_direct_build, choose_match_capacity, direct_join_phase, expand_phase,
    join_batches, make_key_hash_idxs, probe_phase,
)
from igloo_tpu.exec.fused import FusedCompiler, FusionUnsupported
from igloo_tpu.exec.sort_limit import limit_batch, sort_batch, topk_batch
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import stats, tracing

_SHRINK_FACTOR = 4  # shrink a batch when capacity > factor * needed

import os as _os  # noqa: E402

# print each first-in-process program build (kind + fingerprint) to stderr:
# the last line before a hang names the program whose XLA compile is
# pathological (compiles run server-side on tunneled TPUs — local profiling
# sees only an idle wait)
_LOG_COMPILES = _os.environ.get("IGLOO_TPU_LOG_COMPILES", "") == "1"

_SENTINEL = object()  # "use the plan's projection" marker for read_scan_table


def read_scan_table(plan: L.Scan, projection=_SENTINEL) -> pa.Table:
    """Host-side scan IO honoring the plan's partition restriction. Replaces
    the reference's whole-table-only reads (parquet_scan.rs streams fixed
    1024-row batches but custom operators are single-stream) with explicit
    provider partitions the distributed planner / chunked executor slice.
    `projection` overrides the plan's (the column-granular scan cache reads
    only the columns it is missing).

    Partitioned reads first consult the query's storage prefetcher
    (storage/prefetch.py, installed by the chunked/GRACE feeds): a partition
    the reader thread already decoded is handed over without touching the
    source (counter `storage.prefetch_hit`); anything else reads
    synchronously."""
    proj = plan.projection if projection is _SENTINEL else projection
    if plan.partition is None:
        return plan.provider.read(projection=proj,
                                  filters=plan.pushed_filters)
    tok_fn = getattr(plan.provider, "partition_token", None)
    if plan.partition_token is not None and tok_fn is not None:
        cur = tok_fn()
        if cur != plan.partition_token:
            from igloo_tpu.errors import SnapshotChanged
            raise SnapshotChanged(
                f"partition index for {plan.table} changed since planning "
                "(source files moved/replaced)", table=plan.table)
    from igloo_tpu.storage import prefetch as _prefetch
    parts = [t for _, t in _prefetch.take_partitioned(
        plan.provider, plan.partition, proj, plan.pushed_filters)]
    return pa.concat_tables(parts) if parts else \
        plan.provider.read(projection=proj,
                           filters=plan.pushed_filters).slice(0, 0)


def batch_proto_key(batch: DeviceBatch):
    """Hashable prototype of a batch: everything that affects tracing. NOTE:
    deliberately dictionary-free — dictionary content reaches compiled code
    through ConstPool arguments, so only const SHAPES (in the pool signature)
    key the compile cache (round-1 verdict fix: content-keyed DictInfo in
    static aux forced a recompile for every new dictionary)."""
    return (batch.schema, batch.capacity,
            tuple(c.nulls is not None for c in batch.columns),
            # carrier-resident columns trace different programs (narrow lane
            # dtypes + in-jit widens), so the carrier form is part of the
            # prototype; data-dependent payloads (offset value) are NOT
            tuple((str(c.values.dtype), c.carrier.key())
                  if c.carrier is not None else None for c in batch.columns))


def expr_fingerprint(exprs) -> str:
    return "|".join(repr(e) for e in exprs)


def strip_dicts(batch: DeviceBatch) -> DeviceBatch:
    """Drop host-side metadata (dictionaries, bounds) before a batch crosses
    into jax.jit, so the pytree aux (= compile-cache key) is content-free."""
    from dataclasses import replace
    return DeviceBatch(batch.schema,
                       [replace(c, dictionary=None, bounds=None)
                        for c in batch.columns],
                       batch.live)


def attach_dicts(batch: DeviceBatch, dicts, bounds=None) -> DeviceBatch:
    """Re-attach per-column dictionaries + value bounds (host metadata) to a
    jit output. `bounds` defaults to all-unknown."""
    from dataclasses import replace
    if bounds is None:
        bounds = [None] * len(dicts)
    return DeviceBatch(batch.schema,
                       [replace(c, dictionary=d, bounds=b)
                        for c, d, b in zip(batch.columns, dicts, bounds)],
                       batch.live)


def col_meta(cols) -> tuple[list, list]:
    """(dicts, bounds) of a column list, for attach_dicts after a 1:1 jit."""
    return [c.dictionary for c in cols], [c.bounds for c in cols]


def _note_carrier_ratio(provider, batch: DeviceBatch) -> None:
    """Record the observed HBM carrier/wide byte ratio of a freshly scanned
    batch against its provider instance, so the chunked/GRACE/serving budget
    math (chunked.estimated_lane_bytes) prices this table in carrier bytes."""
    if provider is None or not batch.columns:
        return
    from igloo_tpu.exec.codec import record_carrier_ratio
    narrow = wide = 0
    for f, c in zip(batch.schema, batch.columns):
        wide += c.capacity * np.dtype(f.dtype.device_dtype()).itemsize
        narrow += c.values.nbytes
    record_carrier_ratio(provider, narrow, wide)
    if stats.detail_active():
        # EXPLAIN ANALYZE: which scans ride carriers and how hard — resident
        # vs would-be-wide bytes, per scan op
        stats.annotate(encoded_lanes=sum(1 for c in batch.columns
                                         if c.carrier is not None),
                       carrier_bytes=narrow, decoded_bytes=wide)


# per-query D2H accounting at the executor's fetch sites
record_fetch = stats.record_fetch


class _CompileTimed:
    """One-shot wrapper returned by `_jitted` on a cache miss when a query is
    being collected: times the FIRST call (where jax traces, lowers and
    compiles synchronously before dispatch) and attributes it to the current
    operator as compile time. Never cached — later calls get the raw fn.
    Under IGLOO_TRACE_DEVICE=1 the first call is bracketed in a named
    TraceAnnotation so the compile lands attributably in the jax profiler's
    Perfetto timeline."""
    __slots__ = ("fn", "kind")

    def __init__(self, fn, kind: str = ""):
        self.fn = fn
        self.kind = kind

    def __call__(self, *args, **kw):
        t0 = time.perf_counter()
        try:
            with tracing.device_annotation(f"igloo:compile:{self.kind}"):
                return self.fn(*args, **kw)
        finally:
            dt = time.perf_counter() - t0
            stats.record_compile(dt)
            tracing.histogram("compile.first_call_s", dt)


def _device_annotated(fn, kind: str):
    """Execute-side half of the IGLOO_TRACE_DEVICE bridge: every dispatch of
    this program runs inside a named TraceAnnotation. Only built when the
    bridge is on — the off path returns the raw fn untouched."""
    name = f"igloo:execute:{kind}"

    def run(*args, **kw):
        with tracing.device_annotation(name):
            return fn(*args, **kw)
    return run


class Executor:
    # Speculative join expand: when both inputs fit the budget, expand with
    # capacity max(left, right) WITHOUT syncing on the exact candidate total.
    # That bound is exact for FK joins (every TPC-H join: one side's keys are
    # unique, so total <= max live side); overflow (a genuine many-to-many
    # blowup) only DROPS candidates past the cap — expand masks by the true
    # total — so the deferred device-side `total > cap` flags checked at the
    # final fetch make the fallback (exact re-execution, one sync per join)
    # fully correct. Saves one ~100ms device roundtrip per join on a tunneled
    # TPU (round-2 weak #1: warm Q5 spent 5 of its 7 roundtrips here).
    _SPECULATIVE_JOIN_BUDGET = 1 << 22

    def __init__(self, jit_cache: Optional[dict] = None, use_jit: bool = True,
                 batch_cache=None, speculate: bool = True, hints=None):
        # shared across queries when the engine passes its own cache dict
        self._cache = jit_cache if jit_cache is not None else {}
        self._use_jit = use_jit
        self._batch_cache = batch_cache  # Optional[BatchCache]
        self._speculate = speculate
        self._hints = hints  # Optional[HintStore] (persistent nhints)
        # ORDER BY + LIMIT fusion handshake (staged tier): _exec_limit sets
        # the hint before descending into its Sort child; _exec_sort consumes
        # it (identity-matched on the plan node) when dispatch.plan_topk
        # adopts, and raises _limit_taken so _exec_limit skips the mask pass
        self._limit_hint: Optional[tuple] = None
        self._limit_taken = False
        self._deferred_overflow: list = []  # device bools, checked at final fetch
        # (hint key, device int) pairs riding the SAME final fetch: observed
        # live counts that persist as capacity hints for the staged path's
        # adaptive join compaction (mirror of the fused path's ctx.stats)
        self._deferred_stats: list = []

    # --- cache helpers ---

    def _jitted(self, kind: str, fingerprint, build: Callable[[], Callable],
                static_argnums=()) -> Callable:
        # the Pallas dispatch token rides EVERY key: implicit dispatch
        # decisions (the fused gather inside any traced fn) depend on the
        # IGLOO_TPU_PALLAS mode, so a mid-process flip must never serve a
        # program traced under the other mode
        key = (kind, fingerprint, dispatch.cache_token())
        fn = self._cache.get(key)
        if fn is None:
            tracing.counter("jit.miss")
            stats.bump_attr("jit_miss")
            if _LOG_COMPILES:
                import sys
                print(f"igloo-compile: {kind} "
                      f"{hash(repr(fingerprint)) & 0xFFFFFFFF:08x} "
                      f"{repr(fingerprint)[:160]}",
                      file=sys.stderr, flush=True)
            fn = build()
            if self._use_jit:
                fn = jax.jit(fn, static_argnums=static_argnums)
            self._cache[key] = fn
            if stats.current() is not None:
                # the raw fn is what got cached; the wrapper lives for this
                # one first call and books it as the node's compile cost
                fn = _CompileTimed(fn, kind)
                if tracing.device_trace_enabled():
                    fn = _device_annotated(fn, kind)
                return fn
        else:
            tracing.counter("jit.hit")
            stats.bump_attr("jit_hit")
        if tracing.device_trace_enabled():
            return _device_annotated(fn, kind)
        return fn

    # --- entry ---

    def execute(self, plan: L.LogicalPlan) -> DeviceBatch:
        batch = self._exec(plan)
        if self._deferred_overflow or self._deferred_stats:
            deferred, self._deferred_overflow = self._deferred_overflow, []
            stat_pairs, self._deferred_stats = self._deferred_stats, []
            vals, svals = jax.device_get(
                ([f for _, f in deferred], [v for _, v in stat_pairs]))
            self._record_stats(stat_pairs, svals)
            fired = self._fired_deferred(deferred, vals)
            if fired:
                return self._retry_copy(fired).execute(plan)
        return batch

    def _staged_hint(self, key) -> Optional[int]:
        v = self._cache.get(("nhint", key))
        if v is None and self._hints is not None:
            v = self._hints.get(key)
            if v is not None:
                self._cache[("nhint", key)] = v
        return int(v) if v is not None else None

    def _record_stats(self, stats, svals) -> None:
        for (key, _), v in zip(stats, svals):
            self._cache[("nhint", key)] = int(v)
            if self._hints is not None:
                self._hints.put(key, int(v))
        if stats and self._hints is not None:
            self._hints.flush()

    def _record_fired_tag(self, tag) -> None:
        """Negative-cache + counter bookkeeping for ONE fired deferred flag —
        shared by the staged (_fired_deferred) and fused (_fused_run) tiers
        so a tag kind can never gain handling in one and drift in the other
        (the cross-tier ban-key lesson of this PR)."""
        if tag[0] == "dup":
            # THIS side of the join proved to have duplicate keys — the
            # other side may still direct-join
            jfp_core, side = tag[1]
            self._cache[("nodirect", jfp_core, side)] = True
            tracing.counter("join.direct_dup_fallback")
        elif tag[0] == "pallas_probe":
            # probe window overflow: this join's build side carries longer
            # duplicate-hash runs than the kernel scans — sort path from
            # now on
            self._cache[("nopallas_probe", tag[1])] = True
            tracing.counter("pallas.probe_overflow")
        elif tag[0] == "pallas_agg":
            # hash-table bucket exhaustion: more distinct groups than the
            # table holds — sort path from now on
            self._cache[("nopallas_agg", tag[1])] = True
            tracing.counter("pallas.agg_overflow")
        elif tag[0] == "pallas_match":
            # match-materialization window overflow: some probe row owns a
            # longer match run than the kernel's window — scan path from
            # now on
            self._cache[("nopallas_match", tag[1])] = True
            tracing.counter("pallas.match_overflow")

    def _fired_deferred(self, deferred, vals) -> list:
        """Check fetched deferred-flag values; returns the fired tags (empty
        = nothing fired), with negative caches recorded."""
        fired = []
        for (tag, _), v in zip(deferred, vals):
            if bool(v):
                fired.append(tag)
                self._record_fired_tag(tag)
        return fired

    def _retry_copy(self, fired_tags) -> "Executor":
        """The executor to re-run a plan on after `fired_tags` fired. Any
        speculative-family tag (capacity overflow, direct-join dup, semi
        window, stale compaction) needs the exact copy. A Pallas-ONLY
        fallback keeps speculation on: the negative caches just recorded
        already route the failing op to the sort path, and the plan's
        speculative joins were not at fault — disabling them would make the
        repair run pay a count sync per join for nothing. (The sharded
        tier never plans Pallas kernels, so its _exact_copy override is
        always the path taken there.)"""
        if any(t[0] not in ("pallas_probe", "pallas_agg", "pallas_match")
               for t in fired_tags):
            return self._exact_copy()
        return Executor(self._cache, use_jit=self._use_jit,
                        batch_cache=self._batch_cache,
                        speculate=self._speculate, hints=self._hints)

    def _exact_copy(self) -> "Executor":
        """A sibling executor with speculation off (shares all caches); used to
        re-run a plan after a deferred speculative-join overflow fired
        (Pallas-only fallbacks take _retry_copy's speculation-preserving
        sibling instead and never reach here)."""
        tracing.counter("join.speculation_overflow")
        return Executor(self._cache, use_jit=self._use_jit,
                        batch_cache=self._batch_cache, speculate=False,
                        hints=self._hints)

    # Above this capacity a final batch is speculatively compacted down to this
    # many lanes before the device->host fetch: most query results fit, so the
    # common case pays ONE roundtrip carrying (count, compacted lanes) instead
    # of either a huge padded transfer or a count sync followed by a fetch.
    # On overflow (count > cap) we pay the exact compact + refetch.
    _FINAL_FETCH_CAPACITY = 1 << 10

    # whole-plan fusion (exec/fused.py): one dispatch + one fetch per query.
    # ShardedExecutor overrides to False (its stages shard_map over a mesh).
    _FUSE = True

    def execute_to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        # detail-mode stats (EXPLAIN ANALYZE) route to the staged executor:
        # the fused program is ONE dispatch with no internal operator
        # boundaries, so per-operator rows/timings only exist staged
        if self._FUSE and self._use_jit and self._speculate and \
                not stats.detail_active():
            try:
                return self._fused_to_arrow(plan)
            except FusionUnsupported as e:
                tracing.counter("fused.unsupported")
                tracing.counter(f"fused.unsupported.{e.args[0] if e.args else ''}")
        return self._staged_to_arrow(plan)

    def _fused_to_arrow(self, plan: L.LogicalPlan, _retry: bool = True) -> pa.Table:
        """Execute via the fused whole-plan program: one dispatch, one fetch
        of (deferred flags, cardinality stats, row count, compacted result).
        Observed live counts update the adaptive capacity hints; a compaction
        overflow triggers ONE repair re-run with the fresh hints, any other
        flag (direct-join duplicates, speculative overflow) an exact staged
        re-run. Oversized results pay an exact compact + full fetch."""
        with stats.op("FusedProgram" if _retry else "FusedProgram(repair)"):
            return self._fused_run(plan, _retry)

    def _fused_run(self, plan: L.LogicalPlan, _retry: bool) -> pa.Table:
        from igloo_tpu.exec.batch import arrow_from_host
        comp = FusedCompiler(self)
        run, key, meta = comp.compile(plan)
        stats.annotate(nodes=len(comp.fps), leaves=len(comp.leaves))
        # `nofuse` sentinel: armed in the persistent store before a
        # first-in-process fused compile, cleared on success. A process killed
        # mid-compile (pathological XLA compiles run 20+ min on some fused
        # join shapes — BASELINE.md) leaves it armed; after two strikes later
        # processes route this plan to the staged executor instead of
        # recompiling the program that hung.
        sentinel = ("nofuse", key)
        first = ("fused", key) not in self._cache
        if first and self._hints is not None:
            strikes = self._hints.get(sentinel) or 0
            if strikes >= 2:
                tracing.counter("fused.nofuse_sentinel")
                raise FusionUnsupported("nofuse_sentinel")
            self._hints.put(sentinel, strikes + 1)
            self._hints.flush()
        jf = self._jitted("fused", key, lambda: run)
        tracing.counter("fused.execute")
        try:
            big, spec, n_dev, flags, stats_dev = jf(
                [strip_dicts(b) for b in comp.leaves],
                comp.pool.device_args())
        except BaseException as e:
            # an ordinary exception means the compile did NOT hang — clear
            # the strike so transient failures can't poison fusion forever
            # (a process killed mid-compile never reaches this handler)
            if first and self._hints is not None:
                self._hints.remove(sentinel)
                self._hints.flush()
            if comp.pallas_bans and isinstance(e, Exception):
                # compile-failure rung: ban every Pallas plan this program
                # contained and recompile on the sort path (an unrelated
                # error re-raises from the Pallas-free program — the bans
                # are then conservative, not wrong)
                for bkey in comp.pallas_bans:
                    self._cache[bkey] = True
                tracing.counter("pallas.compile_fallback")
                return self._fused_run(plan, _retry)
            raise
        if first and self._hints is not None:
            self._hints.remove(sentinel)
            self._hints.flush()
        flags_h, stats_h, n, host_live, host_vals, host_nulls, host_cargs = \
            jax.device_get(
                (flags, stats_dev, n_dev, spec.live,
                 [c.values for c in spec.columns],
                 [c.nulls for c in spec.columns],
                 [c.carrier_arg for c in spec.columns]))
        record_fetch((host_live, host_vals, host_nulls))
        stats.set_rows(int(n))
        for sid, v in stats_h.items():
            self._cache[("nhint", comp.stat_keys[sid])] = int(v)
            if self._hints is not None:
                self._hints.put(comp.stat_keys[sid], int(v))
        if self._hints is not None:
            self._hints.flush()
        fired = [comp.flag_tags[fid] for fid, v in flags_h.items() if bool(v)]
        if fired:
            for tag in fired:
                self._record_fired_tag(tag)
            if _retry and all(t[0] == "compact" for t in fired):
                # stale cardinality hints only: repair with the fresh ones
                tracing.counter("fused.compact_repair")
                return self._fused_to_arrow(plan, _retry=False)
            return self._retry_copy(fired).execute_to_arrow(plan)
        spec = attach_dicts(spec, meta.dicts, meta.bounds)
        if int(n) <= spec.capacity:
            return arrow_from_host(spec, host_live, host_vals, host_nulls,
                                   host_cargs)
        # result larger than the fetch window: exact compact + full fetch.
        # Clamp to the batch's own capacity (already a family member): the
        # live count can sit in the hysteresis band just under it, and an
        # un-clamped round would pad the fetch a full family step PAST the
        # rows that exist
        want = min(round_capacity(int(n)), big.capacity)
        fp = ("compact", batch_proto_key(big), want)

        def build():
            def fn(b):
                return K.compact_to(b, want)
            return fn
        out = self._jitted("compact", fp, build)(big)
        return to_arrow(attach_dicts(out, meta.dicts, meta.bounds))

    def _staged_to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        from igloo_tpu.exec.batch import arrow_from_host
        batch = self._exec(plan)
        deferred, self._deferred_overflow = self._deferred_overflow, []
        stat_pairs, self._deferred_stats = self._deferred_stats, []
        dvals = [f for _, f in deferred]
        dstats = [v for _, v in stat_pairs]
        cap = self._FINAL_FETCH_CAPACITY
        if batch.capacity <= cap:
            flags, svals, host_live, host_vals, host_nulls, host_cargs = \
                jax.device_get(
                    (dvals, dstats, batch.live,
                     [c.values for c in batch.columns],
                     [c.nulls for c in batch.columns],
                     [c.carrier_arg for c in batch.columns]))
            record_fetch((host_live, host_vals, host_nulls))
            self._record_stats(stat_pairs, svals)
            fired = self._fired_deferred(deferred, flags)
            if fired:
                return self._retry_copy(fired).execute_to_arrow(plan)
            return arrow_from_host(batch, host_live, host_vals, host_nulls,
                                   host_cargs)
        fp = ("spec_compact", batch_proto_key(batch), cap)

        def build():
            def fn(b):
                n = jnp.sum(b.live)
                return K.compact_to(b, cap), n
            return fn
        spec, n_dev = self._jitted("spec_compact", fp, build)(strip_dicts(batch))
        spec = attach_dicts(spec, *col_meta(batch.columns))
        flags, svals, host_n, host_live, host_vals, host_nulls, host_cargs = \
            jax.device_get(
                (dvals, dstats, n_dev, spec.live,
                 [c.values for c in spec.columns],
                 [c.nulls for c in spec.columns],
                 [c.carrier_arg for c in spec.columns]))
        record_fetch((host_live, host_vals, host_nulls))
        self._record_stats(stat_pairs, svals)
        fired = self._fired_deferred(deferred, flags)
        if fired:
            return self._retry_copy(fired).execute_to_arrow(plan)
        if int(host_n) <= cap:
            return arrow_from_host(spec, host_live, host_vals, host_nulls,
                                   host_cargs)
        # overflow: compact to the exact capacity and refetch (clamped to the
        # batch's own capacity — see the fused path's compact above)
        want = min(round_capacity(int(host_n)), batch.capacity)
        fp = ("compact", batch_proto_key(batch), want)

        def build_full():
            def fn(b):
                return K.compact_to(b, want)
            return fn
        out = self._jitted("compact", fp, build_full)(strip_dicts(batch))
        return to_arrow(attach_dicts(out, *col_meta(batch.columns)))

    def _exec(self, plan: L.LogicalPlan) -> DeviceBatch:
        m = getattr(self, "_exec_" + type(plan).__name__.lower(), None)
        if m is None:
            raise NotSupportedError(f"no executor for {type(plan).__name__}")
        with stats.plan_op(plan):
            out = m(plan)
            if stats.detail_active():
                # EXPLAIN ANALYZE: actual row count, one device sync per op
                n = out.num_live()
                stats.set_rows(n)
                stats.annotate(capacity=out.capacity)
                if not isinstance(plan, L.Scan):
                    # the sync is already paid for: feed the adaptive planner
                    # loop (docs/adaptive.md) — EXPLAIN ANALYZE doubles as
                    # the device tier's cardinality profiler
                    from igloo_tpu.exec.hints import plan_fp
                    fp = plan_fp(plan)
                    if fp is not None:
                        stats.observe_card(fp, n)
        if out.schema is not plan.schema and out.schema != plan.schema:
            # keep plan schema authoritative (names may differ from kernel output)
            out = DeviceBatch(plan.schema, out.columns, out.live)
        return out

    # --- leaves ---

    def _exec_scan(self, plan: L.Scan) -> DeviceBatch:
        batch = self._scan_batch(plan)
        # provider-pinned bounds (GRACE partitions: the UNION range across all
        # partitions) replace per-read exact bounds so every partition keys
        # the same compiled programs; a superset range is always safe for the
        # consumers (direct-join table sizing, packed-key radices)
        fixed = getattr(plan.provider, "fixed_bounds", None) \
            if plan.provider is not None else None
        if fixed:
            from dataclasses import replace
            cols = [replace(c, bounds=fixed.get(f.name, c.bounds))
                    for f, c in zip(batch.schema, batch.columns)]
            batch = DeviceBatch(batch.schema, cols, batch.live)
        return batch

    def _scan_batch(self, plan: L.Scan) -> DeviceBatch:
        # GRACE partition pipeline: the prefetch thread already decoded,
        # narrowed and device_put this bucket while the previous partition's
        # program ran — hand its batch through without touching the caches
        # (the provider lives exactly one partition, so caching it would only
        # pin dead HBM)
        pre = getattr(plan.provider, "prebuilt_batch", None) \
            if plan.provider is not None else None
        if pre is not None:
            return pre
        stable = getattr(plan.provider, "stable_row_order", False)
        if self._batch_cache is None or not stable:
            # whole-batch path: providers without deterministic row order
            # (e.g. DBAPI SELECTs with no ORDER BY) must never stitch columns
            # from separate reads; they get one read per (projection) and a
            # whole-batch cache entry.
            key = snap = None
            if self._batch_cache is not None:
                from igloo_tpu.exec.cache import provider_snapshot
                key = (plan.table,
                       tuple(plan.projection) if plan.projection is not None
                       else None,
                       expr_fingerprint(plan.pushed_filters), plan.partition)
                snap = provider_snapshot(plan.provider)
                hit = self._batch_cache.get(key, snap)
                if hit is not None:
                    return hit
            table = read_scan_table(plan)
            if plan.projection is not None:
                table = table.select(plan.projection)
            batch = from_arrow(table, schema=plan.schema)
            _note_carrier_ratio(plan.provider, batch)
            if self._batch_cache is not None:
                self._batch_cache.put(key, batch, snap)
            return batch
        # COLUMN-granular HBM cache: entries are per (table, filters,
        # partition, column), so scans with different projections share the
        # uploaded lanes they have in common — on a tunneled TPU the upload
        # is the dominant per-process cost (BASELINE.md: ~10-20 MB/s), so a
        # 22-query sweep must ship each column at most once. Entry values are
        # (DeviceColumn, n_rows); n makes the live lane reconstructible after
        # its entry is evicted without re-reading a column.
        from igloo_tpu.exec.cache import provider_snapshot
        from igloo_tpu.exec.codec import live_lane
        snap = provider_snapshot(plan.provider)
        # the engine's host fast path executes small plans under
        # jax.default_device(cpu); its uploads must not alias the
        # accelerator-resident copies of the same columns
        dev = getattr(jax.config, "jax_default_device", None)
        base = (plan.table, expr_fingerprint(plan.pushed_filters),
                plan.partition, str(dev) if dev is not None else "default")
        cached = {f.name: self._batch_cache.get(base + ("col", f.name), snap)
                  for f in plan.schema}
        live = self._batch_cache.get(base + ("live",), snap)
        missing = [f for f in plan.schema if cached[f.name] is None]
        known_n = next((v[1] for v in cached.values() if v is not None), None)
        if live is not None:
            live, live_n = live
        else:
            live_n = None
        if live is None and known_n is not None and not missing:
            cap0 = next(v[0].capacity for v in cached.values() if v is not None)
            live = live_lane(cap0, known_n)
            self._batch_cache.put_entry(base + ("live",), (live, known_n),
                                        snap, live.nbytes, plan.table)
        if not missing and live is not None:
            return DeviceBatch(plan.schema,
                               [cached[f.name][0] for f in plan.schema], live)
        proj = [f.name for f in missing]  # non-empty: all-cached paths return above
        table = read_scan_table(plan, projection=proj).select(proj)
        n = table.num_rows
        if (known_n is not None and n != known_n) or \
                (live_n is not None and n != live_n):
            # source changed under an identity snapshot: drop and re-read all
            self._batch_cache.invalidate_table(plan.table)
            return self._exec_scan(plan)
        cap = int(live.shape[0]) if live is not None else (
            round_capacity(n) if known_n is None
            else next(v[0].capacity for v in cached.values() if v is not None))
        decoded = [host_decode_column(table.column(f.name), f)
                   for f in missing]
        new_cols = device_columns(decoded, missing, cap)
        for f, col in zip(missing, new_cols):
            nbytes = col.values.nbytes + (
                col.nulls.nbytes if col.nulls is not None else 0)
            self._batch_cache.put_entry(base + ("col", f.name), (col, n),
                                        snap, nbytes, plan.table)
            cached[f.name] = (col, n)
        if live is None:
            live = live_lane(cap, n)
            self._batch_cache.put_entry(base + ("live",), (live, n), snap,
                                        live.nbytes, plan.table)
        out = DeviceBatch(plan.schema,
                          [cached[f.name][0] for f in plan.schema], live)
        _note_carrier_ratio(plan.provider, out)
        return out

    def _exec_values(self, plan: L.Values) -> DeviceBatch:
        n = len(plan.rows)
        if len(plan.schema) == 0:
            cap = round_capacity(max(n, 1))
            live = np.zeros(cap, dtype=bool)
            live[:n] = True
            return DeviceBatch(plan.schema, [], jnp.asarray(live))
        arrays = []
        for j, f in enumerate(plan.schema):
            vals = [r[j] for r in plan.rows]
            arrays.append(pa.array(vals, type=_pa_type_for(f.dtype)))
        table = pa.Table.from_arrays(arrays, names=plan.schema.names)
        return from_arrow(table, schema=plan.schema)

    # --- pipeline ops (fused per node; XLA fuses chains of these) ---

    def _compile_exprs(self, exprs, batch: DeviceBatch,
                       comp: Optional[ExprCompiler] = None):
        """Host-compile `exprs` against `batch`'s dictionaries. Returns
        (resolved exprs, compiled, compiler) — resolved exprs carry evaluated
        scalar-subquery literals, so fingerprints built from them key the
        compile cache on the actual values."""
        if comp is None:
            comp = ExprCompiler([c.dictionary for c in batch.columns],
                    bounds=[c.bounds for c in batch.columns])
        resolved = [self._resolve_subqueries(e) for e in exprs]
        return resolved, [comp.compile(e) for e in resolved], comp

    def _exec_filter(self, plan: L.Filter) -> DeviceBatch:
        batch = self._exec(plan.input)
        res, [c], comp = self._compile_exprs([plan.predicate], batch)
        fp = ("filter", expr_fingerprint(res), batch_proto_key(batch),
              comp.pool.signature(), tuple(comp.marks))

        def build():
            def fn(b: DeviceBatch, consts) -> DeviceBatch:
                env = Env.from_batch(b, consts)
                v, nl = c.fn(env)
                keep = b.live & v
                if nl is not None:
                    keep = keep & ~nl
                return DeviceBatch(b.schema, b.columns, keep)
            return fn
        out = self._jitted("filter", fp, build)(strip_dicts(batch),
                                                comp.pool.device_args())
        return attach_dicts(out, *col_meta(batch.columns))

    def _exec_project(self, plan: L.Project) -> DeviceBatch:
        batch = self._exec(plan.input)
        res, comps, comp = self._compile_exprs(plan.exprs, batch)
        fp = ("project", expr_fingerprint(res), batch_proto_key(batch),
              plan.schema, comp.pool.signature(), tuple(comp.marks))
        out_schema = plan.schema

        def build():
            def fn(b: DeviceBatch, consts) -> DeviceBatch:
                env = Env.from_batch(b, consts)
                cols = []
                for cc, f in zip(comps, out_schema.fields):
                    v, nl = cc.fn(env)
                    want = f.dtype.device_dtype()
                    if v.dtype != want:
                        v = v.astype(want)
                    cols.append(DeviceColumn(f.dtype, v, nl, None))
                return DeviceBatch(out_schema, cols, b.live)
            return fn
        out = self._jitted("project", fp, build)(strip_dicts(batch),
                                                 comp.pool.device_args())
        return attach_dicts(out, [cc.out_dict for cc in comps],
                    [cc.out_bounds for cc in comps])

    # --- blocking ops ---

    def _exec_aggregate(self, plan: L.Aggregate) -> DeviceBatch:
        batch = self._adaptive_input(self._exec(plan.input), plan.input)
        distinct_aggs = [a for a in plan.aggs if a.distinct]
        if distinct_aggs:
            return self._exec_distinct_aggregate(plan, batch)
        return self._aggregate(batch, plan.group_exprs, plan.aggs, plan.schema)

    def _aggregate(self, batch, group_exprs, aggs, out_schema) -> DeviceBatch:
        comp = ExprCompiler([c.dictionary for c in batch.columns],
                    bounds=[c.bounds for c in batch.columns])
        gres, groups, _ = self._compile_exprs(group_exprs, batch, comp)
        specs = []
        ares = []
        for a in aggs:
            if a.arg is not None:
                [r], [arg], _ = self._compile_exprs([a.arg], batch, comp)
                ares.append(r)
            else:
                arg = None
            out_dict = arg.out_dict if (arg is not None and a.dtype.is_string) else None
            specs.append(AggSpec(a.func, arg, a.dtype, out_dict,
                                 order_arg=minmax_order_arg(a.func, arg, comp)))
        # direct-scatter eligibility is dictionary-CONTENT-dependent (sizes),
        # so it must join the cache key, not just shape signatures
        n_scatters = sum(2 if a.func is E.AggFunc.AVG else 1 for a in aggs)
        seg_dims = seg_dims_for(groups, n_aggs=n_scatters,
                                input_capacity=batch.capacity)
        # packed-key single-sort path for everything the scatter path rejects:
        # a host decision on bounds/dictionary sizes, so it keys the cache too
        # (the spec's radices are static; its offsets ride the const pool)
        pack_spec = None
        if seg_dims is None and groups:
            pack_spec = K.plan_group_packing(groups, comp.pool)
            if pack_spec is not None:
                tracing.counter("pack.agg")
        # Pallas one-pass hash aggregation for the sort tier: needs a
        # full-cover pack (the packed lane is then an exact group id); its
        # table-overflow flag negative-caches this aggregate onto the sort
        # path. A host decision -> part of the cache key.
        pallas_agg = None
        afp_core = ("agg", expr_fingerprint(gres + ares),
                    tuple((a.func, a.dtype) for a in aggs))
        if seg_dims is None and pack_spec is not None:
            pallas_agg = dispatch.plan_segagg(
                pack_spec, len(groups), batch.capacity,
                banned=bool(self._cache.get(("nopallas_agg", afp_core))))
        def agg_fn(pa):
            fp = ("agg", expr_fingerprint(gres + ares),
                  tuple((a.func, a.dtype) for a in aggs),
                  batch_proto_key(batch), out_schema,
                  comp.pool.signature(), tuple(comp.marks), seg_dims,
                  pack_spec, pa)

            def build():
                def fn(b: DeviceBatch, consts):
                    if pa is None:
                        out = aggregate_batch(b, groups, specs, out_schema,
                                              consts, seg_dims=seg_dims,
                                              pack_spec=pack_spec)
                        return out, jnp.zeros((), jnp.bool_)
                    return aggregate_batch(b, groups, specs, out_schema,
                                           consts, seg_dims=seg_dims,
                                           pack_spec=pack_spec,
                                           pallas_agg=pa)
                return fn
            return self._jitted("agg", fp, build)

        try:
            out, agg_ovf = agg_fn(pallas_agg)(strip_dicts(batch),
                                              comp.pool.device_args())
        except Exception:
            if pallas_agg is None:
                raise
            # compile-failure rung (see _exec_join): sort path, negative
            # cache, attributable
            self._cache[("nopallas_agg", afp_core)] = True
            tracing.counter("pallas.compile_fallback")
            pallas_agg = None
            out, agg_ovf = agg_fn(None)(strip_dicts(batch),
                                        comp.pool.device_args())
        stats.annotate(strategy="direct_scatter" if seg_dims is not None
                       else "pallas_segagg" if pallas_agg is not None
                       else "packed_sort" if pack_spec is not None
                       else "lex_sort")
        if pallas_agg is not None:
            stats.annotate(pallas="segagg")
            self._deferred_overflow.append((("pallas_agg", afp_core),
                                            agg_ovf))
        out = attach_dicts(out, [g.out_dict for g in groups] +
                           [s.out_dict for s in specs])
        return self._maybe_shrink(out)

    def _exec_distinct_aggregate(self, plan: L.Aggregate,
                                 batch: DeviceBatch) -> DeviceBatch:
        """agg(DISTINCT x) mixed with arbitrary plain aggregates: stage 1
        groups by (keys..., x), carrying per-combination PARTIALS of every
        plain aggregate (COUNT_STAR -> row count, SUM -> partial sum, AVG ->
        partial sum + count, MIN/MAX pass through); stage 2 re-groups by the
        keys, applying the distinct aggregates to the deduped x column and
        merging the plain partials. Only multiple DISTINCT arguments remain
        unsupported (they would need a null-safe join of per-arg results)."""
        args = {repr(a.arg) for a in plan.aggs if a.distinct}
        if len(args) > 1:
            raise NotSupportedError(
                "multiple distinct aggregate arguments are not supported yet")
        d_arg = next(a.arg for a in plan.aggs if a.distinct)
        k = len(plan.group_exprs)
        # stage 1: group by (keys..., arg); one row per distinct combination
        stage1_groups = list(plan.group_exprs) + [d_arg]
        names = [f"g{i}" for i in range(k)] + ["__arg"]
        s1_fields = [T.Field(n, g.dtype, True)
                     for n, g in zip(names, stage1_groups)]
        s1_aggs: list[E.Aggregate] = []
        # per original plain agg: list of stage-1 column indices it reads
        plain_slots: dict[int, tuple] = {}
        si = k + 1  # stage-1 output: keys..., __arg, partial cols...

        def s1_agg(func, arg, dtype):
            nonlocal si
            a2 = E.Aggregate(func=func, arg=arg, distinct=False)
            a2.dtype = dtype
            s1_aggs.append(a2)
            s1_fields.append(T.Field(f"p{si}", dtype, True))
            si += 1
            return si - 1

        for j, a in enumerate(plan.aggs):
            if a.distinct:
                continue
            if a.func is E.AggFunc.COUNT_STAR:
                plain_slots[j] = ("sum", s1_agg(E.AggFunc.COUNT_STAR, None,
                                                T.INT64))
            elif a.func is E.AggFunc.COUNT:
                plain_slots[j] = ("sum", s1_agg(E.AggFunc.COUNT, a.arg,
                                                T.INT64))
            elif a.func is E.AggFunc.SUM:
                plain_slots[j] = ("sum", s1_agg(E.AggFunc.SUM, a.arg, a.dtype))
            elif a.func in (E.AggFunc.MIN, E.AggFunc.MAX):
                plain_slots[j] = ("assoc", s1_agg(a.func, a.arg, a.dtype))
            elif a.func is E.AggFunc.AVG:
                plain_slots[j] = ("avg",
                                  s1_agg(E.AggFunc.SUM, a.arg, T.FLOAT64),
                                  s1_agg(E.AggFunc.COUNT, a.arg, T.INT64))
            else:  # pragma: no cover - AggFunc is closed
                raise NotSupportedError(f"distinct mix with {a.func}")
        s1_schema = T.Schema(s1_fields)
        deduped = self._aggregate(batch, stage1_groups, s1_aggs, s1_schema)

        # stage 2: group by keys over the deduped rows
        def rebased_col(i, dtype, name=None):
            c = E.Column(name or f"c{i}", index=i)
            c.dtype = dtype
            return c
        g2 = [rebased_col(i, g.dtype, names[i])
              for i, g in enumerate(plan.group_exprs)]
        arg2 = rebased_col(k, d_arg.dtype, "__arg")
        aggs2: list[E.Aggregate] = []
        s2_fields = [T.Field(names[i], g.dtype, True)
                     for i, g in enumerate(plan.group_exprs)]
        # per original agg: stage-2 output column index (or (sum, cnt) pair)
        out_slots: list = []
        oi = k

        def s2_agg(func, arg, dtype):
            nonlocal oi
            a2 = E.Aggregate(func=func, arg=arg, distinct=False)
            a2.dtype = dtype
            aggs2.append(a2)
            s2_fields.append(T.Field(f"o{oi}", dtype, True))
            oi += 1
            return oi - 1

        for j, a in enumerate(plan.aggs):
            if a.distinct:
                out_slots.append(("direct", s2_agg(a.func, arg2, a.dtype)))
                continue
            kind = plain_slots[j][0]
            if kind == "sum":
                col = rebased_col(plain_slots[j][1],
                                  s1_schema.fields[plain_slots[j][1]].dtype)
                out_slots.append(("zero_null" if a.func in (
                    E.AggFunc.COUNT, E.AggFunc.COUNT_STAR) else "direct",
                    s2_agg(E.AggFunc.SUM, col, a.dtype)))
            elif kind == "assoc":
                col = rebased_col(plain_slots[j][1], a.dtype)
                out_slots.append(("direct", s2_agg(a.func, col, a.dtype)))
            else:  # avg: SUM(partial sums) / SUM(partial counts)
                scol = rebased_col(plain_slots[j][1], T.FLOAT64)
                ccol = rebased_col(plain_slots[j][2], T.INT64)
                out_slots.append(("avg", s2_agg(E.AggFunc.SUM, scol, T.FLOAT64),
                                  s2_agg(E.AggFunc.SUM, ccol, T.INT64)))
        s2_schema = T.Schema(s2_fields)
        merged = self._aggregate(deduped, g2, aggs2, s2_schema)

        # final: pick/compute the plan's declared output columns
        cols = list(merged.columns[:k])
        for slot, a in zip(out_slots, plan.aggs):
            if slot[0] == "avg":
                s, c = merged.columns[slot[1]], merged.columns[slot[2]]
                cnt_v = jnp.where(c.nulls, 0, c.values) if c.nulls is not None \
                    else c.values
                denom = jnp.where(cnt_v == 0, 1, cnt_v).astype(jnp.float64)
                cols.append(DeviceColumn(
                    T.FLOAT64, s.values.astype(jnp.float64) / denom,
                    cnt_v == 0, None))
            elif slot[0] == "zero_null":
                c = merged.columns[slot[1]]
                vals = jnp.where(c.nulls, 0, c.values) if c.nulls is not None \
                    else c.values
                cols.append(DeviceColumn(T.INT64, vals, None, None))
            else:
                cols.append(merged.columns[slot[1]])
        return DeviceBatch(plan.schema, cols, merged.live)

    def _exec_distinct(self, plan: L.Distinct) -> DeviceBatch:
        batch = self._adaptive_input(self._exec(plan.input), plan.input)
        fp = ("distinct", batch_proto_key(batch))

        def build():
            return distinct_batch
        out = self._jitted("distinct", fp, build)(strip_dicts(batch))
        out = attach_dicts(out, *col_meta(batch.columns))
        return self._maybe_shrink(out)

    def _adaptive_input(self, batch: DeviceBatch,
                        plan_node: L.LogicalPlan) -> DeviceBatch:
        """Bound a join input's CAPACITY before the probe program compiles:
        XLA compile time on the sorted-probe join grows pathologically with
        lane count (observed: a 2x8.4M-lane probe+expand never finished in
        25 min, while 8.4Mx64 compiles in ~71 s — q18/q21 at SF1), so a side
        whose live count is far below its padded capacity must compact first.
        The live count comes from a persisted per-subtree hint; its first
        observation costs ONE sync, after which dense inputs skip even that
        and sparse ones compact IN-PROGRAM with a deferred overflow flag
        (exact re-run on staleness)."""
        cap = batch.capacity
        if cap <= self._SPECULATIVE_JOIN_BUDGET or not self._speculate \
                or self._use_jit is False:
            return batch
        from igloo_tpu.exec.host import HostExecutor
        fp = HostExecutor._plan_fp(plan_node)
        if fp is None:
            # no stable hint key for this subtree (subqueries/window/union...):
            # carry the padded lanes rather than pay a num_live() device->host
            # sync (~0.1s on a tunneled TPU) on EVERY staged execution
            return batch
        # capacity IS part of this key: an input subtree's capacity comes
        # from its scans (stable run-to-run for the same data), so including
        # it cannot cascade — and it keeps sf1/sf10 executions of the same
        # exprs from sharing live counts (a stale cross-scale hint would
        # force an exact re-run whose unshrunk probes compile pathologically)
        key = ("slive", fp, batch.capacity)
        hint = self._staged_hint(key)
        if hint is None:
            n = batch.num_live()  # one sync, first sight of this subtree only
            self._cache[("nhint", key)] = n
            if self._hints is not None:
                self._hints.put(key, n)
                self._hints.flush()
            stats.observe_card(fp, n)  # sync already paid: adaptive loop
            return self._maybe_shrink(batch, known_live=n)
        want = round_capacity(max(hint, 1))
        # factor 2, not _SHRINK_FACTOR: past the compile budget every halving
        # of padded lanes halves the consumer's whole-program cost — a 2M-live
        # input in 8.4M lanes must not keep its 4x padding (q18's final
        # aggregate sat exactly on the 4x boundary and ran full-width)
        if want * 2 > cap:
            return batch  # dense input: leave as-is, no sync
        jfp = ("acompact_in", batch_proto_key(batch), want)

        def build():
            def fn(b):
                n = jnp.sum(b.live.astype(jnp.int64))
                return K.compact_to(b, want), n, n > want
            return fn
        out, n_dev, ovf = self._jitted("acompact_in", jfp, build)(
            strip_dicts(batch))
        self._deferred_stats.append((key, n_dev))
        self._deferred_overflow.append((("scompact", key), ovf))
        tracing.counter("join.input_compact")
        return attach_dicts(out, *col_meta(batch.columns))

    def _exec_join(self, plan: L.Join) -> DeviceBatch:
        left = self._adaptive_input(self._exec(plan.left), plan.left)
        right = self._adaptive_input(self._exec(plan.right), plan.right)
        pool = ConstPool()
        compL = ExprCompiler([c.dictionary for c in left.columns], pool,
                     bounds=[c.bounds for c in left.columns])
        lres, lk, _ = self._compile_exprs(plan.left_keys, left, compL)
        compR = ExprCompiler([c.dictionary for c in right.columns], pool,
                     bounds=[c.bounds for c in right.columns])
        rres, rk, _ = self._compile_exprs(plan.right_keys, right, compR)
        jt = plan.join_type
        use_lk, use_rk = ([], []) if jt is JoinType.CROSS else (lk, rk)
        lhx = make_key_hash_idxs(use_lk, pool)
        rhx = make_key_hash_idxs(use_rk, pool)
        residual = None
        rres2 = []
        if plan.residual is not None:
            compB = ExprCompiler([c.dictionary for c in left.columns] +
                                 [c.dictionary for c in right.columns], pool)
            r = self._resolve_subqueries(plan.residual)
            rres2 = [r]
            residual = compB.compile(r)
            marks = tuple(compL.marks) + tuple(compR.marks) + tuple(compB.marks)
        else:
            marks = tuple(compL.marks) + tuple(compR.marks)
        fpbase = (expr_fingerprint(lres + rres + rres2),
                  plan.join_type, batch_proto_key(left), batch_proto_key(right),
                  pool.signature(), marks)

        if jt in (JoinType.SEMI, JoinType.ANTI):
            meta_cols = left.columns
        else:
            meta_cols = list(left.columns) + list(right.columns)
        dicts, bnds = col_meta(meta_cols)

        ls, rs = strip_dicts(left), strip_dicts(right)
        consts = pool.device_args()

        # direct "array join" fast path (exec/join.py): dense-integer PK-FK
        # joins become one scatter + one gather; a deferred duplicate flag
        # falls back to the exact sorted-probe path below (via _exact_copy,
        # which runs with _speculate=False and therefore skips this branch).
        # The ("nodirect", jfp) negative cache skips joins whose build side
        # already proved to have duplicate keys.
        jfp_core = (expr_fingerprint(lres + rres + rres2), jt)
        jfp = jfp_core + (left.capacity, right.capacity)
        if self._speculate and use_lk:
            banned = frozenset(
                s for s in ("left", "right")
                if self._cache.get(("nodirect", jfp_core, s)))
            pick = choose_direct_build(use_lk, use_rk, left.capacity,
                                       right.capacity, jt, banned=banned)
            if pick is not None:
                # (blo, tsize) is the canonical positional table — quantized
                # in choose_direct_build so these fingerprint constants are
                # shape-class values, not raw data bounds (jit-key rule)
                side, (blo, tsize), ki = pick
                swapped = side == "left"
                pks = use_rk if swapped else use_lk
                bks = use_lk if swapped else use_rk
                pkey, bkey = pks[ki], bks[ki]
                extra = [(pks[i], bks[i]) for i in range(len(pks)) if i != ki]
                # adaptive capacity: a previous run's observed live count
                # (persisted hint) sizes an IN-PROGRAM compaction — selective
                # joins (q17: 6M-lane probe, ~6k matches) otherwise hand
                # full-width padded batches to every downstream stage, whose
                # static-shape cost scales with CAPACITY, not live rows.
                # Overflow (stale hint) re-runs exactly via _exact_copy.
                # The key MUST be capacity-free: upstream hint adoption
                # changes this join's input capacities, and a cap-dependent
                # key would cascade one adoption level per run (the round-4
                # hfps lesson). A scale change (sf1 -> sf10 data under the
                # same exprs) makes the hint stale instead — the overflow
                # flag repairs it in one exact re-run and re-records.
                hkey = ("sjoin_live", jfp_core)
                hint = self._staged_hint(hkey)
                probe_cap = right.capacity if swapped else left.capacity
                want = None
                if hint is not None:
                    w = round_capacity(max(hint, 1))
                    if w * _SHRINK_FACTOR <= probe_cap:
                        want = w

                def build(want=want):
                    def fn(pb, bb, c):
                        out, dup = direct_join_phase(
                            pb, bb, pkey, bkey, blo, tsize, swapped, jt,
                            residual, plan.schema, c, extra_keys=extra)
                        n = jnp.sum(out.live.astype(jnp.int64))
                        if want is None:
                            return out, dup, n, jnp.asarray(False)
                        return K.compact_to(out, want), dup, n, n > want
                    return fn
                fn = self._jitted(
                    "join_direct",
                    (fpbase, plan.schema, side, blo, tsize, ki, want),
                    build)
                tracing.counter("join.direct")
                stats.annotate(strategy="direct", build_side=side)
                out, dup, n_dev, ovf = fn(
                    rs if swapped else ls, ls if swapped else rs, consts)
                self._deferred_overflow.append(
                    (("dup", (jfp_core, side)), dup))
                self._deferred_stats.append((hkey, n_dev))
                if want is not None:
                    tracing.counter("join.direct_compact")
                    self._deferred_overflow.append(
                        (("scompact", hkey), ovf))
                return attach_dicts(out, dicts[: len(out.columns)],
                                    bnds[: len(out.columns)])

        if jt in (JoinType.SEMI, JoinType.ANTI) and use_lk and \
                self._speculate:
            from igloo_tpu.exec.join import semi_anti_phase
            # windowed sorted membership (no expansion). With a residual the
            # window must cover the build side's duplicate-key runs (TPC-H:
            # <= 7 lineitems per order); a truncated run raises the deferred
            # flag -> exact re-run via _exact_copy (which takes the expand
            # path: correct, possibly slow — the flag is data-dependent and
            # rare by construction)
            win = 2 if residual is None else 12
            # pack the exact-verify lanes (union key ranges across both
            # sides) so each window slot compares one lane, not one per key
            pack_eq = K.plan_pair_packing(use_lk, use_rk, pool)
            if pack_eq is not None:
                tracing.counter("pack.semi")
                consts = pool.device_args()  # re-snapshot with the offsets
            fn = self._jitted(
                "join_semi", fpbase + (win, pack_eq, pool.signature()),
                lambda: (lambda l, r, consts: semi_anti_phase(
                    l, r, use_lk, use_rk, lhx, rhx,
                    jt is JoinType.ANTI, residual, win, consts,
                    pack_eq=pack_eq)))
            tracing.counter("join.semi_sorted")
            stats.annotate(strategy="semi_sorted")
            out, truncated = fn(ls, rs, consts)
            if residual is not None:
                self._deferred_overflow.append(
                    (("semi_window", fpbase), truncated))
            # no shrink sync here: downstream consumers bound their own
            # input capacities adaptively (_adaptive_input)
            return attach_dicts(out, dicts[: len(out.columns)],
                                bnds[: len(out.columns)])

        stats.annotate(strategy="sorted_probe")
        # Pallas hash-probe dispatch (docs/kernels.md): replaces the
        # combined (m+n)-lane sort inside _probe_bounds; the kernel's
        # overflow flag rides the deferred protocol and negative-caches
        # this join onto the sort path when its build side proves to carry
        # long duplicate-hash runs. The plan is a host decision -> part of
        # the probe program's cache key.
        pplan = None
        if use_lk:
            pplan = dispatch.plan_probe(
                right.capacity, left.capacity,
                banned=bool(self._cache.get(("nopallas_probe", jfp_core))))
        def probe_fn(pp):
            return self._jitted(
                "join_probe", (fpbase, pp),
                lambda: (lambda l, r, consts: probe_phase(
                    l, r, use_lk, use_rk, lhx, rhx, consts, probe_plan=pp)))
        def expand_fn(mp):
            # the match plan rides the expand program's cache key (same rule
            # as the probe plan above: host decisions key the trace)
            return self._jitted(
                "join_expand", (fpbase, plan.schema, mp),
                lambda: (lambda l, r, p, match_cap, consts: expand_phase(
                    l, r, p, match_cap, jt, residual, plan.schema, consts,
                    match_plan=mp)),
                static_argnums=(3,))

        try:
            p = probe_fn(pplan)(ls, rs, consts)
        except Exception:
            if pplan is None:
                raise
            # compile-failure rung: a Pallas program the backend cannot
            # lower must fall back to the proven sort path, not fail the
            # query (an unrelated error re-raises from the sort-path run)
            self._cache[("nopallas_probe", jfp_core)] = True
            tracing.counter("pallas.compile_fallback")
            pplan = None
            p = probe_fn(None)(ls, rs, consts)
        if pplan is not None:
            stats.annotate(pallas="probe")
            self._deferred_overflow.append((("pallas_probe", jfp_core),
                                            p.ovf))
        spec_cap = round_capacity(max(left.capacity, right.capacity))
        if (self._speculate and jt is not JoinType.CROSS
                and spec_cap <= self._SPECULATIVE_JOIN_BUDGET):
            total = None
            match_cap = spec_cap
            self._deferred_overflow.append((("overflow", jfp),
                                            p.total > match_cap))
        else:
            total = int(p.total)  # the one host sync
            match_cap = choose_match_capacity(total)
        # Pallas match-materialization dispatch (docs/kernels.md): replaces
        # the owner-scatter + associative-scan chain inside expand_phase;
        # window overflow rides the deferred protocol like the probe kernel
        mplan = dispatch.plan_match(
            left.capacity, match_cap,
            banned=bool(self._cache.get(("nopallas_match", jfp_core))))
        try:
            res = expand_fn(mplan)(ls, rs, p, match_cap, consts)
        except Exception:
            if mplan is None or mplan[1] != "kernel":
                raise
            self._cache[("nopallas_match", jfp_core)] = True
            tracing.counter("pallas.compile_fallback")
            mplan = dispatch.plan_match(left.capacity, match_cap, banned=True)
            res = expand_fn(mplan)(ls, rs, p, match_cap, consts)
        if mplan is not None:
            out, movf = res
            if mplan[1] == "kernel":
                stats.annotate(
                    pallas="probe+match" if pplan is not None else "match")
                self._deferred_overflow.append((("pallas_match", jfp_core),
                                                movf))
        else:
            out = res
        out = attach_dicts(out, dicts[: len(out.columns)],
                           bnds[: len(out.columns)])
        if total is None:
            # speculative path: carrying padded lanes beats a count sync
            return out
        if jt in (JoinType.INNER, JoinType.CROSS):
            # live rows <= total (residual can only reduce), so the already-
            # synced candidate count bounds the shrink without a second sync
            return self._maybe_shrink(out, known_live=total)
        return self._maybe_shrink(out)

    def _exec_window(self, plan: L.Window) -> DeviceBatch:
        from igloo_tpu.exec.window import compile_window, window_batch
        batch = self._adaptive_input(self._exec(plan.input), plan.input)
        comp = ExprCompiler([c.dictionary for c in batch.columns],
                            bounds=[c.bounds for c in batch.columns])
        wfp, pk, okeys, specs, wdicts, wbounds = compile_window(
            plan, comp, self._resolve_subqueries)
        fp = ("window", wfp, batch_proto_key(batch), plan.schema,
              comp.pool.signature(), tuple(comp.marks))
        asc, nf = list(plan.ascending), list(plan.nulls_first)

        def build():
            def fn(b, consts):
                return window_batch(b, pk, okeys, asc, nf, specs,
                                    plan.schema, consts)
            return fn
        out = self._jitted("window", fp, build)(strip_dicts(batch),
                                                comp.pool.device_args())
        dicts, bnds = col_meta(batch.columns)
        return attach_dicts(out, dicts + wdicts, bnds + wbounds)

    def _exec_sort(self, plan: L.Sort) -> DeviceBatch:
        from igloo_tpu.exec.expr_compile import rank_lane
        batch = self._adaptive_input(self._exec(plan.input), plan.input)
        res, keys, comp = self._compile_exprs(plan.keys, batch)
        # ORDER BY over unsorted (high-cardinality) dictionaries sorts ranks
        keys = [rank_lane(k, comp) if k.dtype.is_string else k for k in keys]
        # pack the longest integer-family key prefix into one sort lane
        pack = K.plan_prefix_packing(keys, plan.ascending, plan.nulls_first,
                                     comp.pool)
        if pack is not None:
            tracing.counter("pack.sort")
        hint = self._limit_hint
        if hint is not None and hint[0] == id(plan):
            # ORDER BY + LIMIT fusion: the parent Limit deposited its bounds
            # before descending; adopt a partial top-k when the plan fits
            # (full pack required — one lane totally orders the rows)
            self._limit_hint = None
            _, limit, offset = hint
            k_total = limit + offset
            fp_core = (expr_fingerprint(res), tuple(plan.ascending),
                       tuple(plan.nulls_first), batch_proto_key(batch),
                       comp.pool.signature(), tuple(comp.marks), pack)
            # ban key uses the FUSED compiler's topk core format so a fused
            # compile failure's ban is visible here and vice versa
            tfp_core = ("|".join(repr(e) for e in res),
                        tuple(plan.ascending), tuple(plan.nulls_first))
            tplan = dispatch.plan_topk(
                batch.capacity, k_total,
                pack is not None and pack[1] == len(keys),
                banned=bool(self._cache.get(("nopallas_topk", tfp_core))))
            if tplan is not None:
                out_cap = round_capacity(k_total)

                def tbuild(tp):
                    def mk():
                        def fn(b, consts):
                            return topk_batch(b, keys, consts, pack, tp,
                                              limit, offset, out_cap)
                        return fn
                    return self._jitted("topk", ("topk", fp_core, tp,
                                                 limit, offset, out_cap), mk)
                try:
                    out = tbuild(tplan)(strip_dicts(batch),
                                        comp.pool.device_args())
                except Exception:
                    if tplan[1] != "pallas":
                        raise
                    self._cache[("nopallas_topk", tfp_core)] = True
                    tracing.counter("pallas.compile_fallback")
                    tplan = dispatch.plan_topk(
                        batch.capacity, k_total,
                        pack is not None and pack[1] == len(keys),
                        banned=True)
                    out = tbuild(tplan)(strip_dicts(batch),
                                        comp.pool.device_args())
                self._limit_taken = True
                return attach_dicts(out, *col_meta(batch.columns))
        fp = ("sort", expr_fingerprint(res), tuple(plan.ascending),
              tuple(plan.nulls_first), batch_proto_key(batch),
              comp.pool.signature(), tuple(comp.marks), pack)

        def build():
            def fn(b, consts):
                return sort_batch(b, keys, plan.ascending, plan.nulls_first,
                                  consts, pack=pack)
            return fn
        out = self._jitted("sort", fp, build)(strip_dicts(batch),
                                              comp.pool.device_args())
        return attach_dicts(out, *col_meta(batch.columns))

    def _exec_limit(self, plan: L.Limit) -> DeviceBatch:
        if isinstance(plan.input, L.Sort) and plan.limit is not None:
            # deposit the LIMIT bounds for the Sort child (identity-matched
            # there, so an intervening rewrite can never mis-adopt); when the
            # child took the top-k its output already IS the limited batch
            prev = self._limit_hint
            self._limit_hint = (id(plan.input), plan.limit, plan.offset)
            self._limit_taken = False
            try:
                batch = self._exec(plan.input)
            finally:
                self._limit_hint = prev
            if self._limit_taken:
                self._limit_taken = False
                return self._maybe_shrink(batch, known_live=plan.limit)
        else:
            batch = self._exec(plan.input)
        fp = ("limit", plan.limit, plan.offset, batch_proto_key(batch))

        def build():
            def fn(b):
                return limit_batch(b, plan.limit, plan.offset)
            return fn
        out = self._jitted("limit", fp, build)(strip_dicts(batch))
        out = attach_dicts(out, *col_meta(batch.columns))
        # LIMIT bounds the live count statically — no sync needed
        known = plan.limit if plan.limit is not None else None
        return self._maybe_shrink(out, known_live=known)

    def _exec_union(self, plan: L.Union) -> DeviceBatch:
        batches = [self._exec(ch) for ch in plan.inputs]
        return union_batches(batches, plan.schema)

    def _exec_setopjoin(self, plan: L.SetOpJoin) -> DeviceBatch:
        left = self._maybe_shrink(self._exec_distinct_of(plan.left))
        right = self._maybe_shrink(self._exec_distinct_of(plan.right))
        # align dictionaries via union-batch machinery semantics: keys compare
        # via cross-table hash lanes inside the join kernel, so no remap needed
        lk = [self._col_ref(left, i) for i in range(len(left.schema))]
        rk = [self._col_ref(right, i) for i in range(len(right.schema))]
        jt = JoinType.ANTI if plan.anti else JoinType.SEMI
        return join_batches(left, right, lk, rk, jt, None, plan.schema)

    def _exec_distinct_of(self, plan: L.LogicalPlan) -> DeviceBatch:
        batch = self._exec(plan)
        fp = ("distinct", batch_proto_key(batch))

        def build():
            return distinct_batch
        out = self._jitted("distinct", fp, build)(strip_dicts(batch))
        return attach_dicts(out, *col_meta(batch.columns))

    def _col_ref(self, batch: DeviceBatch, i: int) -> Compiled:
        f = batch.schema.fields[i]
        return Compiled(lambda env, _i=i: (env.values[_i], env.nulls[_i]),
                        f.dtype, batch.columns[i].dictionary)

    # --- scalar subqueries ---

    def _resolve_subqueries(self, e: E.Expr) -> E.Expr:
        def sub(n):
            if isinstance(n, E.ScalarSubquery):
                # memoized on the node: plans are rebuilt per engine.execute,
                # so this caches only within one execution — in particular a
                # fused attempt falling back to the staged path (or a repair
                # re-run) does not re-execute the subquery
                memo = getattr(n, "_resolved_lit", None)
                if memo is not None:
                    return memo
                if not isinstance(n.query, L.LogicalPlan):
                    raise PlanError("unbound scalar subquery reached executor")
                val, dtype = self._eval_scalar(n.query)
                lit = E.Literal(value=val, literal_type=dtype)
                lit.dtype = n.dtype or dtype
                n._resolved_lit = lit
                return lit
            return n
        return E.transform(e, sub)

    def _eval_scalar(self, plan: L.LogicalPlan):
        # scope the deferred speculative-overflow flags to the subquery: its
        # final fetch must not consume (and mask) the outer query's flags
        saved, self._deferred_overflow = self._deferred_overflow, []
        saved_stats, self._deferred_stats = self._deferred_stats, []
        try:
            t = self.execute_to_arrow(plan)
        finally:
            self._deferred_overflow = saved + self._deferred_overflow
            self._deferred_stats = saved_stats + self._deferred_stats
        if t.num_rows > 1:
            raise ExecError("scalar subquery returned more than one row")
        dtype = plan.schema.fields[0].dtype
        if t.num_rows == 0:
            return None, dtype
        v = t.column(0)[0].as_py()
        if dtype.id == T.TypeId.DATE32 and v is not None:
            import datetime as _dt
            v = v.toordinal() - _dt.date(1970, 1, 1).toordinal()
        elif dtype.id == T.TypeId.TIMESTAMP and v is not None:
            import datetime as _dt
            v = (v - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)
        return v, dtype

    # --- capacity management (shape bucketing between stages) ---

    # Below this capacity a batch is cheap enough to carry oversized: skipping
    # the shrink avoids a num_live() device->host sync (~100ms on a tunneled
    # TPU), which dominated warm query time (round-2 weak #1).
    _SYNC_FREE_CAPACITY = 1 << 16

    def _maybe_shrink(self, batch: DeviceBatch,
                      known_live: Optional[int] = None) -> DeviceBatch:
        if known_live is None and batch.capacity <= self._SYNC_FREE_CAPACITY:
            return batch
        n = batch.num_live() if known_live is None else known_live  # host sync
        want = round_capacity(max(n, 1))
        if batch.capacity > _SHRINK_FACTOR * want:
            fp = ("compact", batch_proto_key(batch), want)

            def build():
                def fn(b):
                    return K.compact_to(b, want)
                return fn
            out = self._jitted("compact", fp, build)(strip_dicts(batch))
            return attach_dicts(out, *col_meta(batch.columns))
        return batch


def union_batches(batches: list[DeviceBatch], out_schema: T.Schema) -> DeviceBatch:
    """UNION ALL: concatenate column-wise; string columns remap through the union
    dictionary host-side first."""
    caps = [b.capacity for b in batches]
    cols = []
    for i, f in enumerate(out_schema):
        want = f.dtype.device_dtype()
        if f.dtype.is_string:
            uni = None
            for b in batches:
                uni, _, _ = _unify_dicts(uni, b.columns[i].dictionary)
            luts = []
            for b in batches:
                _, _, lut = _unify_dicts(uni, b.columns[i].dictionary)
                luts.append(lut)
            # ids must be WIDE (int32 lane) before the LUT remap: a carrier id
            # lane would index the union LUT with offset-shrunk codes
            vals = jnp.concatenate([
                _remap(wide_values(b.columns[i]), luts[j])
                for j, b in enumerate(batches)])
            dct = uni
        else:
            # per-input carriers generally differ across UNION branches (one
            # spec per upload), so this boundary widens eagerly
            vals = jnp.concatenate([
                wide_values(b.columns[i]).astype(want) for b in batches])
            dct = None
        if any(b.columns[i].nulls is not None for b in batches):
            nulls = jnp.concatenate([
                b.columns[i].nulls if b.columns[i].nulls is not None
                else jnp.zeros((caps[j],), dtype=bool)
                for j, b in enumerate(batches)])
        else:
            nulls = None
        cols.append(DeviceColumn(f.dtype, vals, nulls, dct))
    live = jnp.concatenate([b.live for b in batches])
    return DeviceBatch(out_schema, cols, live)


def _remap(ids, lut: np.ndarray):
    if len(lut) == 0:
        return jnp.zeros_like(ids)
    return jnp.take(jnp.asarray(lut), jnp.clip(ids, 0, len(lut) - 1))


def _pa_type_for(d: T.DataType) -> pa.DataType:
    from igloo_tpu.exec.batch import dtype_to_arrow
    return dtype_to_arrow(d)
