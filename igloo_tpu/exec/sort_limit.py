"""Sort / Limit / Offset kernels.

The reference delegates ORDER BY/LIMIT to DataFusion entirely (no custom operator).
TPU design: multi-key sort = k iterated stable argsorts over order-normalized int64
lanes (kernels.lex_argsort) — no comparators, fully static shapes. When a prefix of
the keys is integer-family with host-known bounds, it packs into ONE lane
(kernels.plan_prefix_packing; see docs/sort_keys.md), collapsing the chain — a
fully packed ORDER BY is a single argsort that also handles dead-row placement.
LIMIT is a mask over the running live-row count, not a truncation, so shapes
stay put.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from igloo_tpu.exec import dispatch
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import DeviceBatch
from igloo_tpu.exec.expr_compile import Compiled, Env


def sort_batch(batch: DeviceBatch, keys: list[Compiled], ascending: list[bool],
               nulls_first: list[bool], consts: tuple = (),
               pack: Optional[tuple] = None) -> DeviceBatch:
    """Jit-traceable stable sort; dead rows end up last. `pack` (a host
    decision from kernels.plan_prefix_packing, part of the caller's cache key)
    is (spec, n) with the first n keys fused into one packed lane."""
    env = Env.from_batch(batch, consts)
    vals, nls = [], []
    for k in keys:
        v, nl = k.fn(env)
        vals.append(v)
        nls.append(nl)
    lanes = []
    start = 0
    if pack is not None:
        spec, start = pack
        packed = K.pack_key_lane(spec, vals[:start], nls[:start], consts)
        if start == len(keys):
            # every key packed: one argsort orders rows AND sinks dead rows
            perm = jnp.argsort(K.packed_sort_key(packed, batch.live),
                               stable=True)
            return K.apply_perm(batch, perm)
        lanes.append((packed, True))
    for k, v, nl, asc, nf in zip(keys[start:], vals[start:], nls[start:],
                                 ascending[start:], nulls_first[start:]):
        lanes.extend(K.sort_lanes_for(v, nl, k.dtype.is_float, asc, nf))
    perm = K.lex_argsort(lanes, batch.live)
    return K.apply_perm(batch, perm)


def topk_batch(batch: DeviceBatch, keys: list[Compiled],
               consts: tuple, pack: tuple, plan: tuple,
               limit: int, offset: int, out_cap: int) -> DeviceBatch:
    """Jit-traceable fused ORDER BY + LIMIT: a partial top-k over the fully
    packed sort lane replaces the full argsort when LIMIT ≪ rows. `plan`
    (dispatch.plan_topk, part of the caller's cache key) requires `pack` to
    cover EVERY key, so one packed lane totally orders the rows — the
    selected positions are the stable sort's first LIMIT+OFFSET, and the
    output batch shrinks to `out_cap` (the LIMIT's capacity family member)
    instead of carrying the input capacity with a mask. Rows are
    bit-identical to ``sort_batch`` + ``limit_batch``."""
    env = Env.from_batch(batch, consts)
    vals, nls = [], []
    for k in keys:
        v, nl = k.fn(env)
        vals.append(v)
        nls.append(nl)
    spec, _ = pack
    packed = K.pack_key_lane(spec, vals, nls, consts)
    perm = dispatch.topk_perm(plan, K.packed_sort_key(packed, batch.live))
    k_total = limit + offset
    if out_cap > k_total:
        perm = jnp.concatenate(
            [perm, jnp.zeros((out_cap - k_total,), perm.dtype)])
    cols = K.gather_batch(batch, perm)
    io = jnp.arange(out_cap)
    live = jnp.take(batch.live, perm) & (io >= offset) & (io < k_total)
    return DeviceBatch(batch.schema, cols, live)


def limit_batch(batch: DeviceBatch, limit, offset: int = 0) -> DeviceBatch:
    """Jit-traceable: keep live rows (offset, offset+limit] in current row order."""
    cum = jnp.cumsum(batch.live.astype(jnp.int64))
    keep = batch.live & (cum > offset)
    if limit is not None:
        keep = keep & (cum <= offset + limit)
    return DeviceBatch(batch.schema, batch.columns, keep)
